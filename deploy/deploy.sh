#!/bin/sh
# Minimal bootstrap (the reference's deploy/ubuntu.sh role): install the
# package + services on a Debian-ish host. Run from the repo root.
set -e

PYTHON=${PYTHON:-python3}

$PYTHON -m pip install .
$PYTHON -c "from veles_tpu.export.native import build_native; build_native()"

if [ -d /etc/systemd/system ] && [ "$(id -u)" = 0 ]; then
    id veles >/dev/null 2>&1 || useradd -r -s /usr/sbin/nologin veles
    install -d -o veles -g veles /var/lib/veles-tpu/forge
    install -m 644 deploy/systemd/veles-tpu-forge.service \
        deploy/systemd/veles-tpu-web-status.service /etc/systemd/system/
    systemctl daemon-reload
    echo "enable with: systemctl enable --now veles-tpu-forge veles-tpu-web-status"
fi
echo "done."
