#!/usr/bin/env python3
"""Benchmark: AlexNet training throughput, samples/sec/chip + MFU.

The driver-defined north star (BASELINE.json: "Znicz ImageNet-AlexNet
samples/sec/chip"). Trains the full AlexNet stack (227x227x3, 1000
classes, conv+LRN+pool+fc+dropout+softmax) on synthetic ImageNet-shaped
data with the fused step compiler on one TPU chip and reports
steady-state training throughput (compile excluded) over a >=30 s
timed window, plus roofline accounting: analytic model TFLOP/s against
the chip's measured large-matmul rate (MFU).

vs_baseline: the reference ships no samples/sec table
(BASELINE.json.published == {}); 500 img/s is the documented
2015-era single-GPU AlexNet training throughput (cuDNN-class hardware
the reference's CUDA backend targeted), used as the denominator.

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.
"""

import json
import logging
import os
import sys
import time

logging.disable(logging.WARNING)

BASELINE_SAMPLES_PER_SEC = 500.0
MIN_TIMED_WINDOW_S = 30.0
#: compute policy for the headline number (a first-class framework
#: capability: --precision on the CLI; f32 params + f32 accumulation,
#: bf16 activations between layers — see veles_tpu/nn/precision.py)
PRECISION = os.environ.get("VELES_BENCH_PRECISION", "bfloat16")
#: VELES_BENCH_TELEMETRY=1: span tracing ON through the timed window
#: (one span per compiled segment) — the <2% overhead guard committed
#: in docs/PERF.md §Telemetry runs this bench with and without
TELEMETRY = os.environ.get("VELES_BENCH_TELEMETRY", "0") != "0"


def model_train_flops_per_sample(wf):
    """Analytic FLOPs to train ONE sample: 3x the forward matmul/conv
    FLOPs (forward + grad-input + grad-weights passes), the standard
    accounting (e.g. the scaling-book convention). Elementwise ops
    (LRN, pooling, dropout, activations) are excluded — they are
    bandwidth, not FLOPs. Shared with scripts/bench_all.py (ONE
    source of truth for the published MFU tables)."""
    total = 0.0
    for fwd in wf.forwards:
        name = type(fwd).__name__
        out_shape = tuple(fwd.output.shape)
        if name.startswith("Conv"):
            ky, kx, cin, cout = fwd.weights.shape
            out_hw = out_shape[1] * out_shape[2]
            total += 2.0 * out_hw * ky * kx * cin * cout * 3.0
        elif name.startswith("MultiHeadAttention"):
            _, s, d = tuple(fwd.input.shape)
            # 4 projections (q,k,v,out) + scores + scores@v
            total += (4 * 2.0 * s * d * d + 2 * 2.0 * s * s * d) * 3.0
        elif name.startswith("MoE"):
            _, s, d = tuple(fwd.input.shape)
            # top-1 switch: each token visits ONE expert's up+down,
            # plus the router
            total += s * (2.0 * d * fwd.hidden * 2 +
                          2.0 * d * fwd.n_experts) * 3.0
        elif name.startswith("All2All"):
            fin, fout = fwd.weights.shape
            total += 2.0 * fin * fout * 3.0
        # pooling/LRN/dropout: no matmul FLOPs
    return total


def prepare_segment_run(trainer, warm=2, seed=0):
    """(params, states, idx, keys) after ``warm`` compiled segments —
    THE warm-up/settle discipline, called by bench.py main,
    scripts/bench_all.py and scripts/profile_step.py: the first warm
    segment pays the XLA compile, the second absorbs the one-time
    donated-buffer re-layout so what follows is pure steady state."""
    import jax
    import jax.numpy as jnp

    idx = jnp.asarray(trainer._segment_indices(2))
    keys = jax.random.split(jax.random.PRNGKey(seed), idx.shape[0])
    params, states = trainer.pull_params()
    t0 = time.time()
    for i in range(warm):
        params, states, losses, _ = trainer._train_segment(
            params, states, idx, keys)
        float(losses[-1])
        print("warmup segment %d done: %.1fs" % (i, time.time() - t0),
              file=sys.stderr, flush=True)
    return params, states, idx, keys


def timed_segment_window(trainer, params, states, idx, keys,
                         min_window_s):
    """The phase-2 window discipline, shared with
    scripts/bench_all.py: chunks of compiled segments with ONE forcing
    read per chunk (float() pulls a scalar through the relay;
    block_until_ready alone can return early). ~20 segments in flight
    both amortize the round-trips and stay under the relay's
    async-queue limit (deeper queues are rejected with
    INVALID_ARGUMENT). Returns (params, states, segments, elapsed_s,
    final_loss)."""
    from veles_tpu.telemetry import tracing
    from veles_tpu.telemetry.registry import get_registry

    # chunk-amortized step times land in the registry: the "telemetry"
    # column scripts/bench_all.py publishes (step p50/p95)
    step_hist = get_registry().histogram(
        "veles_bench_step_ms",
        "Per-segment step time, amortized over one forcing-read chunk")
    chunk = min(20, max(1, 2560 // idx.shape[0]))
    segs = 0
    start = time.time()
    while True:
        t_chunk = time.time()
        for _ in range(chunk):
            with tracing.span("bench:segment"):
                params, states, losses, _ = trainer._train_segment(
                    params, states, idx, keys)
        final_loss = float(losses[-1])
        step_hist.observe((time.time() - t_chunk) / chunk * 1e3)
        segs += chunk
        elapsed = time.time() - start
        if elapsed >= min_window_s:
            return params, states, segs, elapsed, final_loss


def measured_matmul_peak_tflops():
    """Sustained large-matmul rate of THIS chip (the roofline's compute
    ceiling): a 50-long chain of 8192^2 f32 matmuls inside one jit (on
    TPU, f32 dot runs the MXU's native bf16-pass path by default, so
    this is the relevant ceiling for either precision policy)."""
    import jax
    import jax.numpy as jnp

    n, iters = 8192, 50
    a = jnp.ones((n, n), jnp.float32)

    def body(x, _):
        return (x @ a) * (1.0 / n), None

    f = jax.jit(lambda a0: jax.lax.scan(body, a0, None,
                                        length=iters)[0].sum())
    float(f(a))  # compile + warm
    t = time.time()
    float(f(a))
    dt = time.time() - t
    return 2.0 * n ** 3 * iters / dt / 1e12


def main():
    import jax
    import jax.numpy as jnp

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.alexnet import (ALEXNET_LAYERS,
                                          AlexNetWorkflow,
                                          SyntheticImageLoader)
    from veles_tpu.nn.precision import set_policy
    from veles_tpu.train import FusedTrainer

    set_policy(PRECISION)
    if TELEMETRY:
        from veles_tpu.telemetry import tracing
        tracing.enable()
        print("telemetry: span tracing ENABLED through the timed window",
              file=sys.stderr)
    batch = int(os.environ.get("VELES_BENCH_BATCH", 128))
    # 16k samples (bf16-stored, ~5 GB HBM) instead of r2's 1k: the
    # live-loss phase descends visibly from the fresh-model ~6.9
    # (VERDICT r2 weak #2), and the 128-step compiled segments this
    # size produces lifted throughput ~8% by amortizing per-dispatch
    # overhead (docs/PERF.md r3).
    n_train = int(os.environ.get("VELES_BENCH_NTRAIN", 16384))
    prng.get().seed(42)
    prng.get("loader").seed(43)
    wf = AlexNetWorkflow(
        DummyLauncher(),
        loader_factory=lambda w: SyntheticImageLoader(
            w, n_train=n_train, n_valid=batch, side=227, n_classes=1000,
            minibatch_size=batch, dtype="bfloat16"),
        layers=ALEXNET_LAYERS, max_epochs=1)
    t0 = time.time()
    wf.initialize(device=Device(backend=None))
    print("loader init (generation): %.0fs" % (time.time() - t0),
          file=sys.stderr, flush=True)

    import numpy

    t0 = time.time()
    trainer = FusedTrainer(
        wf, stage_s2d=os.environ.get("VELES_BENCH_STAGE_S2D", "1") != "0")
    print("trainer build (incl. s2d staging upload): %.0fs, staged=%s"
          % (time.time() - t0, trainer._staged_s2d),
          file=sys.stderr, flush=True)
    # host-side snapshot of the fresh model: the warmup DONATES the
    # pulled device buffers, so the timed window re-uploads from here
    # to start from an untrained model (live descending loss)
    host_init = jax.tree_util.tree_map(numpy.asarray,
                                       trainer.pull_params())

    # warm-up: TWO segments — the first pays the XLA compile (cheap on
    # re-runs via the persistent cache in ~/.veles_tpu/cache/xla), the
    # second absorbs the one-time donated-buffer re-layout so the timed
    # region is pure steady state (prepare_segment_run: the discipline
    # shared with scripts/bench_all.py and scripts/profile_step.py)
    t_compile = time.time()
    params, states, idx, keys = prepare_segment_run(trainer, warm=2,
                                                    seed=0)
    print("warmup (compile + settle): %.1fs" % (time.time() - t_compile),
          file=sys.stderr, flush=True)

    # -- phase 1 (untimed): LIVE-LOSS evidence. Restart from the fresh
    # model and read the loss after every epoch — the descent from
    # ~ln(1000) is the signal a silent gradient regression would erase
    # (VERDICT r2 weak #2). Reads are eager and this phase is NOT
    # timed: a mid-window read (or even retaining the loss arrays)
    # serializes the relay's execution pipeline and halves throughput.
    params, states = jax.tree_util.tree_map(jnp.asarray, host_init)
    series = []
    for _ in range(10):
        params, states, losses, _ = trainer._train_segment(
            params, states, idx, keys)
        series.append(float(losses[-1]))
    print("loss per epoch (fresh model): %s  (policy=%s, %d samples)"
          % (" ".join("%.3f" % v for v in series), PRECISION, n_train),
          file=sys.stderr)
    if not (series[0] > series[-1] >= 0.0 and series[0] > 1.0):
        print("WARNING: loss not live/decreasing — gradient regression?",
              file=sys.stderr)

    # -- phase 2 (timed): steady-state throughput, continuing the same
    # training run (discipline in timed_segment_window, shared with
    # scripts/bench_all.py)
    params, states, epochs, elapsed, final_loss = timed_segment_window(
        trainer, params, states, idx, keys, MIN_TIMED_WINDOW_S)
    print("timed window: %d epochs x %d samples in %.1fs, loss %.3f -> "
          "%.4f" % (epochs, n_train, elapsed, series[-1], final_loss),
          file=sys.stderr)
    from veles_tpu.telemetry.registry import get_registry
    step = get_registry().get("veles_bench_step_ms").labels()
    print("telemetry: step p50 %.1f / p95 %.1f ms over %d chunks "
          "(tracing %s)" % (step.percentile(50), step.percentile(95),
                            step.count, "on" if TELEMETRY else "off"),
          file=sys.stderr)

    samples_per_sec = epochs * n_train / elapsed

    # roofline accounting
    flops = model_train_flops_per_sample(wf)
    eff_tflops = samples_per_sec * flops / 1e12
    peak_tflops = measured_matmul_peak_tflops()
    mfu = eff_tflops / peak_tflops
    print("model: %.2f GFLOP/sample (trained)  effective: %.1f TFLOP/s  "
          "chip matmul peak: %.1f TFLOP/s  MFU: %.1f%%"
          % (flops / 1e9, eff_tflops, peak_tflops, mfu * 100),
          file=sys.stderr)

    # the MFU detail above goes to stderr (captured in the driver's
    # tail); stdout carries exactly the driver's 4-key contract
    print(json.dumps({
        "metric": "alexnet_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 1),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC,
                             3),
    }))


if __name__ == "__main__":
    sys.exit(main())
