#!/usr/bin/env python3
"""Benchmark: AlexNet training throughput, samples/sec/chip.

The driver-defined north star (BASELINE.json: "Znicz ImageNet-AlexNet
samples/sec/chip"). Trains the full AlexNet stack (227x227x3, 1000
classes, conv+LRN+pool+fc+dropout+softmax) on synthetic ImageNet-shaped
data with the fused step compiler on one TPU chip and reports
steady-state training throughput (compile excluded).

vs_baseline: the reference ships no samples/sec table
(BASELINE.json.published == {}); 500 img/s is the documented
2015-era single-GPU AlexNet training throughput (cuDNN-class hardware
the reference's CUDA backend targeted), used as the denominator.

Prints exactly ONE JSON line on stdout.
"""

import json
import logging
import sys
import time

logging.disable(logging.WARNING)

BASELINE_SAMPLES_PER_SEC = 500.0


def main():
    import jax
    import jax.numpy as jnp

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.alexnet import (ALEXNET_LAYERS,
                                          AlexNetWorkflow,
                                          SyntheticImageLoader)
    from veles_tpu.train import FusedTrainer

    batch = 128
    n_train = 1024
    prng.get().seed(42)
    prng.get("loader").seed(43)
    wf = AlexNetWorkflow(
        DummyLauncher(),
        loader_factory=lambda w: SyntheticImageLoader(
            w, n_train=n_train, n_valid=batch, side=227, n_classes=1000,
            minibatch_size=batch),
        layers=ALEXNET_LAYERS, max_epochs=1)
    wf.initialize(device=Device(backend=None))

    trainer = FusedTrainer(wf)
    params, states = trainer.pull_params()
    idx = trainer._segment_indices(2)  # TRAIN segment index matrix
    keys = jax.random.split(jax.random.PRNGKey(0), idx.shape[0])
    idx = jnp.asarray(idx)

    # warm-up: TWO segments — the first pays the XLA compile (cheap on
    # re-runs via the persistent cache in ~/.veles_tpu/cache/xla), the
    # second absorbs the one-time donated-buffer re-layout so the timed
    # region is pure steady state
    t_compile = time.time()
    for _ in range(2):
        params, states, losses, _ = trainer._train_segment(
            params, states, idx, keys)
        float(losses[-1])
    print("warmup (compile + settle): %.1fs" % (time.time() - t_compile),
          file=sys.stderr)

    # steady state: time full training epochs; the float() read forces
    # the whole on-device chain (block_until_ready alone can return
    # early through the remote-execution relay)
    epochs = 5
    start = time.time()
    for _ in range(epochs):
        params, states, losses, _ = trainer._train_segment(
            params, states, idx, keys)
    final_loss = float(losses[-1])
    elapsed = time.time() - start
    print("final loss: %.4f" % final_loss, file=sys.stderr)

    samples_per_sec = epochs * n_train / elapsed
    print(json.dumps({
        "metric": "alexnet_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 1),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC,
                             3),
    }))


if __name__ == "__main__":
    sys.exit(main())
