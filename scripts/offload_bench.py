#!/usr/bin/env python3
"""Out-of-core model-state overlap bench (ISSUE 17): host-offloaded
param/optimizer groups, synchronous vs double-buffered transfers.

Every leg trains the SAME seeded workflow on a CPU-deterministic
model. The offload legs force the params out-of-core (``VELES_OFFLOAD=1``
+ a tiny ``VELES_OFFLOAD_GROUP_MB`` so several layer groups stream per
step) with a fixed per-transfer sleep injected (``--transfer-ms`` ->
``VELES_OFFLOAD_THROTTLE_MS``) — the "interconnect is the bottleneck"
scenario. Legs differ ONLY in ring shape:

* ``incore`` — ``VELES_OFFLOAD=0``: the resident baseline (bounds the
  offloaded step overhead);
* ``sync``   — depth 0: every H2D upload and D2H writeback inline on
  the step thread;
* ``double`` — depth 2, 2 workers: uploads prefetch ahead of compute
  and a writeback thread retires updated groups concurrently.

Per leg: step-thread transfer wait (``veles_offload_wait_ms`` sum /
p50), compute-overlap fraction, wall time and the final loss — which
must be IDENTICAL across legs (offload must not change the math; the
bench asserts it). Prints one JSON line per leg and a ``summary`` line
with the sync/double wait ratio — the perf gate's
``offload_overlap_ratio`` metric.

Usage::

    JAX_PLATFORMS=cpu python scripts/offload_bench.py [--transfer-ms 12]
        [--epochs 2] [--min-ratio 1.5]
"""

import argparse
import json
import logging
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

logging.disable(logging.WARNING)


def build_workflow(epochs):
    import numpy

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist import MnistWorkflow

    prng.get().seed(42)
    prng.get("loader").seed(43)
    rng = numpy.random.RandomState(7)

    def provider():
        x = rng.rand(2100, 12, 12).astype(numpy.float32)
        y = (x.reshape(len(x), -1).sum(1) > 72).astype(numpy.int32)
        return x[:2000], y[:2000], x[2000:], y[2000:]

    wf = MnistWorkflow(DummyLauncher(), provider=provider,
                       layers=(64, 48), minibatch_size=100,
                       learning_rate=0.05, max_epochs=epochs)
    wf.initialize(device=Device(backend=None))
    return wf


def run_leg(name, epochs, offload, depth, workers):
    from veles_tpu.telemetry.registry import get_registry
    from veles_tpu.train import FusedTrainer
    from veles_tpu.train import offload as offload_mod

    registry = get_registry()
    for metric in ("veles_offload_h2d_ms", "veles_offload_d2h_ms",
                   "veles_offload_wait_ms",
                   "veles_offload_compute_overlap_fraction"):
        family = registry.get(metric)
        if family is not None:
            family.reset()
    wf = build_workflow(epochs)
    trainer = FusedTrainer(wf, offload=offload, offload_depth=depth,
                           offload_workers=workers)
    assert trainer.offloaded == offload, "leg residency mismatch"
    start = time.time()
    history = trainer.train()
    wall = time.time() - start
    # offload_wait_s is the canonical step-thread transfer wait: the
    # pipeline waits PLUS the sync leg's inline writebacks (which the
    # wait histogram, by design, does not count)
    wait_s = trainer.offload_wait_s
    row = {
        "leg": name, "depth": depth, "workers": workers,
        "epochs": len(history),
        "wall_s": round(wall, 2),
        "final_loss": round(
            history[-1]["validation"]["normalized"], 6),
    }
    if offload:
        wait = registry.get("veles_offload_wait_ms").labels()
        gauge = registry.get("veles_offload_compute_overlap_fraction")
        overlap = {labels["phase"]: child.value
                   for labels, child in gauge.series()}.get("train")
        row.update({
            "groups": trainer._offload_engine.plan.n_groups,
            "transfers": wait.count,
            "offload_wait_ms": round(wait_s * 1e3, 1),
            "offload_wait_p50_ms": round(wait.percentile(50), 2),
            "train_overlap": round(overlap or 0.0, 3),
        })
    offload_mod.shutdown_all()
    print(json.dumps(row), flush=True)
    return row


def main():
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n")[0])
    parser.add_argument("--transfer-ms", type=float, default=12.0,
                        help="injected sleep per H2D/D2H group move")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--group-mb", type=float, default=0.01,
                        help="forced per-group budget (keeps several "
                             "groups streaming per step)")
    parser.add_argument("--min-ratio", type=float, default=0.0,
                        help="fail unless sync/double wait ratio >= "
                             "this (the CI overlap guard)")
    args = parser.parse_args()

    os.environ["VELES_OFFLOAD_THROTTLE_MS"] = str(args.transfer_ms)
    os.environ["VELES_OFFLOAD_GROUP_MB"] = str(args.group_mb)

    # the buffered leg stages a whole batch walk ahead (depth covers
    # the 2G-1 per-batch transfer tasks), two upload workers + the
    # writeback thread giving three concurrent transfer channels
    legs = [("incore", False, 0, 1), ("sync", True, 0, 1),
            ("double", True, 6, 2)]
    rows = [run_leg(name, args.epochs, offload, depth, workers)
            for name, offload, depth, workers in legs]

    losses = {r["final_loss"] for r in rows}
    if len(losses) != 1:
        raise SystemExit("offload changed the math: losses %r" % losses)
    incore, sync, double = rows
    ratio = sync["offload_wait_ms"] / max(double["offload_wait_ms"],
                                          1e-9)
    print(json.dumps({
        "leg": "summary", "transfer_ms": args.transfer_ms,
        "incore_wall_s": incore["wall_s"],
        "sync_wait_ms": sync["offload_wait_ms"],
        "double_wait_ms": double["offload_wait_ms"],
        "wait_ratio_sync_over_double": round(ratio, 2),
        "step_overhead_ratio": round(
            double["wall_s"] / max(incore["wall_s"], 1e-9), 2),
        "loss_match": True,
    }), flush=True)
    if args.min_ratio and ratio < args.min_ratio:
        raise SystemExit(
            "overlap regressed: sync/double offload-wait ratio "
            "%.2f < %.1f" % (ratio, args.min_ratio))
    return 0


if __name__ == "__main__":
    sys.exit(main())
