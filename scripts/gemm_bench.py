#!/usr/bin/env python3
"""GEMM discipline benchmark (VERDICT r1 item #10).

Times XLA dot, pallas_gemm, pallas_kahan_gemm and the fori-loop Kahan
at the reference's 1500^2 computing-power shape
(``veles/accelerated_units.py:713-778``) and the AlexNet fc shapes,
printing a Markdown table (appended to docs/PERF.md by hand).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bench(fn, a, b, iters=30):
    """Chained in-jit iterations: the remote-dispatch relay costs
    ~5 ms per call, so timing per-call would measure the wire. The
    scalar carry serializes steps and defeats CSE."""
    import jax
    import jax.numpy as jnp

    def body(c, _):
        out = fn(a + c, b)
        return out[0, 0] * 1e-30, None

    chain = jax.jit(lambda: jax.lax.scan(
        body, jnp.float32(0), None, length=iters)[0])
    float(chain())  # compile + force
    t = time.time()
    float(chain())
    dt = time.time() - t
    flops = 2 * a.shape[0] * a.shape[1] * b.shape[1] * iters
    return flops / dt / 1e12, dt / iters * 1000


def main():
    import jax
    import jax.numpy as jnp
    import numpy

    from veles_tpu.ops.gemm import (_kahan_matmul_loop, pallas_gemm,
                                    pallas_kahan_gemm)

    rng = numpy.random.RandomState(0)
    shapes = [
        ("1500^2 (reference computing_power)", (1500, 1500, 1500)),
        ("AlexNet fc6 fwd (128x9216 @ 9216x4096)", (128, 9216, 4096)),
        ("AlexNet fc7 fwd (128x4096 @ 4096x4096)", (128, 4096, 4096)),
        ("AlexNet fc6 wgrad (9216x128 @ 128x4096)", (9216, 128, 4096)),
        ("4096^3 (tileable square)", (4096, 4096, 4096)),
    ]
    xla = jax.jit(lambda a, b: jnp.dot(
        a, b, preferred_element_type=jnp.float32))
    kloop = jax.jit(_kahan_matmul_loop)
    rows = ["| shape | XLA dot | pallas_gemm | pallas Kahan | "
            "fori Kahan |", "|---|---|---|---|---|"]
    for name, (m, k, n) in shapes:
        a = jnp.asarray(rng.rand(m, k).astype("f") - 0.5)
        b = jnp.asarray(rng.rand(k, n).astype("f") - 0.5)
        cells = []
        for fname, fn in (("xla", xla), ("pallas", pallas_gemm),
                          ("pallas_kahan", pallas_kahan_gemm),
                          ("kahan_loop", kloop)):
            print("  %s %s..." % (name, fname), file=sys.stderr,
                  flush=True)
            try:
                tf, ms = bench(fn, a, b)
                cells.append("%.1f TF/s (%.2f ms)" % (tf, ms))
            except Exception as e:
                cells.append("error: %s" % type(e).__name__)
        rows.append("| %s | %s |" % (name, " | ".join(cells)))
        print(rows[-1], flush=True)
    print("\n".join(rows[:2] + rows[2:]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
