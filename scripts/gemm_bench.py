#!/usr/bin/env python3
"""GEMM discipline benchmark (VERDICT r1 item #10).

Times XLA dot, pallas_gemm, pallas_kahan_gemm and the fori-loop Kahan
at the reference's 1500^2 computing-power shape
(``veles/accelerated_units.py:713-778``) and the AlexNet fc shapes,
printing a Markdown table (appended to docs/PERF.md by hand).

``--autotune`` instead runs the :mod:`veles_tpu.ops.autotune` search
across the flagship model's ACTUAL GEMM shapes (fc6/fc7/fc8 forward,
wgrad and dgrad at the bench batch, plus the fused bias+activation
forward variants) and prints the per-shape XLA-vs-best-Pallas table
from the resulting cache entries — the winners persist to the
per-device cache file, so a subsequent ``bench.py`` run picks them up
with zero measurements. ``--dtype`` selects the compute dtype
(default bfloat16, the flagship policy's MXU dtype).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the flagship AlexNet fc GEMMs at the bench batch (B=128):
#: (layer, pass, activation-or-None, (M, K, N), (ta, tb)). fc6:
#: 9216->4096 relu, fc7: 4096->4096 relu, fc8: 4096->1000 linear
#: (softmax head). wgrad is x.T @ dpre (M=fan_in, K=batch, ta=1) and
#: dgrad is dpre @ w.T (tb=1) — the flags must match the keys the
#: fused-linear backward consults at runtime, or the pre-tuned
#: winners never hit. Shared with scripts/profile_step.py --tune.
def flagship_gemm_shapes(batch=128):
    fcs = [("fc6", 9216, 4096, "relu"),
           ("fc7", 4096, 4096, "relu"),
           ("fc8", 4096, 1000, "linear")]
    out = []
    for name, fin, fout, act in fcs:
        out.append((name + " fwd", "gemm", None, (batch, fin, fout),
                    (0, 0)))
        out.append((name + " fwd+epilogue", "linear", act,
                    (batch, fin, fout), (0, 0)))
        out.append((name + " wgrad", "gemm", None, (fin, batch, fout),
                    (1, 0)))
        out.append((name + " dgrad", "gemm", None, (batch, fout, fin),
                    (0, 1)))
    return out


def bench(fn, a, b, iters=30):
    """Chained in-jit iterations: the remote-dispatch relay costs
    ~5 ms per call, so timing per-call would measure the wire. The
    scalar carry serializes steps and defeats CSE."""
    import jax
    import jax.numpy as jnp

    def body(c, _):
        out = fn(a + c, b)
        return out[0, 0] * 1e-30, None

    chain = jax.jit(lambda: jax.lax.scan(
        body, jnp.float32(0), None, length=iters)[0])
    float(chain())  # compile + force
    t = time.time()
    float(chain())
    dt = time.time() - t
    flops = 2 * a.shape[0] * a.shape[1] * b.shape[1] * iters
    return flops / dt / 1e12, dt / iters * 1000


def autotune_main(dtype="bfloat16", batch=128, out_dtype=None):
    """Search the flagship shapes, then print the per-shape table.

    ``dtype`` is the compute (operand) dtype and ``out_dtype`` the
    layer-output dtype — they must match the active precision policy's
    (compute, keep-or-accum) pair or the persisted ``linear`` keys
    will never be consulted at runtime (profile_step.py --tune derives
    both from the policy). Default: out_dtype = dtype, which is right
    for the uniform float32 and bfloat16 policies."""
    os.environ.setdefault("VELES_AUTOTUNE", "search")
    from veles_tpu.ops import autotune

    out_dtype = out_dtype or dtype

    print("autotune: mode=%s device=%s cache=%s"
          % (autotune.mode(), autotune.device_kind(),
             autotune.cache_path()), file=sys.stderr, flush=True)
    if not autotune.tunable():
        print("NOT TUNABLE here (no TPU and no VELES_AUTOTUNE_FORCE): "
              "plans will fall back without measuring", file=sys.stderr)

    rows = ["| shape | M x K x N | XLA | best Pallas | winner |",
            "|---|---|---|---|---|"]
    for label, op, act, (m, k, n), (ta, tb) in \
            flagship_gemm_shapes(batch):
        t0 = time.time()
        if op == "linear":
            impl, cfg = autotune.linear_plan(m, n, k, dtype, act,
                                             out_dtype)
        else:
            impl, cfg = autotune.gemm_plan(m, n, k, dtype, ta=ta,
                                           tb=tb, level=0)
        key_fields = dict(m=m, n=n, k=k, dtype=dtype)
        if op == "linear":
            key_fields.update(act=str(act), out=out_dtype)
        else:
            key_fields.update(ta=ta, tb=tb)
        entry = autotune.get_cache().get(
            autotune._key(op if op == "linear" else "gemm",
                          **key_fields)) or {}
        impl_ms = entry.get("impl_ms", {})
        flops = 2.0 * m * n * k

        def tfs(ms):
            return "%.1f TF/s" % (flops / (ms * 1e-3) / 1e12) if ms \
                else "-"
        win = impl if not cfg else "%s %s" % (impl, {
            k2: v for k2, v in cfg.items() if v is not None} or "")
        rows.append("| %s | %dx%dx%d | %s | %s | %s |" % (
            label, m, k, n, tfs(impl_ms.get("xla")),
            tfs(min((v for k2, v in impl_ms.items() if k2 != "xla"),
                    default=None) if impl_ms else None), win))
        print("%s  (%.1fs)" % (rows[-1], time.time() - t0),
              file=sys.stderr, flush=True)
    print("\n".join(rows))
    # the LRN/col-reduce plans are only CONSULTED from inside a jit
    # trace at runtime (where _plan defers searching), so this eager
    # sweep is what creates their cache entries: the flagship LRN
    # row-views (conv1 55x55x96, conv2 27x27x256 at the bench batch,
    # exercised by the VELES_LRN=pallas ablation) and the fc-width
    # column reduces
    for rows_, c in ((batch * 55 * 55, 96), (batch * 27 * 27, 256)):
        for which in ("fwd", "bwd"):
            t0 = time.time()
            impl, cfg = autotune.lrn_plan(rows_, c, dtype, which)
            print("lrn_%s %dx%d -> %s %s  (%.1fs)"
                  % (which, rows_, c, impl, cfg or "",
                     time.time() - t0), file=sys.stderr, flush=True)
    for n in (1000, 4096):
        t0 = time.time()
        impl, cfg = autotune.reduce_plan(batch, n, dtype)
        print("col_reduce %dx%d -> %s %s  (%.1fs)"
              % (batch, n, impl, cfg or "", time.time() - t0),
              file=sys.stderr, flush=True)
    s = autotune.summary()
    print("\nsearches=%d hits=%d misses=%d -> %s"
          % (s["searches"], s["hits"], s["misses"], s["path"]),
          file=sys.stderr)
    return 0


def main():
    import jax
    import jax.numpy as jnp
    import numpy

    from veles_tpu.ops.gemm import (_kahan_matmul_loop, pallas_gemm,
                                    pallas_kahan_gemm)

    rng = numpy.random.RandomState(0)
    shapes = [
        ("1500^2 (reference computing_power)", (1500, 1500, 1500)),
        ("AlexNet fc6 fwd (128x9216 @ 9216x4096)", (128, 9216, 4096)),
        ("AlexNet fc7 fwd (128x4096 @ 4096x4096)", (128, 4096, 4096)),
        ("AlexNet fc6 wgrad (9216x128 @ 128x4096)", (9216, 128, 4096)),
        ("4096^3 (tileable square)", (4096, 4096, 4096)),
    ]
    xla = jax.jit(lambda a, b: jnp.dot(
        a, b, preferred_element_type=jnp.float32))
    kloop = jax.jit(_kahan_matmul_loop)
    rows = ["| shape | XLA dot | pallas_gemm | pallas Kahan | "
            "fori Kahan |", "|---|---|---|---|---|"]
    for name, (m, k, n) in shapes:
        a = jnp.asarray(rng.rand(m, k).astype("f") - 0.5)
        b = jnp.asarray(rng.rand(k, n).astype("f") - 0.5)
        cells = []
        for fname, fn in (("xla", xla), ("pallas", pallas_gemm),
                          ("pallas_kahan", pallas_kahan_gemm),
                          ("kahan_loop", kloop)):
            print("  %s %s..." % (name, fname), file=sys.stderr,
                  flush=True)
            try:
                tf, ms = bench(fn, a, b)
                cells.append("%.1f TF/s (%.2f ms)" % (tf, ms))
            except Exception as e:
                cells.append("error: %s" % type(e).__name__)
        rows.append("| %s | %s |" % (name, " | ".join(cells)))
        print(rows[-1], flush=True)
    print("\n".join(rows[:2] + rows[2:]))
    return 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--autotune", action="store_true",
                        help="run the shape search over the flagship "
                             "GEMMs and persist winners to the "
                             "per-device autotune cache")
    parser.add_argument("--dtype", default="bfloat16",
                        help="compute dtype for --autotune")
    parser.add_argument("--out-dtype", default=None,
                        help="layer-output dtype for the fused-"
                             "epilogue search (default: --dtype)")
    parser.add_argument("--batch", type=int, default=128)
    cli = parser.parse_args()
    sys.exit(autotune_main(cli.dtype, cli.batch, cli.out_dtype)
             if cli.autotune else main())
