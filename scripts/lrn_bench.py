#!/usr/bin/env python3
"""Fused-Pallas-LRN vs XLA benchmark (VERDICT r2 item #1).

Times forward and forward+backward at the AlexNet LRN shapes, f32 and
bf16, chained in-jit (the relay costs ~5 ms per dispatch and
block_until_ready can return early — force with a scalar read).
Appended to docs/PERF.md by hand.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bench_fwd(fn, x, iters=50):
    """The inputs are jit ARGUMENTS, never closure captures — captured
    arrays bake into the HLO as literals and 150 MB activations blow
    the relay's compile-request size limit (HTTP 413)."""
    import jax
    import jax.numpy as jnp

    def chain_fn(x):
        def body(c, _):
            y = fn(x + c.astype(x.dtype))
            # consume EVERY element: a [0]-slice carry lets XLA
            # dead-code-eliminate the bulk of a transparent formulation
            # while an opaque Pallas kernel still does the real work —
            # the sum costs one fused pass, identically for everyone
            return jnp.sum(y.astype(jnp.float32)) * 1e-30, None
        return jax.lax.scan(body, jnp.float32(0), None, length=iters)[0]

    chain = jax.jit(chain_fn)
    float(chain(x))
    t = time.time()
    float(chain(x))
    return (time.time() - t) / iters * 1000


def bench_fwdbwd(fn, x, g, iters=50):
    import jax
    import jax.numpy as jnp

    def chain_fn(x, g):
        def body(c, _):
            y, vjp = jax.vjp(fn, x + c.astype(x.dtype))
            dx, = vjp(g)
            return (jnp.sum(y.astype(jnp.float32)) +
                    jnp.sum(dx.astype(jnp.float32))) * 1e-30, None
        return jax.lax.scan(body, jnp.float32(0), None, length=iters)[0]

    chain = jax.jit(chain_fn)
    float(chain(x, g))
    t = time.time()
    float(chain(x, g))
    return (time.time() - t) / iters * 1000


def main():
    import jax
    import jax.numpy as jnp
    import numpy

    from veles_tpu.nn.normalization import _lrn_slices
    from veles_tpu.ops.lrn import lrn_fused

    print("platform:", jax.devices()[0].platform, file=sys.stderr)
    rng = numpy.random.RandomState(0)
    shapes = [("conv1 (128,55,55,96)", (128, 55, 55, 96)),
              ("conv2 (128,27,27,256)", (128, 27, 27, 256))]
    print("| shape dtype | XLA fwd | Pallas fwd | XLA fwd+bwd | "
          "Pallas fwd+bwd |\n|---|---|---|---|---|", flush=True)
    for name, shape in shapes:
        for dtype in (jnp.float32, jnp.bfloat16):
            x = jnp.asarray(rng.randn(*shape), dtype=dtype)
            g = jnp.asarray(rng.randn(*shape), dtype=dtype)
            xla = lambda v: _lrn_slices(v)
            pallas = lambda v: lrn_fused(v)
            cells = []
            for label, t in (
                    ("xla fwd", lambda: bench_fwd(xla, x)),
                    ("pallas fwd", lambda: bench_fwd(pallas, x)),
                    ("xla fb", lambda: bench_fwdbwd(xla, x, g)),
                    ("pallas fb", lambda: bench_fwdbwd(pallas, x, g))):
                print("  %s %s %s..." % (name, jnp.dtype(dtype).name,
                                         label),
                      file=sys.stderr, flush=True)
                try:
                    cells.append("%.2f ms" % t())
                except Exception as e:
                    cells.append("error: %s" % type(e).__name__)
            print("| %s %s | %s |" % (
                name, jnp.dtype(dtype).name, " | ".join(cells)),
                flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
