#!/usr/bin/env python
"""CI lint gate over the veles-analyze static checkers.

Mirrors ``scripts/perf_gate.py``'s design: a committed baseline
(``scripts/lint_baseline.json``) records the accepted debt with a
human-written reason per entry; anything the checkers find that is NOT
in the baseline hard-fails the job. Stale suppressions (fingerprints
no checker produces any more) are reported so the baseline only ever
shrinks.

Modes
-----
(default)        analyze veles_tpu/ against the baseline; exit 1 on
                 any unsuppressed finding.
--self-test      prove the gate CAN fail: run the checkers over the
                 known-bad fixtures in tests/fixtures/lint/ and
                 REQUIRE every checker code to fire (and the known-
                 clean fixture to stay clean). A gate that cannot
                 fail gates nothing — CI runs this next to the real
                 gate, like perf_gate's regressed-fixture step.
--update-baseline  rewrite the baseline from current findings
                 (requires --reason); for paying down or accepting
                 debt deliberately, never run in CI.
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from veles_tpu.analysis import core                    # noqa: E402
from veles_tpu.analysis.__main__ import build_project  # noqa: E402

BASELINE = os.path.join(REPO, "scripts", "lint_baseline.json")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")

#: every code the self-test requires the bad fixtures to produce —
#: one per checker rule, so a silently-dead rule fails CI
EXPECTED_CODES = (
    "LOCK001", "LOCK002", "LOCK003",
    "TRACE001", "TRACE002", "TRACE003", "TRACE004", "TRACE005",
    "TRACE006",
    "MET001", "MET002", "MET003",
    "KNOB001", "KNOB002", "KNOB003",
)


def run_gate(baseline_path):
    project = build_project([os.path.join(REPO, "veles_tpu")], REPO)
    findings = core.run_all(project)
    baseline = core.load_baseline(baseline_path)
    new, suppressed, stale = core.apply_baseline(findings, baseline)
    for f in new:
        print("FAIL %s" % f.render())
    if suppressed:
        print("     %d baseline-suppressed finding(s)" % len(suppressed))
    for fp in stale:
        print("WARN stale suppression %s — debt paid, remove it from "
              "scripts/lint_baseline.json" % fp)
    print("lint gate: %d file(s), %d new finding(s) -> %s"
          % (len(project.modules), len(new),
             "FAIL" if new else "PASS"))
    return 1 if new else 0


def run_self_test():
    bad = [os.path.join(FIXTURES, name)
           for name in sorted(os.listdir(FIXTURES))
           if name.startswith("bad_") and name.endswith(".py")]
    clean = [os.path.join(FIXTURES, "clean.py")]
    if not bad:
        print("SELF-TEST FAIL: no bad fixtures under %s" % FIXTURES)
        return 1
    project = build_project(bad, REPO, complete=False)
    findings = core.run_all(project)
    fired = {f.code for f in findings}
    missing = [c for c in EXPECTED_CODES if c not in fired]
    ok = True
    if missing:
        ok = False
        print("SELF-TEST FAIL: known-bad fixtures did not trigger %s "
              "— those rules are dead and gate nothing"
              % ", ".join(missing))
    if not findings:
        ok = False
        print("SELF-TEST FAIL: the gate cannot fail")
    clean_findings = core.run_all(build_project(clean, REPO,
                                                complete=False))
    if clean_findings:
        ok = False
        for f in clean_findings:
            print("SELF-TEST FAIL (clean fixture): %s" % f.render())
    print("lint gate self-test: %d finding(s) on bad fixtures, "
          "%d code(s) covered, clean fixture %s -> %s"
          % (len(findings), len(fired & set(EXPECTED_CODES)),
             "clean" if not clean_findings else "DIRTY",
             "PASS" if ok else "FAIL"))
    return 0 if ok else 1


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--baseline", default=BASELINE)
    parser.add_argument("--self-test", action="store_true")
    parser.add_argument("--update-baseline", action="store_true")
    parser.add_argument("--reason", default="")
    args = parser.parse_args(argv)
    if args.self_test:
        return run_self_test()
    if args.update_baseline:
        if not args.reason.strip():
            parser.error("--update-baseline requires --reason")
        project = build_project([os.path.join(REPO, "veles_tpu")], REPO)
        findings = core.run_all(project)
        core.write_baseline(args.baseline, findings, args.reason)
        print("baseline rewritten: %d suppression(s)" % len(findings))
        return 0
    return run_gate(args.baseline)


if __name__ == "__main__":
    sys.exit(main())
