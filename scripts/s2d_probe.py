#!/usr/bin/env python3
"""Space-to-depth probe for AlexNet conv1 (round-4 lever, docs/PERF.md).

The 11x11-stride-4 conv on 227x227x3 feeds the MXU a 3-deep reduction
axis; rearranging 4x4 input patches into channels gives an equivalent
4x4-stride-1 conv with cin=48. This script (a) verifies the transform
is EXACT against lax.conv, (b) times both fwd and fwd+bwd at the
bench shape. Standalone: no framework changes until the numbers argue.

Math: with x padded by 2 and p = 4u + r,
  y[i,j,o] = sum_{a,b,c} x[4i+a-2, 4j+b-2, c] w[a,b,c,o]
           = sum_{da,db,r,s,c} xs[i+da, j+db, rsc] w2[da,db,rsc,o]
where xs[u,v,(r,s,c)] = xpad[4u+r, 4v+s, c] and
w2[da,db,(r,s,c),o] = w[4da+r, 4db+s, c, o] for 4da+r in [0,11).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy


def conv1_ref(x, w):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(4, 4), padding=[(2, 2), (2, 2)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def s2d_input(x):
    """(N, 227, 227, 3) -> padded s2d (N, 59, 59, 48).

    Pad 2 on the left (the conv's own padding) and 7 on the right —
    enough that the 4-tap VALID window yields the reference's 56
    outputs (the 4th tap row is all-zero kernel, reading zero pad)."""
    n = x.shape[0]
    xp = jnp.pad(x, [(0, 0), (2, 7), (2, 7), (0, 0)])
    # (N, 59, 4, 59, 4, 3) -> (N, 59, 59, 4, 4, 3)
    xs = xp.reshape(n, 59, 4, 59, 4, 3).transpose(0, 1, 3, 2, 4, 5)
    return xs.reshape(n, 59, 59, 48)


def s2d_kernel(w):
    """(11, 11, 3, 96) -> (4, 4, 48, 96) zero-extended to 16 taps."""
    w16 = jnp.pad(w, [(0, 5), (0, 5), (0, 0), (0, 0)])  # 11 -> 16
    # (4, 4(da), ...) index [4*da + r] -> [da, r]
    w2 = w16.reshape(4, 4, 4, 4, 3, 96)   # (da, r, db, s, c, o)
    w2 = w2.transpose(0, 2, 1, 3, 4, 5)   # (da, db, r, s, c, o)
    return w2.reshape(4, 4, 48, 96)


def conv1_s2d(xs, w2):
    # taps da,db in [0,4) correspond to offsets 0..3 on the s2d grid
    # starting at u=i: out[i] = sum_da xs[i+da] — VALID over 58 gives
    # 55... we need out size 57: floor((227+4-11)/4)+1 = 56? compute
    # exactly below and slice to the reference's output size.
    y = jax.lax.conv_general_dilated(
        xs, w2, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y


def bench_fwd(fn, *args, iters=30):
    def chain(args):
        def body(c, _):
            out = fn(*[a + c.astype(a.dtype) if i == 0 else a
                       for i, a in enumerate(args)])
            return jnp.sum(out.astype(jnp.float32)) * 1e-30, None
        return jax.lax.scan(body, jnp.float32(0), None,
                            length=iters)[0]
    f = jax.jit(chain)
    float(f(args))
    t = time.time()
    float(f(args))
    return (time.time() - t) / iters * 1000


def bench_fwdbwd(fn, g, *args, iters=30):
    def chain(args):
        def body(c, _):
            y, vjp = jax.vjp(lambda x: fn(x, *args[1:]),
                             args[0] + c.astype(args[0].dtype))
            dx, = vjp(g)
            return (jnp.sum(y.astype(jnp.float32)) +
                    jnp.sum(dx.astype(jnp.float32))) * 1e-30, None
        return jax.lax.scan(body, jnp.float32(0), None,
                            length=iters)[0]
    f = jax.jit(chain)
    float(f(args))
    t = time.time()
    float(f(args))
    return (time.time() - t) / iters * 1000


def main():
    rng = numpy.random.RandomState(0)
    # numerics check on a small CPU-friendly shape first
    x = jnp.asarray(rng.randn(2, 227, 227, 3).astype("f"))
    w = jnp.asarray(rng.randn(11, 11, 3, 96).astype("f") * 0.05)
    y_ref = conv1_ref(x, w)
    y_s2d = conv1_s2d(s2d_input(x), s2d_kernel(w))
    out = y_ref.shape[1]
    print("ref out:", y_ref.shape, "s2d out:", y_s2d.shape,
          file=sys.stderr)
    y_cut = y_s2d[:, :out, :out, :]
    err = float(jnp.max(jnp.abs(y_cut - y_ref)))
    scale = float(jnp.max(jnp.abs(y_ref)))
    print("max abs err %.3e (scale %.3e)" % (err, scale))
    if err > 1e-3 * scale:
        print("TRANSFORM NOT EXACT — stopping before timing")
        return 1

    for dtype in (jnp.bfloat16, jnp.float32):
        xb = jnp.asarray(rng.randn(128, 227, 227, 3), dtype=dtype)
        wb = jnp.asarray(numpy.asarray(w), dtype=dtype)
        xs = s2d_input(xb)
        w2 = s2d_kernel(wb)
        g = jnp.ones_like(conv1_ref(xb, wb))
        g2 = jnp.ones_like(conv1_s2d(xs, w2))
        name = jnp.dtype(dtype).name
        print("%s conv1 fwd: ref %.2f ms  s2d %.2f ms" % (
            name, bench_fwd(conv1_ref, xb, wb),
            bench_fwd(conv1_s2d, xs, w2)))
        print("%s conv1 fwd+bwd: ref %.2f ms  s2d %.2f ms" % (
            name, bench_fwdbwd(conv1_ref, g, xb, wb),
            bench_fwdbwd(conv1_s2d, g2, xs, w2)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
