#!/usr/bin/env python3
"""Accuracy-parity runs against the reference's published MNIST
baselines (``manualrst_veles_algorithms.rst:32``: 1.48% FC validation
error; shipped conv snapshot 0.73%).

Zero-egress environments cannot fetch the real IDX files, so the runs
use the committed deterministic golden-digit dataset
(:mod:`veles_tpu.datasets`) — same shapes, comparable difficulty
(linear model ~46% error, so the thresholds are not reachable by a
degenerate model). With network (or pre-downloaded IDX files in
--mnist-dir), the same configs train on real MNIST via
``mnist_idx_provider``.

Usage: python scripts/parity_run.py [--mnist-dir DIR] [--out FILE]
Writes a Markdown results table (default docs/PARITY_RUNS.md).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mnist-dir", default=None,
                        help="directory with the 4 IDX files (real MNIST)")
    parser.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "PARITY_RUNS.md"))
    parser.add_argument("--fc-epochs", type=int, default=40)
    parser.add_argument("--conv-epochs", type=int, default=25)
    parser.add_argument("--cifar-epochs", type=int, default=40)
    parser.add_argument("--ae-epochs", type=int, default=30)
    parser.add_argument("--som-epochs", type=int, default=10)
    args = parser.parse_args()

    if args.mnist_dir:
        from veles_tpu.models.mnist import mnist_idx_provider
        provider = mnist_idx_provider(args.mnist_dir)
        dataset = "real MNIST (%s)" % args.mnist_dir
        fc_target, conv_target = 0.0160, 0.0090
        # real MNIST: the AE bar IS the reference's published number;
        # no published Kohonen bar exists, so the SOM targets stay the
        # golden-digit-calibrated ones (same normalization + shapes)
        # and are advisory there
        ae_target = 0.5478
        som_qe_target, som_te_target = 9.0, 0.06
    else:
        from veles_tpu.datasets import golden_digits
        provider = golden_digits(n_train=12000, n_valid=2000)
        dataset = "golden digits (committed, seed 2026, 12k/2k)"
        fc_target, conv_target = 0.0150, 0.0200
        # AE: full-budget 0.1617 measured r5 (reference context 0.5478
        # on real MNIST; mean-predictor floor 0.3358). SOM: QE 7.86 /
        # TE 3.4% measured (untrained codebook: 24.5 / 96%).
        ae_target = 0.2000
        som_qe_target, som_te_target = 9.0, 0.06

    from veles_tpu.models.parity import (train_ae, train_cifar,
                                         train_conv, train_fc, train_som)
    from veles_tpu.datasets import golden_objects
    cifar_provider = golden_objects(n_train=10000, n_valid=2000)
    cifar_target = 0.1600  # beat the reference's 17.21% CIFAR-10 bar

    t = time.time()
    fc_err = train_fc(provider, args.fc_epochs)
    t_fc = time.time() - t
    t = time.time()
    conv_err = train_conv(provider, args.conv_epochs)
    t_conv = time.time() - t
    t = time.time()
    cifar_err = train_cifar(cifar_provider, args.cifar_epochs)
    t_cifar = time.time() - t
    t = time.time()
    ae_rmse = train_ae(provider, args.ae_epochs)
    t_ae = time.time() - t
    t = time.time()
    som = train_som(provider, args.som_epochs)
    t_som = time.time() - t

    rows = [
        ("FC 784-100-10 (BASELINE config 1)", fc_err, fc_target,
         "%", "reference 1.48% on real MNIST", t_fc),
        ("conv 16c5-p2-32c5-p2-100-10 (config 2 analog)", conv_err,
         conv_target, "%", "reference conv snapshot 0.73%", t_conv),
        ("CIFAR conv cifar10-quick + mean_disp (config 2, golden "
         "objects 32x32x3)", cifar_err, cifar_target, "%",
         "reference CIFAR-10 17.21%", t_cifar),
        ("AE 784-100-784 val RMSE (BASELINE config 4)", ae_rmse,
         ae_target, "rmse", "reference 0.5478 RMSE on real MNIST",
         t_ae),
        ("Kohonen 8x8 quantization error (config 4)",
         som["quantization_error"], som_qe_target, "raw",
         "untrained codebook %.1f" %
         som["untrained_quantization_error"], t_som),
        ("Kohonen 8x8 topographic error (config 4)",
         som["topographic_error"], som_te_target, "%",
         "untrained codebook %.0f%%" %
         (100 * som["untrained_topographic_error"]), 0.0),
    ]
    lines = [
        "# Accuracy parity runs",
        "",
        "Dataset: %s" % dataset,
        "",
        # unit-neutral label: rows carry % error, RMSE and raw
        # quantization error (each row names its unit)
        "| Config | metric | target | reference context | train s |",
        "|---|---|---|---|---|",
    ]
    ok = True
    for name, err, target, unit, ctx, secs in rows:
        status = "✅" if err <= target else "❌"
        ok &= err <= target
        if unit == "%":
            val = "**%.2f%%** %s | ≤%.2f%%" % (100 * err, status,
                                               100 * target)
        else:
            val = "**%.4f** %s | ≤%.4f" % (err, status, target)
        lines.append("| %s | %s | %s | %.0f |" % (name, val, ctx, secs))
    lines += [
        "",
        "Conv beats FC: %s (%.2f%% < %.2f%%)" %
        ("✅" if conv_err < fc_err else "❌", 100 * conv_err,
         100 * fc_err),
        "",
        "Asserted continuously by `tests/test_parity.py` (reduced "
        "budget); regenerate with `python scripts/parity_run.py`.",
    ]
    with open(args.out, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
