#!/usr/bin/env python3
"""CI perf-regression gate (ISSUE 7 tentpole part 5).

Compares a machine-readable perf snapshot against a committed baseline
with per-metric tolerances, and exits non-zero when a hard-gated
metric regresses — the mechanism that stops "the refactor that quietly
doubled step time" from merging.

Three modes::

    perf_gate.py --capture SNAP.json     # run the probe, write snapshot
    perf_gate.py SNAP.json               # compare vs scripts/perf_baseline.json
    perf_gate.py SNAP.json --baseline F  # compare vs an explicit baseline
    perf_gate.py --update-baseline SNAP.json   # adopt snapshot values,
                                               # keeping each metric's policy

**The probe** is a seeded, CPU-deterministic tiny training run through
the real fused pipeline (FusedRunner + telemetry + cost attribution),
so the snapshot carries both *quality* metrics (final loss, epochs
completed — bit-stable across runs on one jaxlib) and *cost* metrics
(analytic segment FLOPs from ``Compiled.cost_analysis()``, measured
step/compile times, host RSS).

**The baseline** maps each metric to a policy::

    {"metrics": {"final_loss": {"value": 0.31, "tolerance": 0.25,
                                "direction": "lower", "gate": "hard"}}}

``direction`` says which way is good ("higher" = bigger is better);
a metric regresses when it moves the BAD way by more than
``tolerance`` (a fraction of the baseline value). ``gate: "hard"``
fails CI; ``gate: "report"`` only prints — the wall-clock throughput
metrics stay report-only until a TPU-attached bench round promotes
them (shared CI runners are too noisy to gate on milliseconds).

A hard metric MISSING from the snapshot also fails: a probe change
that silently drops a gated signal must not pass by omission.
"""

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

DEFAULT_BASELINE = os.path.join(HERE, "scripts", "perf_baseline.json")

#: probe geometry — small enough for seconds-long CPU CI, big enough
#: that the loss actually moves (so a broken optimizer regresses it)
SAMPLES = 120
BATCH = 20
EPOCHS = 4
SEED = 1234


def _probe_workflow():
    import numpy

    from veles_tpu import prng
    from veles_tpu.launcher import Launcher
    from veles_tpu.models.mnist import MnistWorkflow

    rng = numpy.random.RandomState(SEED)
    x = rng.rand(SAMPLES, 6, 6).astype(numpy.float32)
    y = (x.reshape(SAMPLES, -1).sum(1) > 18).astype(numpy.int32)
    split = SAMPLES - 2 * BATCH

    prng.get().seed(SEED)
    prng.get("loader").seed(SEED + 1)
    launcher = Launcher(graphics=False)
    wf = MnistWorkflow(
        launcher,
        provider=lambda: (x[:split], y[:split], x[split:], y[split:]),
        layers=(16,), minibatch_size=BATCH, learning_rate=0.1,
        max_epochs=EPOCHS)
    launcher.initialize()
    t0 = time.perf_counter()
    launcher.run()
    wall = time.perf_counter() - t0
    return wf, wall


def _input_pipeline_probe():
    """ISSUE 8 overlap guard: a tiny streamed (out-of-core) run with a
    throttled host ETL, synchronous vs prefetched. The waits are
    sleep-dominated so the ratio is structural, not machine-speed:
    if the pipeline silently degrades to the synchronous path the
    ratio collapses to ~1 and the hard gate fails."""
    import numpy

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader import prefetch
    from veles_tpu.models.mnist import MnistWorkflow
    from veles_tpu.telemetry.registry import get_registry
    from veles_tpu.train import FusedTrainer

    saved = {k: os.environ.get(k) for k in
             ("VELES_ETL_THROTTLE_MS", "VELES_SHARD_MB")}
    os.environ["VELES_ETL_THROTTLE_MS"] = "40"
    os.environ["VELES_SHARD_MB"] = "0.004"  # 1 minibatch per shard

    rng = numpy.random.RandomState(SEED)
    x = rng.rand(200, 6, 6).astype(numpy.float32)
    y = (x.reshape(200, -1).sum(1) > 18).astype(numpy.int32)

    def run(depth, workers):
        hist = get_registry().get("veles_step_input_wait_ms")
        if hist is not None:
            hist.reset()
        prng.get().seed(SEED)
        prng.get("loader").seed(SEED + 1)
        wf = MnistWorkflow(
            DummyLauncher(),
            provider=lambda: (x[:160], y[:160], x[160:], y[160:]),
            layers=(16,), minibatch_size=20, max_epochs=1)
        wf.initialize(device=Device(backend=None))
        trainer = FusedTrainer(wf, stream=True, prefetch_depth=depth,
                               prefetch_workers=workers)
        trainer.train()
        child = get_registry().get("veles_step_input_wait_ms").labels()
        return child.sum

    try:
        sync_ms = run(0, 1)
        deep_ms = run(4, 4)
    finally:
        prefetch.shutdown_all()
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
    return {"step_input_wait_ms": deep_ms,
            "input_wait_overlap_ratio": sync_ms / max(deep_ms, 1e-9)}


def _offload_probe():
    """ISSUE 17 overlap guard: a tiny host-offloaded run (several
    layer groups per step) with a throttled interconnect, synchronous
    vs double-buffered ring. Sleep-dominated like the input probe, so
    the ratio is structural and gates HARD — if the ring silently
    degrades to inline transfers it collapses to ~1. A second,
    unthrottled pair measures the offloaded-vs-in-core step overhead
    (report-only: real wall time, noisy on shared runners)."""
    import numpy

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist import MnistWorkflow
    from veles_tpu.train import FusedTrainer
    from veles_tpu.train import offload

    saved = {k: os.environ.get(k) for k in
             ("VELES_OFFLOAD_THROTTLE_MS", "VELES_OFFLOAD_GROUP_MB")}
    os.environ["VELES_OFFLOAD_GROUP_MB"] = "0.001"

    rng = numpy.random.RandomState(SEED)
    x = rng.rand(200, 6, 6).astype(numpy.float32)
    y = (x.reshape(200, -1).sum(1) > 18).astype(numpy.int32)

    def run(offloaded, depth, workers, throttle_ms):
        os.environ["VELES_OFFLOAD_THROTTLE_MS"] = str(throttle_ms)
        prng.get().seed(SEED)
        prng.get("loader").seed(SEED + 1)
        wf = MnistWorkflow(
            DummyLauncher(),
            provider=lambda: (x[:160], y[:160], x[160:], y[160:]),
            layers=(16, 12), minibatch_size=20, max_epochs=1)
        wf.initialize(device=Device(backend=None))
        trainer = FusedTrainer(wf, offload=offloaded,
                               offload_depth=depth,
                               offload_workers=workers)
        assert trainer.offloaded == offloaded
        t0 = time.perf_counter()
        trainer.train()
        return trainer.offload_wait_s * 1e3, time.perf_counter() - t0

    try:
        sync_ms, _ = run(True, 0, 1, 40)
        double_ms, _ = run(True, 6, 2, 40)
        _, incore_s = run(False, 0, 1, 0)
        _, off_s = run(True, 6, 2, 0)
    finally:
        offload.shutdown_all()
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
    return {"offload_overlap_ratio": sync_ms / max(double_ms, 1e-9),
            "offload_step_overhead_ratio": off_s / max(incore_s, 1e-9)}


def _federation_probe(n_series=100, beats=50, rounds=3):
    """ISSUE 9 overhead guard (report-only): heartbeat round-trip with
    vs. without the federation snapshot piggyback, over a real
    loopback coordinator pair with a ~2x``n_series``-series slave
    registry whose series half-churn every beat — a realistic worst
    case (steady state deltas are far smaller). The ratio keeps the
    observability plane's cost visible in the perf baseline."""
    from veles_tpu.parallel.coordinator import (CoordinatorClient,
                                                CoordinatorServer)
    from veles_tpu.telemetry.federation import SnapshotEncoder
    from veles_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    hist = reg.histogram("probe_ms", labels=("op",))
    gauge = reg.gauge("probe_value", labels=("op",))
    for i in range(n_series):
        hist.labels(op="op%d" % i).observe(1.0)
        gauge.labels(op="op%d" % i).set(float(i))

    server = CoordinatorServer(checksum="fedprobe")
    try:
        client = CoordinatorClient(server.address, checksum="fedprobe",
                                   heartbeat_interval=3600.0,
                                   federate=False)
        client.connect()
        proto = client._hb_proto
        encoder = SnapshotEncoder(registry=reg)
        encoder.encode()  # prime: steady-state deltas, not full pushes

        def run_leg(with_telemetry):
            total = 0.0
            for i in range(beats):
                if with_telemetry:
                    # churn half the series so every delta is honest
                    for j in range(0, n_series, 2):
                        hist.labels(op="op%d" % j).observe(float(i))
                msg = {"cmd": "heartbeat", "power": 1.0, "rtt_ms": 1.0}
                t0 = time.perf_counter()
                if with_telemetry:
                    delta = encoder.encode()
                    if delta is not None:
                        msg["telemetry"] = delta
                proto.send(msg)
                proto.recv()
                total += time.perf_counter() - t0
            return total / beats

        run_leg(False)  # warm the path
        base = min(run_leg(False) for _ in range(rounds))
        fed = min(run_leg(True) for _ in range(rounds))
        client.close()
    finally:
        server.stop()
    return {"federation_overhead_ratio": fed / max(base, 1e-9)}


def _sched_federation_probe(n_series=200, beats=50, rounds=3):
    """ISSUE 19 overhead guard (report-only): the elastic-tier twin of
    :func:`_federation_probe` — heartbeat round-trip against a real
    :class:`RendezvousServer` with vs. without the SnapshotEncoder
    delta piggyback, from a 200-series worker registry whose series
    half-churn every beat. The delta rides the SAME beat the
    supervisor's liveness verdict depends on, so its encode+absorb
    cost stays pinned in the baseline."""
    from veles_tpu.parallel.elastic import (RendezvousClient,
                                            RendezvousServer)
    from veles_tpu.telemetry.federation import SnapshotEncoder
    from veles_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    gauge = reg.gauge("probe_value", labels=("op",))
    for i in range(n_series):
        gauge.labels(op="op%d" % i).set(float(i))

    server = RendezvousServer(min_workers=1, settle_s=0.05).start()
    try:
        client = RendezvousClient(server.address, "probe-worker")
        gen = client.join_wait(timeout_s=30.0)["gen"]
        encoder = SnapshotEncoder(registry=reg)
        encoder.encode()  # prime: steady-state deltas, not full pushes

        def run_leg(with_telemetry):
            total = 0.0
            for i in range(beats):
                if with_telemetry:
                    # churn half the series so every delta is honest
                    for j in range(0, n_series, 2):
                        gauge.labels(op="op%d" % j).set(float(i + j))
                t0 = time.perf_counter()
                telemetry = encoder.encode() if with_telemetry \
                    else None
                client.heartbeat_full(gen, telemetry=telemetry)
                total += time.perf_counter() - t0
            return total / beats

        run_leg(False)  # warm the path
        base = min(run_leg(False) for _ in range(rounds))
        fed = min(run_leg(True) for _ in range(rounds))
        client.close()
    finally:
        server.stop()
    return {"sched_federation_overhead_ratio": fed / max(base, 1e-9)}


def _recovery_probe():
    """ISSUE 12 recovery-time guard (report-only): a loopback
    coordinator pair where one slave takes a job and dies abruptly
    (socket closed, no result); measured is the wall time from the
    death to the requeued job's result arriving from the healthy
    sibling — the veles_recovery_ms{event="requeue"} path end to
    end. Report-only because shared CI runners make wall time noisy;
    the structural assertions live in tests/test_fault_tolerance.py."""
    from veles_tpu.parallel.coordinator import (CoordinatorClient,
                                                CoordinatorServer)

    server = CoordinatorServer(checksum="recovery",
                               heartbeat_timeout=0.5)
    try:
        server.submit(*[{"n": i} for i in range(4)])
        victim = CoordinatorClient(server.address,
                                   checksum="recovery").connect()
        victim.proto.send({"cmd": "job"})
        victim.proto.recv()  # job is now in-flight on the victim
        t0 = time.perf_counter()
        # abrupt: kill the raw channels (no goodbye — client.close()
        # would send the voluntary-exit bye and measure the CLEAN
        # disconnect instead of a death)
        victim._closed = True
        victim._hb_stop.set()
        victim.proto.close()
        victim._hb_proto.close()
        healthy = CoordinatorClient(server.address,
                                    checksum="recovery").connect()
        healthy.serve_forever(lambda job: job["n"], max_idle=20)
        server.wait(4, timeout=20)
        recovery_s = time.perf_counter() - t0
        healthy.close()
    finally:
        server.stop()
    return {"recovery_time_s": recovery_s}


def _spmd_recovery_probe():
    """ISSUE 13 recovery-time guard (report-only): the elastic SPMD
    supervision tier with jax-free stub workers — rendezvous anchor +
    two supervisors; one worker is SIGKILLed; measured is the server's
    break -> new-generation-formed time at world size 1 (detection +
    settle + re-rendezvous — the pure orchestration cost; checkpoint
    restore and XLA recompile ride on top in a real pod and are
    covered by `bench_distributed.py --chaos spmd-kill`). Report-only
    for the same reason as recovery_time_s: shared CI wall clocks are
    noisy; the structural assertions live in tests/test_elastic.py."""
    import signal
    import threading

    from veles_tpu.parallel.elastic import (ElasticSupervisor,
                                            RendezvousServer)

    server = RendezvousServer(expected=2, min_workers=1, settle_s=0.3,
                              heartbeat_timeout_s=2.0).start()
    stub = ("import os, time\n"
            "if os.environ.get('VELES_ELASTIC_GEN') == '0':\n"
            "    time.sleep(60)\n")
    argv = [sys.executable, "-c", stub]
    addr = "%s:%d" % server.address
    sups = [ElasticSupervisor(addr, argv, member="p%d" % i,
                              max_restarts=0, poll_s=0.05)
            for i in range(2)]
    rcs = [None, None]
    threads = [threading.Thread(target=lambda i=i: rcs.__setitem__(
        i, sups[i].run()), daemon=True) for i in range(2)]
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not (
                server.phase == "running" and
                all(s.worker is not None for s in sups)):
            time.sleep(0.02)
        if server.phase != "running" or sups[1].worker is None:
            raise RuntimeError(
                "spmd recovery probe: generation 0 did not form "
                "(phase=%s)" % server.phase)
        time.sleep(0.1)
        os.kill(sups[1].worker.pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=30)
        recovery = server.last_recovery_s
    finally:
        for sup in sups:
            sup._kill_worker()
        server.stop()
    if rcs[0] != 0 or recovery is None:
        raise RuntimeError("spmd recovery probe failed: rcs=%r" % rcs)
    return {"spmd_recovery_time_s": recovery}


_GSPMD_PROBE = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ.setdefault("VELES_TPU_BACKEND", "cpu")
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy

from veles_tpu import prng
from veles_tpu.backends import Device
from veles_tpu.dummy import DummyLauncher
from veles_tpu.models.mnist import MnistWorkflow
from veles_tpu.parallel import wire
from veles_tpu.parallel.gspmd import BATCH_AXIS, GSPMDTrainer, gspmd_mesh
from veles_tpu.parallel.mesh import named_sharding
from veles_tpu.train import FusedTrainer

SEED = %(seed)d


def build_wf():
    rng = numpy.random.RandomState(SEED)
    x = rng.rand(160, 6, 6).astype(numpy.float32)
    y = (x.reshape(160, -1).sum(1) > 18).astype(numpy.int32)
    prng.get().seed(SEED)
    prng.get("loader").seed(SEED + 1)
    wf = MnistWorkflow(
        DummyLauncher(),
        provider=lambda: (x[:128], y[:128], x[128:], y[128:]),
        layers=(16,), minibatch_size=32, learning_rate=0.1,
        max_epochs=3)
    wf.initialize(device=Device(backend="cpu"))
    return wf


def curve(history):
    return [(h["epoch"], h["validation"]["loss"],
             h["validation"]["normalized"], h["train"]["loss"],
             h["train"]["normalized"]) for h in history]


fused = curve(FusedTrainer(build_wf()).train())
gspmd = curve(GSPMDTrainer(build_wf()).train())
parity = 1.0 if fused == gspmd else 0.0

# exchange-cycle ratio: the shm wire's oob encode/copy/decode vs the
# jitted psum merge, same mid-size tree (sleep-free, so report-only)
rng = numpy.random.RandomState(SEED)
tree = {"w0": rng.randn(512, 1024).astype(numpy.float32),
        "b0": rng.randn(1024).astype(numpy.float32),
        "w1": rng.randn(1024, 512).astype(numpy.float32)}
mesh = gspmd_mesh()
n = mesh.shape[BATCH_AXIS]
parts = {k: jax.device_put(numpy.broadcast_to(v, (n,) + v.shape),
                           named_sharding(mesh, BATCH_AXIS))
         for k, v in tree.items()}
merge = jax.jit(lambda t: {k: jnp.sum(v, axis=0) for k, v in t.items()},
                out_shardings=named_sharding(mesh))
jax.block_until_ready(merge(parts))


def best(fn, cycles=5):
    out = None
    for _ in range(cycles):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        out = dt if out is None or dt < out else out
    return out


def wire_cycle():
    blob = wire.encode_chunks(tree).join()
    decoded = wire.decode(bytes(blob))
    for arr in decoded.values():
        arr.ravel()[0]


merge_s = best(lambda: jax.block_until_ready(merge(parts)))
wire_s = best(wire_cycle)
print(json.dumps({"gspmd_loss_parity": parity,
                  "gspmd_exchange_speedup": wire_s / merge_s}))
"""


def _gspmd_probe():
    """ISSUE 15 gate: loss parity of the GSPMD path vs the fused
    single-device path (HARD — the bit-identity chain to the
    coordinator tier rests on it), plus the shm-wire-vs-psum exchange
    cycle ratio (report-only: wall-clock on a shared-core virtual
    mesh). Runs in a subprocess because the mesh needs the forced
    8-device CPU platform, which must be set before jax imports."""
    import subprocess
    import tempfile

    script = _GSPMD_PROBE % {"repo": HERE, "seed": SEED}
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(script)
        path = f.name
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    try:
        out = subprocess.run(
            [sys.executable, path], env=env, capture_output=True,
            text=True, timeout=600)
    finally:
        os.unlink(path)
    if out.returncode != 0:
        raise RuntimeError("gspmd probe failed:\n%s" % out.stderr[-3000:])
    return json.loads(out.stdout.strip().splitlines()[-1])


class _ProbePool(object):
    """A replica-pool stand-in with a fixed host-side service delay
    per batch: the serving probes below are SLEEP-dominated (like the
    input-pipeline probe) so their ratios are structural, not
    machine-speed. Results are computed with real numpy so the cache
    bit-identity contract stays honest."""

    def __init__(self, weights, delay_s=0.004, max_batch_size=8):
        import queue as _queue
        import threading as _threading
        self.max_batch_size = max_batch_size
        self._w = weights
        self._delay = delay_s
        self._queue = _queue.Queue()
        self._busy = 0
        self._stop = _threading.Event()
        self._thread = _threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

        class _Model(object):
            name = "probe"
            version = 1
            sample_shape = (weights.shape[0],)

        self.model = _Model()

    def _loop(self):
        import numpy
        while not self._stop.is_set():
            try:
                batch, on_done = self._queue.get(timeout=0.05)
            except Exception:
                continue
            self._busy = 1
            time.sleep(self._delay)          # the "forward"
            on_done(numpy.tanh(batch @ self._w), batch.shape[0], None)
            self._busy = 0

    def any_idle(self):
        return self._busy == 0 and self._queue.empty()

    def submit(self, batch, on_done):
        self._queue.put((batch, on_done))

    def stats(self):
        return [{"load": self._busy}]

    def size(self):
        return 1

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


def _serving_cache_probe(requests=200, hot=8, delay_s=0.004):
    """ISSUE 14 cache guard (hard): repeat-heavy traffic (``hot``
    distinct inputs, ``requests`` total) through the dynamic batcher
    with the result cache on vs off, against a fixed-delay service.
    Cache-off pays the delay per request; cache-on pays it ``hot``
    times — the ratio is ~requests/hot by construction, and collapses
    to ~1 if the consult-before-admission path silently breaks."""
    import numpy

    from veles_tpu.serving.cache import ResultCache
    from veles_tpu.serving.engine import DynamicBatcher

    rng = numpy.random.RandomState(SEED)
    weights = rng.rand(16, 4).astype(numpy.float32)
    rows = [rng.rand(16).astype(numpy.float32) for _ in range(hot)]

    def measure(cache):
        pool = _ProbePool(weights, delay_s=delay_s)
        batcher = DynamicBatcher(pool, batch_timeout_ms=0.0,
                                 max_queue=64, cache=cache)
        try:
            t0 = time.perf_counter()
            for i in range(requests):
                batcher.submit(rows[i % hot]).result(timeout=60)
            return time.perf_counter() - t0
        finally:
            batcher.stop()
            pool.stop()

    t_off = measure(None)
    t_on = measure(ResultCache(model="perf-gate"))
    return {"serving_cache_hit_speedup": t_off / max(t_on, 1e-9)}


def _sched_probe():
    """ISSUE 18 gate: the gang-scheduler contention bench in quick
    shape — a prod job preempts a preemptible research gang on a
    pool of one slot (checkpoint + SIGKILL + resume). The resumed
    job's loss curve vs the uninterrupted baseline is
    ``sched_loss_parity`` (HARD at exactly 1.0 — the determinism
    chain from ISSUE 12/13 checkpointing rests on it); the measured
    displacement time is ``sched_preempt_resume_s`` (report-only:
    sleep-paced but still wall-clock on a shared runner). Runs as a
    subprocess because the bench spawns its own worker gangs."""
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json",
                                     delete=False) as f:
        path = f.name
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(HERE, "scripts", "sched_bench.py"),
             "--quick", "--json", path],
            capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError("sched probe failed:\n%s"
                               % out.stderr[-3000:])
        with open(path) as f:
            summary = json.load(f)
    finally:
        os.unlink(path)
    return {"sched_preempt_resume_s":
            float(summary["sched_preempt_resume_s"]),
            "sched_loss_parity": float(summary["sched_loss_parity"])}


def _sched_restart_probe():
    """ISSUE 20 (report-only): the durable-scheduler chaos leg —
    SIGKILL a `sched serve --state-dir` subprocess mid-contention and
    restart it on the same dir. The bench hard-fails unless the
    surviving gang is adopted and both loss curves stay bit-equal to
    uninterrupted baselines; what the gate tracks is the measured
    restart -> serving-again wall time (journal replay + pid probe +
    adoption), which carries real python startup cost on a shared
    runner and so only reports."""
    import subprocess
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json",
                                     delete=False) as f:
        path = f.name
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(HERE, "scripts", "sched_bench.py"),
             "--quick", "--chaos", "sched-kill", "--json", path],
            capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError("sched restart probe failed:\n%s"
                               % out.stderr[-3000:])
        with open(path) as f:
            summary = json.load(f)
    finally:
        os.unlink(path)
    return {"sched_restart_recovery_s":
            float(summary["sched_restart_recovery_s"])}


def _sched_journal_probe(n_jobs=200):
    """ISSUE 20 (report-only): what the fsync'd write-ahead journal
    costs on the scheduler's bookkeeping path. Submits N jobs into a
    scheduler whose pool is fully blocked (placement never spawns —
    pure submit + journal-append work), with and without a state
    dir, and reports the wall ratio. Report-only: fsync latency is
    the filesystem's to decide on a shared runner."""
    import tempfile

    from veles_tpu.sched import JobSpec, Scheduler

    def measure(state_dir):
        sched = Scheduler(1, tick_s=3600.0, state_dir=state_dir)
        sched.pool.hold("blocker", 0, sched.pool.size)
        t0 = time.perf_counter()
        for i in range(n_jobs):
            sched.submit(JobSpec(
                name="journal-probe-%d" % i,
                argv=[sys.executable, "-c", "pass"],
                tenant="bench"))
        wall = time.perf_counter() - t0
        sched.stop()
        return wall

    t_memory = measure(None)
    with tempfile.TemporaryDirectory(prefix="sched-journal-") as d:
        t_journal = measure(d)
    return {"sched_journal_overhead_ratio":
            t_journal / max(t_memory, 1e-9)}


def _serving_elastic_probe(delay_s=0.01, backlog=120):
    """ISSUE 14 autoscale guard (report-only): a real replica pool on
    a tiny jitted model, flooded so the queue breaches; measured are
    the p95 of request completion under the burst and the autoscaler's
    breach -> warmed-replica reaction time. Report-only: both carry
    real compile/wall time and shared CI runners are noisy; the
    structural assertions live in tests/test_serving_elastic.py."""
    import numpy

    from veles_tpu.serving.autoscale import Autoscaler
    from veles_tpu.serving.engine import DynamicBatcher
    from veles_tpu.serving.model_store import ServeableModel
    from veles_tpu.serving.replica import ReplicaPool
    from veles_tpu.telemetry.registry import MetricsRegistry

    rng = numpy.random.RandomState(SEED)
    weights = rng.rand(64, 8).astype(numpy.float32)

    def apply(params, x):
        import jax.numpy as jnp
        return jnp.tanh(jnp.dot(x.reshape((x.shape[0], -1)),
                                params["w"]))

    model = ServeableModel([(apply, {"w": weights})], (64,),
                           name="probe")

    class _Slow(ServeableModel):
        def forward_fn(self):
            inner = ServeableModel.forward_fn(self)

            def forward(x):
                time.sleep(delay_s)     # traced once per bucket; the
                return inner(x)         # backlog outlives every trace

            return forward

    slow = _Slow(model.layers, model.sample_shape, name="probe")
    registry = MetricsRegistry()
    pool = ReplicaPool(slow, n_replicas=1, max_batch_size=4, warm=False)
    batcher = DynamicBatcher(pool, batch_timeout_ms=0.0, max_queue=1024)
    scaler = Autoscaler(pool, batcher, min_replicas=1, max_replicas=2,
                        up_queue_per_replica=8.0, up_for_s=0.05,
                        up_cooldown_s=0.0, interval_s=0.02,
                        registry=registry)
    try:
        xs = rng.rand(backlog, 64).astype(numpy.float32)
        t0 = time.perf_counter()
        futures = [batcher.submit(x) for x in xs]
        scaler.start()
        done_ms = []
        for f in futures:
            f.result(timeout=120)
            done_ms.append((time.perf_counter() - t0) * 1e3)
        hist = registry.get("veles_autoscale_reaction_s")
        child = hist.labels(model="default")
        reaction = child.sum / child.count if child.count else -1.0
    finally:
        scaler.stop()
        batcher.stop()
        pool.stop()
    done_ms.sort()
    return {"serving_burst_p95_ms":
            done_ms[int(0.95 * (len(done_ms) - 1))],
            "autoscale_reaction_s": reaction}


def capture():
    """Run the probe and return the snapshot dict."""
    from veles_tpu.telemetry import profiler
    from veles_tpu.telemetry.registry import get_registry

    wf, wall = _probe_workflow()
    history = wf.decision.epoch_history
    samples = sum(h["train"]["samples"] + h["validation"]["samples"]
                  for h in history)
    metrics = {
        "final_loss": float(history[-1]["validation"]["normalized"]),
        "epochs_completed": float(len(history)),
        "samples_per_sec": samples / wall if wall > 0 else 0.0,
    }
    cost = profiler.get_cost_book().cost("train_segment")
    if cost and cost.get("flops"):
        metrics["train_segment_gflop"] = cost["flops"] / 1e9
    step = get_registry().get("veles_step_ms")
    if step is not None:
        summary = {labels.get("phase"): child.summary()
                   for labels, child in step.series()}
        train = summary.get("train") or {}
        if train.get("p50") is not None:
            metrics["step_p50_ms"] = float(train["p50"])
    phases = profiler.phase_report()
    if phases.get("compile"):
        metrics["compile_ms"] = float(phases["compile"])
    rss = profiler.host_rss_bytes()
    if rss:
        metrics["host_rss_gb"] = rss / 2.0 ** 30
    metrics.update(_input_pipeline_probe())
    metrics.update(_offload_probe())
    metrics.update(_gspmd_probe())
    metrics.update(_federation_probe())
    metrics.update(_sched_federation_probe())
    metrics.update(_recovery_probe())
    metrics.update(_spmd_recovery_probe())
    metrics.update(_serving_cache_probe())
    metrics.update(_serving_elastic_probe())
    metrics.update(_sched_probe())
    metrics.update(_sched_restart_probe())
    metrics.update(_sched_journal_probe())
    return {"schema": "veles-perf-snapshot/1",
            "probe": {"samples": SAMPLES, "batch": BATCH,
                      "epochs": EPOCHS, "seed": SEED},
            "metrics": metrics}


def compare(snapshot, baseline):
    """``(failures, lines)``: hard regressions + the full report."""
    lines = []
    failures = []
    snap = snapshot.get("metrics", {})
    base = baseline.get("metrics", {})
    for name in sorted(base):
        policy = base[name]
        ref = float(policy["value"])
        tol = float(policy.get("tolerance", 0.1))
        direction = policy.get("direction", "higher")
        hard = policy.get("gate", "hard") == "hard"
        tag = "hard" if hard else "report"
        if name not in snap:
            line = "MISSING  %-22s baseline %.4g [%s]" % (name, ref, tag)
            if hard:
                failures.append(line)
            lines.append(line)
            continue
        new = float(snap[name])
        if direction == "higher":
            bound = ref * (1.0 - tol)
            regressed = new < bound
        else:
            bound = ref * (1.0 + tol)
            regressed = new > bound
        delta = (new - ref) / ref * 100.0 if ref else 0.0
        status = "REGRESS" if regressed else "ok"
        line = ("%-8s %-22s %.4g vs %.4g (%+.1f%%, %s is better, "
                "tol %.0f%%) [%s]"
                % (status, name, new, ref, delta, direction,
                   tol * 100.0, tag))
        lines.append(line)
        if regressed and hard:
            failures.append(line)
    for name in sorted(set(snap) - set(base)):
        lines.append("new      %-22s %.4g (no baseline policy)"
                     % (name, float(snap[name])))
    return failures, lines


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("snapshot", nargs="?",
                        help="snapshot JSON to compare (from --capture)")
    parser.add_argument("--capture", metavar="OUT",
                        help="run the probe and write the snapshot here")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline policy file (default %(default)s)")
    parser.add_argument("--update-baseline", metavar="SNAP",
                        help="rewrite the baseline's values from this "
                             "snapshot, keeping each metric's policy")
    args = parser.parse_args(argv)

    if args.capture:
        snapshot = capture()
        with open(args.capture, "w") as f:
            json.dump(snapshot, f, indent=1, sort_keys=True)
        print("perf snapshot -> %s" % args.capture)
        for name, value in sorted(snapshot["metrics"].items()):
            print("  %-22s %.4g" % (name, value))
        return 0

    if args.update_baseline:
        with open(args.update_baseline) as f:
            snapshot = json.load(f)
        with open(args.baseline) as f:
            baseline = json.load(f)
        for name, policy in baseline["metrics"].items():
            if name in snapshot["metrics"]:
                policy["value"] = round(
                    float(snapshot["metrics"][name]), 6)
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=1, sort_keys=True)
            f.write("\n")
        print("baseline values updated from %s -> %s"
              % (args.update_baseline, args.baseline))
        return 0

    if not args.snapshot:
        parser.error("need a snapshot to compare "
                     "(or --capture / --update-baseline)")
    with open(args.snapshot) as f:
        snapshot = json.load(f)
    with open(args.baseline) as f:
        baseline = json.load(f)
    failures, lines = compare(snapshot, baseline)
    print("perf gate: %s vs %s" % (args.snapshot, args.baseline))
    for line in lines:
        print("  " + line)
    if failures:
        print("PERF GATE FAILED: %d hard regression(s)" % len(failures))
        return 1
    print("perf gate passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
