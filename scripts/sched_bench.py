#!/usr/bin/env python3
"""Gang-scheduler contention bench (ISSUE 18): two tenants fight for
a pool of ONE device slot and the preempted tenant must lose NOTHING.

Leg ``baseline`` runs the elastic worker-demo uninterrupted and keeps
its loss curve. Leg ``contended`` starts a real in-process
``Scheduler`` over a single slot, submits the SAME demo as a
preemptible ``research`` job, waits for its generation-initial
checkpoint, then submits a short non-preemptible ``prod`` job — which
forces a genuine checkpoint + SIGKILL + resume cycle on the research
gang. Measured, not guessed:

* ``sched_preempt_resume_s`` — wall time the research job spent
  displaced (PREEMPTED -> RUNNING), the perf gate's report-only cost
  probe;
* ``sched_loss_parity`` — 1.0 iff the preempted job's final loss
  curve is BIT-IDENTICAL to the uninterrupted baseline (the ISSUE 18
  acceptance property, a HARD perf-gate metric at exactly 1.0).

The contended leg also pins the ISSUE 19 pane of glass: a
``SchedulerControl`` endpoint runs next to the scheduler, BOTH
tenants' live loss must federate onto its ``/metrics`` with
``{job,tenant}`` labels and land in ``/history.json``, and the
research job must resume under the SAME trace id it was submitted
with (the preemption window shows up as a gap in its history —
``sched_history_gap_s`` in the summary).

Scheduler state changes stream as ``EVENT`` markers on stderr in the
elastic supervisor's announce format, so a log reader can line this
bench up with `bench_distributed.py --chaos` output.

``--chaos sched-kill`` (ISSUE 20) runs the durability scenario
instead: the scheduler runs as a REAL subprocess (``python -m
veles_tpu sched serve --state-dir``), the same two-tenant contention
is staged through its HTTP control endpoint, and then the scheduler
process is SIGKILLed while the research job sits PREEMPTED and the
prod gang is mid-epoch. A replacement serve on the SAME state dir and
SAME port must adopt the surviving prod gang without killing it
(same job id, still RUNNING, ``veles_sched_gangs_adopted_total``
moves), resume the research job under its original trace id, and
finish BOTH jobs with loss curves bit-identical to uninterrupted
baselines. The restart -> serving wall time is the summary's
``sched_restart_recovery_s`` (report-only in the perf gate).

Prints one JSON line per leg and a ``summary`` line the perf gate and
`bench_all.py` consume.

Usage::

    JAX_PLATFORMS=cpu python scripts/sched_bench.py [--epochs 4]
        [--epoch-sleep 0.4] [--quick] [--json OUT]
        [--chaos sched-kill]
"""

import argparse
import json
import logging
import os
import re
import signal
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

logging.disable(logging.WARNING)

T0 = time.time()


def announce(name, **fields):
    print("EVENT %s t=%.6f %s"
          % (name, time.time() - T0,
             " ".join("%s=%s" % kv for kv in sorted(fields.items()))),
          file=sys.stderr, flush=True)


def worker_env():
    # the demo workers must see ONE CPU device in every leg so the
    # curves are comparable bit-for-bit
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [HERE] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p])
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return env


def demo_argv(out, epochs, epoch_sleep=0.0):
    argv = [sys.executable, "-m", "veles_tpu.parallel.elastic",
            "worker-demo", "--out", out, "--epochs", str(epochs)]
    if epoch_sleep:
        argv += ["--epoch-sleep", str(epoch_sleep)]
    return argv


def http_get(port, path):
    from urllib.request import urlopen
    url = "http://127.0.0.1:%d%s" % (port, path)
    with urlopen(url, timeout=5.0) as resp:
        return resp.read().decode("utf-8")


def job_row(port, job_id):
    for row in json.loads(http_get(port, "/jobs.json"))["jobs"]:
        if row.get("id") == job_id:
            return row
    raise SystemExit("/jobs.json lost job %s" % job_id)


def wait_for_live_loss(port, job_id, tenant, timeout_s=240.0):
    """Block until the scheduler's OWN /metrics shows the job's
    federated live loss with {job,tenant} labels (ISSUE 19)."""
    needle = ('veles_sched_job_loss{job="%s",tenant="%s"}'
              % (job_id, tenant))
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if needle in http_get(port, "/metrics"):
            announce("sched_live_loss", job=job_id, tenant=tenant)
            return
        time.sleep(0.1)
    raise SystemExit("scheduler /metrics never showed %s" % needle)


def wait_for_manifest(snaps, timeout_s=240.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        for dirpath, _, files in os.walk(snaps):
            if "MANIFEST.json" in files:
                return dirpath
        time.sleep(0.1)
    raise SystemExit("no checkpoint manifest appeared in %s" % snaps)


def http_post(port, path, payload):
    from urllib.request import Request, urlopen
    req = Request("http://127.0.0.1:%d%s" % (port, path),
                  data=json.dumps(payload).encode("utf-8"),
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=10.0) as resp:
        return json.loads(resp.read().decode("utf-8"))


def wait_for_state(port, job_id, want, timeout_s=240.0):
    terminal = ("done", "failed")
    deadline = time.monotonic() + timeout_s
    row = None
    while time.monotonic() < deadline:
        row = job_row(port, job_id)
        if row["state"] == want:
            return row
        if row["state"] in terminal and want not in terminal:
            raise SystemExit(
                "job %s went %s while waiting for %s (error=%r)"
                % (job_id, row["state"], want, row.get("error")))
        time.sleep(0.05)
    raise SystemExit("job %s never reached %s (last state %r)"
                     % (job_id, want, row and row["state"]))


def metric_total(port, family):
    """Sum a counter family off the scheduler's /metrics text."""
    total = 0.0
    pattern = re.compile(
        r"^%s(?:\{[^}]*\})? ([0-9.eE+-]+)$" % re.escape(family))
    for line in http_get(port, "/metrics").splitlines():
        m = pattern.match(line)
        if m:
            total += float(m.group(1))
    return total


def spawn_serve(state_dir, log_dir, addr, env, errlog):
    """Start ``sched serve`` as a real subprocess and block until its
    SCHED announce line — printed only after journal replay and gang
    adoption finished, so returning == the control plane serves 200s."""
    argv = [sys.executable, "-m", "veles_tpu", "sched", "serve",
            "--pool", "1", "--tick-s", "0.05", "--min-run-s", "0.5",
            "--addr", addr, "--log-dir", log_dir,
            "--state-dir", state_dir]
    proc = subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=errlog, text=True)
    line = proc.stdout.readline()
    if not line.startswith("SCHED "):
        proc.kill()
        proc.wait()
        raise SystemExit("sched serve never announced (got %r); see %s"
                         % (line, getattr(errlog, "name", "stderr")))
    host, _, port = line.split()[1].rpartition(":")
    return proc, int(port)


def run_chaos_sched_kill(workdir, epochs, epoch_sleep, env):
    """SIGKILL the scheduler process mid-contention; the restart on
    the same state dir must adopt, resume, and change NO math."""
    # the prod gang must outlive the scheduler outage (kill + python
    # startup + replay) or there is nothing left to adopt — pace it
    # with a generous per-epoch sleep (no RNG impact on the curve)
    prod_epochs, prod_sleep = 2, 4.0
    env = dict(env)
    env["VELES_SCHED_METRICS_S"] = "0.1"
    state_dir = os.path.join(workdir, "state")
    log_dir = os.path.join(workdir, "logs")
    snaps = os.path.join(workdir, "snaps")
    research_out = os.path.join(workdir, "research.json")
    prod_out = os.path.join(workdir, "prod.json")
    base_research = os.path.join(workdir, "base-research.json")
    base_prod = os.path.join(workdir, "base-prod.json")

    announce("sched_chaos_baselines")
    for out, n, sleep_s in ((base_research, epochs, epoch_sleep),
                            (base_prod, prod_epochs, prod_sleep)):
        proc = subprocess.run(demo_argv(out, n, sleep_s), env=env,
                              capture_output=True, timeout=600)
        if proc.returncode != 0:
            raise SystemExit(
                "chaos baseline failed:\n%s"
                % proc.stderr.decode(errors="replace")[-3000:])

    errlog = open(os.path.join(workdir, "serve.log"), "ab")
    t0 = time.time()
    proc, port = spawn_serve(state_dir, log_dir, "127.0.0.1:0", env,
                             errlog)
    announce("sched_chaos_serve", port=port)
    recovery_s = None
    try:
        research_id = http_post(port, "/submit", {
            "name": "research-train", "tenant": "research",
            "argv": demo_argv(research_out, epochs, epoch_sleep),
            "snapshot_dir": snaps})["id"]
        announce("sched_submit", job=research_id, tenant="research",
                 preemptible=True)
        wait_for_manifest(snaps)
        wait_for_live_loss(port, research_id, "research")
        trace_before = job_row(port, research_id).get("trace_id")
        prod_id = http_post(port, "/submit", {
            "name": "prod-train", "tenant": "prod",
            "argv": demo_argv(prod_out, prod_epochs, prod_sleep)})["id"]
        announce("sched_submit", job=prod_id, tenant="prod",
                 preemptible=False)
        # the kill lands at the worst moment: the research gang is
        # displaced (nothing running to carry its state) and the prod
        # gang is alive mid-epoch (everything to lose by a re-spawn)
        wait_for_state(port, research_id, "preempted")
        wait_for_state(port, prod_id, "running")
        wait_for_live_loss(port, prod_id, "prod")
        announce("sched_kill", pid=proc.pid)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()

        t_restart = time.time()
        proc, port = spawn_serve(state_dir, log_dir,
                                 "127.0.0.1:%d" % port, env, errlog)
        recovery_s = time.time() - t_restart
        announce("sched_recovered", recovery_s="%.3f" % recovery_s)
        adopted = metric_total(port, "veles_sched_gangs_adopted_total")
        if adopted < 1:
            raise SystemExit("restarted scheduler adopted no gangs "
                             "(veles_sched_gangs_adopted_total=%s)"
                             % adopted)
        prod_row = job_row(port, prod_id)
        if prod_row["state"] != "running":
            raise SystemExit(
                "prod gang did not survive the restart as an adopted "
                "RUNNING job: %r" % prod_row)
        research_row = job_row(port, research_id)
        if research_row.get("trace_id") != trace_before:
            raise SystemExit(
                "research job changed trace id across the scheduler "
                "restart: %r -> %r"
                % (trace_before, research_row.get("trace_id")))
        wait_for_state(port, prod_id, "done", timeout_s=600)
        wait_for_state(port, research_id, "done", timeout_s=600)
        if job_row(port, research_id).get("trace_id") != trace_before:
            raise SystemExit("research trace id changed after resume")
    finally:
        if proc.poll() is None:
            proc.terminate()
            proc.wait()
        errlog.close()
    wall = time.time() - t0

    parity = 1.0
    for out, base in ((research_out, base_research),
                      (prod_out, base_prod)):
        with open(out) as f:
            curve = json.load(f)
        with open(base) as f:
            base_curve = json.load(f)
        if curve != base_curve:
            parity = 0.0
    row = {"leg": "chaos-sched-kill", "wall_s": round(wall, 2),
           "restart_recovery_s": round(recovery_s, 3),
           "gangs_adopted": adopted,
           "loss_parity": parity,
           "trace_id": trace_before}
    print(json.dumps(row), flush=True)
    return row


def run_baseline(out, epochs, epoch_sleep, env):
    announce("sched_baseline_start", epochs=epochs)
    t0 = time.time()
    proc = subprocess.run(demo_argv(out, epochs, epoch_sleep), env=env,
                          capture_output=True, timeout=600)
    if proc.returncode != 0:
        raise SystemExit("baseline demo failed:\n%s"
                         % proc.stderr.decode(errors="replace")[-3000:])
    row = {"leg": "baseline", "epochs": epochs,
           "wall_s": round(time.time() - t0, 2)}
    print(json.dumps(row), flush=True)
    return row


def run_contended(workdir, epochs, epoch_sleep, env):
    from veles_tpu.sched import (DONE, JobSpec, Scheduler,
                                 SchedulerControl)

    snaps = os.path.join(workdir, "snaps")
    research_out = os.path.join(workdir, "research.json")
    prod_out = os.path.join(workdir, "prod.json")
    log_dir = os.path.join(workdir, "logs")

    # fast rollup pushes so the one-pane assertions land well inside
    # the bench window (the knob only matters when the scheduler set
    # VELES_SCHED_METRICS_URL, so the baseline leg is untouched)
    env = dict(env)
    env["VELES_SCHED_METRICS_S"] = "0.1"

    t0 = time.time()
    sched = Scheduler(1, tick_s=0.05, min_run_s=0.5,
                      log_dir=log_dir).start()
    control = SchedulerControl(sched).start()
    port = control.address[1]
    try:
        research = sched.submit(JobSpec(
            name="research-train",
            argv=demo_argv(research_out, epochs, epoch_sleep),
            tenant="research", snapshot_dir=snaps, env=env))
        announce("sched_submit", job=research.id, tenant="research",
                 preemptible=True)
        # the preemption must be a genuine checkpoint + restore, not
        # a fresh rebuild: wait for the generation-initial manifest
        wait_for_manifest(snaps)
        announce("sched_checkpoint", job=research.id)
        # ISSUE 19: the research gang's loss must reach the pane of
        # glass BEFORE the preemption, so the trace id captured here
        # can be compared against the resumed job afterwards
        wait_for_live_loss(port, research.id, "research")
        trace_before = job_row(port, research.id).get("trace_id")
        # two epochs + a sleep: prod must still be RUNNING after its
        # first loss lands, or the live /metrics check has no window
        prod = sched.submit(JobSpec(
            name="prod-train",
            argv=demo_argv(prod_out, 2, epoch_sleep=0.4),
            tenant="prod", env=env))
        announce("sched_submit", job=prod.id, tenant="prod",
                 preemptible=False)
        wait_for_live_loss(port, prod.id, "prod")
        states = sched.wait([research.id, prod.id], timeout_s=600)
        trace_after = job_row(port, research.id).get("trace_id")
        history = json.loads(http_get(
            port, "/history.json?series=veles_sched_job_loss"))
    finally:
        control.stop()
        sched.stop(kill=True)
    wall = time.time() - t0

    if not trace_before or trace_after != trace_before:
        raise SystemExit(
            "research job changed trace id across the preemption: "
            "%r -> %r" % (trace_before, trace_after))
    loss_points = {s["labels"].get("job"): s["points"]
                   for s in history["series"]
                   if s["name"] == "veles_sched_job_loss"}
    for jid, tenant in ((research.id, "research"), (prod.id, "prod")):
        if not loss_points.get(jid):
            raise SystemExit("no loss history for %s job %s"
                             % (tenant, jid))
    # the preemption window must be VISIBLE in the victim's history:
    # the store never interpolates, so the displacement shows up as
    # the widest inter-point gap (reported, pinned by test_sched.py)
    stamps = [p[0] for p in loss_points[research.id]]
    gap_s = max((b - a for a, b in zip(stamps, stamps[1:])),
                default=0.0)

    if states != {research.id: DONE, prod.id: DONE}:
        tails = []
        if os.path.isdir(log_dir):
            for name in sorted(os.listdir(log_dir)):
                with open(os.path.join(log_dir, name), "rb") as f:
                    tails.append("%s:\n%s" % (
                        name,
                        f.read().decode(errors="replace")[-2000:]))
        raise SystemExit("contended leg did not converge: %r\n%s"
                         % (states, "\n".join(tails)))
    announce("sched_done", preemptions=research.preemptions,
             resume_s="%.3f" % (research.preempt_resume_s or 0.0))
    row = {"leg": "contended", "epochs": epochs,
           "wall_s": round(wall, 2),
           "preemptions": research.preemptions,
           "prod_preemptions": prod.preemptions,
           "preempt_resume_s": round(research.preempt_resume_s or 0.0,
                                     3),
           "trace_id": trace_before,
           "history_gap_s": round(gap_s, 3),
           "research_out": research_out}
    print(json.dumps(row), flush=True)
    return row


def main():
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--epochs", type=int, default=4,
                        help="research-job epochs (baseline matches)")
    parser.add_argument("--epoch-sleep", type=float, default=0.4,
                        help="injected per-epoch sleep — the window "
                             "the prod job preempts into (no RNG "
                             "impact, curves stay comparable)")
    parser.add_argument("--quick", action="store_true",
                        help="CI smoke shape: 3 epochs")
    parser.add_argument("--json", metavar="OUT",
                        help="also write the summary JSON here")
    parser.add_argument("--chaos", choices=("sched-kill",),
                        help="run the durability scenario instead: "
                             "SIGKILL the scheduler subprocess "
                             "mid-contention, restart on the same "
                             "state dir, assert adoption + parity")
    args = parser.parse_args()
    if args.quick:
        args.epochs = min(args.epochs, 3)

    env = worker_env()
    if args.chaos == "sched-kill":
        with tempfile.TemporaryDirectory(
                prefix="sched-chaos-") as workdir:
            chaos = run_chaos_sched_kill(workdir, args.epochs,
                                         args.epoch_sleep, env)
        summary = {
            "leg": "summary", "chaos": "sched-kill",
            "epochs": args.epochs,
            "sched_restart_recovery_s": chaos["restart_recovery_s"],
            "sched_gangs_adopted": chaos["gangs_adopted"],
            "sched_chaos_loss_parity": chaos["loss_parity"],
        }
        print(json.dumps(summary), flush=True)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(summary, f, indent=1, sort_keys=True)
                f.write("\n")
        if chaos["loss_parity"] != 1.0:
            raise SystemExit(
                "the scheduler restart changed the math: a resumed "
                "curve differs from its uninterrupted baseline")
        return 0
    with tempfile.TemporaryDirectory(prefix="sched-bench-") as workdir:
        base_out = os.path.join(workdir, "baseline.json")
        run_baseline(base_out, args.epochs, args.epoch_sleep, env)
        contended = run_contended(workdir, args.epochs,
                                  args.epoch_sleep, env)
        with open(base_out) as f:
            base_curve = json.load(f)
        with open(contended["research_out"]) as f:
            research_curve = json.load(f)

    parity = 1.0 if research_curve == base_curve else 0.0
    summary = {
        "leg": "summary", "epochs": args.epochs,
        "preemptions": contended["preemptions"],
        "sched_preempt_resume_s": contended["preempt_resume_s"],
        "sched_loss_parity": parity,
        "sched_history_gap_s": contended["history_gap_s"],
    }
    print(json.dumps(summary), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
    if contended["preemptions"] < 1:
        raise SystemExit("prod job never preempted the research gang "
                         "— the contention scenario did not happen")
    if parity != 1.0:
        raise SystemExit(
            "preemption changed the math: the resumed curve differs "
            "from the uninterrupted baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
