#!/usr/bin/env python3
"""Measured perf for every BASELINE config + the beyond-parity units
(VERDICT r4 next #2 — the reference imposed the same discipline on
itself via DeviceBenchmark, ``veles/accelerated_units.py:706-824``).

One row per compute path: steady-state training samples/s on the chip
with bench.py's read-free timed-window discipline (warm segments pay
the compile, then chunked compiled segments with ONE forcing read per
chunk), plus analytic model TFLOP/s against the chip's measured
large-matmul peak (MFU). bench.py stays the driver's AlexNet contract;
this script is the breadth table committed in docs/PERF.md.

MFU is matmul-FLOPs-only (the scaling-book convention bench.py uses):
configs dominated by tiny matmuls (FC-100, SOM 8x8) honestly report
single-digit MFU — they are latency/bandwidth bound, which is the
point of publishing them.

Usage: python scripts/bench_all.py [config ...]  (default: all)
Prints one markdown row per config on stdout, diagnostics on stderr.
"""

import json
import logging
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

logging.disable(logging.WARNING)

MIN_WINDOW_S = float(os.environ.get("VELES_BENCH_ALL_WINDOW", 10.0))
PRECISION = os.environ.get("VELES_BENCH_PRECISION", "bfloat16")


def _seed():
    from veles_tpu import prng
    prng.get().seed(1234)
    prng.get("loader").seed(1235)


def _bench_fused(wf):
    """Steady samples/s with bench.py's shared disciplines
    (prepare_segment_run pays compile + settle, then the timed
    window). Returns (samples_per_sec, (step_p50_ms, step_p95_ms)) —
    the step tail comes from the telemetry registry histogram the
    window feeds."""
    import bench

    from veles_tpu.telemetry.registry import get_registry
    from veles_tpu.train import FusedTrainer
    trainer = FusedTrainer(wf)
    params, states, idx, keys = bench.prepare_segment_run(
        trainer, warm=2, seed=0)
    step_hist = get_registry().histogram("veles_bench_step_ms")
    step_hist.reset()  # one config's tail must not leak into the next
    params, states, segs, elapsed, _ = bench.timed_segment_window(
        trainer, params, states, idx, keys, MIN_WINDOW_S)
    step = step_hist.labels()
    mb = trainer.workflow.loader.max_minibatch_size
    valid = (idx >= 0).sum() / idx.shape[0] / mb  # fill fraction
    return (segs * idx.shape[0] * mb * float(valid) / elapsed,
            (step.percentile(50), step.percentile(95)))


# -- config builders -------------------------------------------------------


def build_fc():
    from veles_tpu.datasets import golden_digits
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist import MnistWorkflow
    _seed()
    return MnistWorkflow(DummyLauncher(),
                         provider=golden_digits(n_train=12000,
                                                n_valid=2000),
                         layers=(100,), minibatch_size=500,
                         max_epochs=1)


def build_conv():
    from veles_tpu.datasets import golden_digits
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist import MnistLoader
    from veles_tpu.models.parity import CONV_LAYERS
    from veles_tpu.standard_workflow import StandardWorkflow
    _seed()
    return StandardWorkflow(
        DummyLauncher(),
        loader=lambda w: MnistLoader(
            w, provider=golden_digits(n_train=12000, n_valid=2000),
            flatten=False, minibatch_size=250),
        layers=CONV_LAYERS, loss="softmax", max_epochs=1)


def build_cifar():
    from veles_tpu.datasets import golden_objects
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.cifar import CifarWorkflow
    _seed()
    return CifarWorkflow(DummyLauncher(),
                         provider=golden_objects(n_train=10000,
                                                 n_valid=2000),
                         minibatch_size=250, max_epochs=1)


def build_ae():
    from veles_tpu.datasets import golden_digits
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist_ae import MnistAEWorkflow
    _seed()
    return MnistAEWorkflow(DummyLauncher(),
                           provider=golden_digits(n_train=12000,
                                                  n_valid=2000),
                           bottleneck=100, minibatch_size=500,
                           learning_rate=0.001, max_epochs=1)


def build_attention():
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.samples import (SequenceProvider,
                                          SequenceWorkflow)
    _seed()
    return SequenceWorkflow(
        DummyLauncher(),
        provider=SequenceProvider(n_train=4096, n_valid=256,
                                  seq=256, dim=256),
        minibatch_size=64, heads=8, max_epochs=1)


def build_moe():
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.samples import (SequenceProvider,
                                          SequenceWorkflow)
    _seed()
    return SequenceWorkflow(
        DummyLauncher(),
        provider=SequenceProvider(n_train=4096, n_valid=256,
                                  seq=128, dim=256),
        minibatch_size=64, heads=8, moe=True, n_experts=8,
        max_epochs=1)


def bench_som():
    """SOM has no GD chain: time the jitted batch update directly —
    that IS config 4's training compute path (nn/kohonen.py)."""
    import jax
    import jax.numpy as jnp
    import numpy

    from veles_tpu.nn.kohonen import _make_grid, _som_update

    sx = sy = 8
    features = 784
    batch = 1024
    rng = numpy.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, features).astype(numpy.float32))
    codebook = jnp.asarray(
        rng.rand(sx * sy, features).astype(numpy.float32) * 0.2 - 0.1)
    grid = jnp.asarray(_make_grid(sx, sy))
    sigma, lr = numpy.float32(2.0), numpy.float32(0.1)

    codebook, win = _som_update(codebook, x, grid, sigma, lr)
    win.block_until_ready()  # compile
    steps = 0
    start = time.time()
    while True:
        for _ in range(50):
            codebook, win = _som_update(codebook, x, grid, sigma, lr)
        win.block_until_ready()
        steps += 50
        elapsed = time.time() - start
        if elapsed >= MIN_WINDOW_S:
            break
    rate = steps * batch / elapsed
    # two (batch x units x features) dots per update
    flops = 4.0 * sx * sy * features
    return rate, flops, "Kohonen 8x8 SOM (batch 1024)"


CONFIGS = {
    "fc": (build_fc, "MNIST FC 784-100-10 (config 1, batch 500)"),
    "conv": (build_conv,
             "MNIST conv 16c5-32c5 (config 2 analog, batch 250)"),
    "cifar": (build_cifar,
              "CIFAR cifar10-quick (config 2, batch 250)"),
    "ae": (build_ae, "MNIST AE 784-100-784 (config 4, batch 500)"),
    "attention": (build_attention,
                  "attention 2L seq=256 d=256 h=8 (batch 64)"),
    "moe": (build_moe,
            "attention+MoE 8 experts seq=128 d=256 (batch 64)"),
}


def main():
    from veles_tpu.backends import Device
    from veles_tpu.nn.precision import set_policy

    import bench  # repo-root bench.py: shared matmul-peak measurement

    names = sys.argv[1:] or list(CONFIGS) + [
        "som", "serving", "serving-cache", "serving-burst", "offload",
        "sched"]
    set_policy(PRECISION)
    peak = bench.measured_matmul_peak_tflops()
    print("chip matmul peak: %.1f TF/s, policy=%s, window>=%.0fs"
          % (peak, PRECISION, MIN_WINDOW_S), file=sys.stderr)

    print("| Config | samples/s | model GFLOP/sample | eff TFLOP/s "
          "| MFU | step p50/p95 ms |")
    print("|---|---|---|---|---|---|")
    for name in names:
        t0 = time.time()
        if name == "serving" or name.startswith("serving-"):
            # the serving engine has its own metric shape (QPS vs the
            # legacy path, not samples/s vs MFU) — delegate and print
            # its row verbatim after the table. "serving" is the
            # ISSUE 3 baseline; "serving-{cache,burst,diurnal,
            # multitenant}" are the ISSUE 14 elastic-plane scenarios
            import bench_serving
            scenario = name[len("serving-"):] if "-" in name \
                else "baseline"
            result = bench_serving.SCENARIOS[scenario](quick=True)
            print(bench_serving.markdown_row(result), flush=True)
            print("%s: %s in %.0fs total"
                  % (name, "PASS" if result["pass"] else "FAIL",
                     time.time() - t0), file=sys.stderr)
            continue
        if name == "offload":
            # the out-of-core model-state bench (ISSUE 17) has its own
            # metric shape (transfer-wait ratio vs samples/s) —
            # delegate like the serving scenarios and echo its summary
            import subprocess
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(HERE, "scripts", "offload_bench.py"),
                 "--transfer-ms", "12", "--epochs", "1"],
                capture_output=True, text=True)
            summary = next(
                (line for line in proc.stdout.splitlines()[::-1]
                 if '"summary"' in line), proc.stdout.strip())
            print(summary, flush=True)
            print("%s: %s in %.0fs total"
                  % (name, "PASS" if proc.returncode == 0 else "FAIL",
                     time.time() - t0), file=sys.stderr)
            continue
        if name == "sched":
            # the gang-scheduler contention bench (ISSUE 18): its
            # verdicts are preempt->resume seconds and a loss-parity
            # bit (not samples/s) — delegate and echo the summary
            import subprocess
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(HERE, "scripts", "sched_bench.py"),
                 "--quick"],
                capture_output=True, text=True)
            summary = next(
                (line for line in proc.stdout.splitlines()[::-1]
                 if '"summary"' in line), proc.stdout.strip())
            print(summary, flush=True)
            print("%s: %s in %.0fs total"
                  % (name, "PASS" if proc.returncode == 0 else "FAIL",
                     time.time() - t0), file=sys.stderr)
            continue
        if name == "som":
            rate, flops, label = bench_som()
            step_tail = None  # no segment histogram on the SOM path
        else:
            build, label = CONFIGS[name]
            wf = build()
            wf.initialize(device=Device(backend=None))
            flops = bench.model_train_flops_per_sample(wf)
            rate, step_tail = _bench_fused(wf)
        eff = rate * flops / 1e12
        tail = ("%.1f / %.1f" % step_tail if step_tail else "—")
        print("| %s | **%s** | %.4f | %.2f | %.1f%% | %s |"
              % (label,
                 ("{:,.0f}".format(rate)), flops / 1e9, eff,
                 100.0 * eff / peak, tail), flush=True)
        print("%s: %.1f samples/s in %.0fs total"
              % (name, rate, time.time() - t0), file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
