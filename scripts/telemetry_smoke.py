#!/usr/bin/env python3
"""Telemetry smoke (ISSUE 4, wired into the tier-1 CI workflow).

Drives the REAL surfaces end-to-end, cheaply:

1. trains the tiny parity-shaped model through the actual CLI with
   ``--trace-out`` and asserts the dump is valid Chrome trace-event
   JSON (the thing Perfetto/chrome://tracing loads) containing step
   and workflow spans;
2. starts a web_status dashboard and asserts ``GET /metrics`` returns
   Prometheus text with at least one counter, and ``/metrics.json``
   and ``/profile.json`` (ISSUE 7: the attribution report) parse;
3. with ``--flight``: trains the same tiny model with a NaN injected
   into the training data and asserts the flight recorder left a
   loadable record naming the offending sweep (the CI smoke for the
   black box — this mode runs INSTEAD of the default checks);
4. with ``--cluster`` (ISSUE 9): starts a master + 2 in-process slaves
   and a dashboard, scrapes the FEDERATED ``/metrics`` +
   ``/cluster.json`` and asserts per-slave series are present while
   the slaves live and garbage-collected after a clean disconnect
   (this mode also runs INSTEAD of the default checks);
5. with ``--sched`` (ISSUE 19): starts a gang scheduler + 2 one-worker
   gangs under different tenants, asserts both jobs' live loss lands
   on the scheduler ``/metrics`` with ``{job,tenant}`` labels and in
   ``/history.json``, then SIGKILLs one gang and asserts its
   ``sched_job_failed`` flight record carries the job's trace id
   (also INSTEAD of the default checks).

Exit code 0 = the exercised surfaces are alive. Runs on CPU in a few
seconds.
"""

import json
import os
import subprocess
import sys
import tempfile
import urllib.request

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

WORKFLOW = """
import numpy
from veles_tpu.models.mnist import MnistWorkflow


class TinyProvider(object):
    def __call__(self):
        rng = numpy.random.RandomState(0)
        x = rng.rand(80, 6, 6).astype(numpy.float32)
        y = (x.reshape(80, -1).sum(1) > 18).astype(numpy.int32)
        return x[:60], y[:60], x[60:], y[60:]


def run(load, main):
    load(MnistWorkflow, provider=TinyProvider(), layers=(8,),
         minibatch_size=20, max_epochs=2)
    main()
"""


def check_trace(tmpdir):
    wf_path = os.path.join(tmpdir, "smoke_workflow.py")
    with open(wf_path, "w") as f:
        f.write(WORKFLOW)
    trace_path = os.path.join(tmpdir, "trace.json")
    env = dict(os.environ, PYTHONPATH=HERE, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu", wf_path, "-s", "7",
         "--trace-out", trace_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        timeout=600)
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == 0, "CLI run failed:\n" + out[-2000:]
    with open(trace_path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert isinstance(events, list) and events, "empty trace"
    for event in events:
        if event.get("ph") == "M":
            continue
        missing = {"ph", "ts", "pid", "tid"} - set(event)
        assert not missing, "event missing %s: %r" % (missing, event)
    names = {e["name"] for e in events}
    assert any(n.startswith("step:") for n in names), names
    assert any(n.startswith("workflow:") or n.startswith("epoch")
               for n in names), names
    print("trace-out OK: %d events, %d distinct span names"
          % (len(events), len(names)))


def check_web_status():
    from veles_tpu.web_status import WebStatusServer
    server = WebStatusServer(host="127.0.0.1", port=0).start()
    try:
        base = "http://127.0.0.1:%d" % server.port
        with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
            assert resp.status == 200
            assert "text/plain" in resp.headers["Content-Type"]
            text = resp.read().decode()
        counters = [line for line in text.splitlines()
                    if not line.startswith("#") and
                    line.startswith("veles_")]
        assert counters, "no counters exposed:\n" + text
        with urllib.request.urlopen(base + "/metrics.json",
                                    timeout=5) as resp:
            snap = json.load(resp)
        assert snap["counters"], snap
        with urllib.request.urlopen(base + "/profile.json",
                                    timeout=5) as resp:
            profile = json.load(resp)
        for key in ("ops", "phases_ms", "memory", "step_mfu"):
            assert key in profile, profile.keys()
        print("web_status /metrics OK: %d series lines; /profile.json "
              "OK: %d op rows, phases %s"
              % (len(counters), len(profile["ops"]),
                 list(profile["phases_ms"])))
    finally:
        server.stop()


NAN_WORKFLOW = WORKFLOW.replace(
    "return x[:60], y[:60], x[60:], y[60:]",
    "x[5, 0, 0] = numpy.nan  # first train sweep goes non-finite\n"
    "        return x[:60], y[:60], x[60:], y[60:]")


def check_flight_record(tmpdir):
    wf_path = os.path.join(tmpdir, "nan_workflow.py")
    with open(wf_path, "w") as f:
        f.write(NAN_WORKFLOW)
    flight_dir = os.path.join(tmpdir, "flight")
    env = dict(os.environ, PYTHONPATH=HERE, JAX_PLATFORMS="cpu",
               VELES_FLIGHT_DIR=flight_dir)
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu", wf_path, "-s", "7"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        timeout=600)
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == 0, "CLI run failed:\n" + out[-2000:]
    records = sorted(os.listdir(flight_dir)) if \
        os.path.isdir(flight_dir) else []
    jsons = [r for r in records if r.endswith(".json")]
    assert jsons, "no flight record written; run output:\n" + out[-2000:]
    from veles_tpu.telemetry import flight
    record = flight.load_record(os.path.join(flight_dir, jsons[0]))
    assert record["reason"].startswith("non_finite"), record["reason"]
    assert "step" in record["context"], record["context"]
    print("flight record OK: %s (%s) naming %r"
          % (jsons[0], record["reason"], record["context"]["step"]))


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=5) as resp:
        assert resp.status == 200, (path, resp.status)
        return resp.read().decode()


def check_cluster():
    import threading
    import time

    import numpy

    from veles_tpu import prng
    from veles_tpu.launcher import Launcher
    from veles_tpu.models.mnist import MnistWorkflow
    from veles_tpu.telemetry import federation
    from veles_tpu.web_status import WebStatusServer

    def provider():
        rng = numpy.random.RandomState(0)
        x = rng.rand(120, 6, 6).astype(numpy.float32)
        y = (x.reshape(120, -1).sum(1) > 18).astype(numpy.int32)
        return x[:100], y[:100], x[100:], y[100:]

    def make(launcher):
        return MnistWorkflow(launcher, provider=provider, layers=(8,),
                             minibatch_size=20, max_epochs=2)

    prng.get().seed(42)
    prng.get("loader").seed(43)
    master = Launcher(listen_address="127.0.0.1:0", graphics=False)
    make(master)
    master.initialize()
    port = master._server.address[1]
    slaves = []
    for _ in range(2):
        prng.get().seed(42)
        prng.get("loader").seed(43)
        slave = Launcher(master_address="127.0.0.1:%d" % port,
                         graphics=False, eager=True,
                         heartbeat_interval=0.1)
        make(slave)
        slave.initialize()
        slaves.append(slave)
    sids = sorted(s._client.id for s in slaves)

    dashboard = WebStatusServer(host="127.0.0.1", port=0).start()
    base = "http://127.0.0.1:%d" % dashboard.port
    try:
        # slaves heartbeat from initialize() on — wait for both feeds
        deadline = time.time() + 30
        while sorted(federation.get_federation().slaves()) != sids:
            assert time.time() < deadline, \
                "slave feeds never arrived: %s" \
                % federation.get_federation().slaves()
            time.sleep(0.05)
        # the master's OWN per-slave families (RTT, exchange, job
        # times) outlive a clean disconnect by design — end-of-run
        # snapshots still read them. Only series the slaves PUSHED
        # (the federated feed) must appear now and vanish on GC.
        master_prefixes = ("veles_slave_", "veles_exchange_",
                           "veles_jobs_total", "veles_job_source_ms",
                           "veles_result_sink_ms",
                           "veles_cluster_flight_notices_total")

        def federated_lines(text, sid):
            return [line for line in text.splitlines()
                    if 'slave="%s"' % sid in line and
                    not line.startswith(master_prefixes)]

        text = _get(base, "/metrics")
        for sid in sids:
            assert federated_lines(text, sid), \
                "no federated series for %s:\n%s" % (sid, text[:2000])
        cluster = json.loads(_get(base, "/cluster.json"))
        assert sorted(cluster["slaves"]) == sids, cluster
        for sid in sids:
            assert cluster["slaves"][sid]["telemetry"]["seq"] >= 1
        assert cluster["run"].get("trace_id") == master._server.trace_id
        print("cluster view OK: 2 slave feeds federated, "
              "/cluster.json lists %s" % ", ".join(sids))

        # run the tiny distributed job to completion, then the clean
        # disconnects must GC both feeds
        threads = [threading.Thread(target=s.run, daemon=True)
                   for s in slaves]
        for t in threads:
            t.start()
        master.run()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "slave hung"
        deadline = time.time() + 10
        while federation.get_federation().slaves():
            assert time.time() < deadline, \
                "feeds not GC'd: %s" % federation.get_federation().slaves()
            time.sleep(0.05)
        text = _get(base, "/metrics")
        for sid in sids:
            assert not federated_lines(text, sid), \
                "federated series for disconnected %s survived GC:\n%s" \
                % (sid, "\n".join(federated_lines(text, sid)[:5]))
        cluster = json.loads(_get(base, "/cluster.json"))
        assert not cluster["slaves"], cluster
        print("cluster GC OK: per-slave series gone after clean "
              "disconnect")
    finally:
        dashboard.stop()
        for s in slaves:
            s.stop()
        master.stop()


def check_sched():
    """ISSUE 19: the scheduler is one pane of glass. Two one-worker
    gangs under different tenants federate their live training series
    to the scheduler's ``/metrics`` with ``{job,tenant}`` labels and
    into ``/history.json``; a SIGKILLed gang's ``sched_job_failed``
    flight record carries the job's trace id."""
    import signal
    import tempfile
    import time

    from veles_tpu.sched import JobSpec, Scheduler, SchedulerControl
    from veles_tpu.telemetry import flight

    with tempfile.TemporaryDirectory() as tmpdir:
        flight_dir = os.path.join(tmpdir, "flight")
        # the scheduler's own recorder must land records where this
        # check can read them (set BEFORE the first dump creates it)
        os.environ["VELES_FLIGHT_DIR"] = flight_dir
        worker_env = {k: v for k, v in os.environ.items()
                      if k != "XLA_FLAGS"}
        worker_env.update(PYTHONPATH=HERE, JAX_PLATFORMS="cpu",
                          VELES_FLIGHT_DIR=flight_dir,
                          VELES_SCHED_METRICS_S="0.2")

        def demo(out):
            return [sys.executable, "-m",
                    "veles_tpu.parallel.elastic", "worker-demo",
                    "--out", out, "--epochs", "60",
                    "--epoch-sleep", "0.3"]

        sched = Scheduler(2, tick_s=0.05, preempt=False,
                          log_dir=os.path.join(tmpdir, "logs")).start()
        control = SchedulerControl(sched).start()
        base = "http://127.0.0.1:%d" % control.port
        try:
            job_a = sched.submit(JobSpec(
                name="gang-a", argv=demo(os.path.join(tmpdir, "a.json")),
                tenant="acme", env=worker_env))
            job_b = sched.submit(JobSpec(
                name="gang-b", argv=demo(os.path.join(tmpdir, "b.json")),
                tenant="zeta", env=worker_env))
            want = {(job_a.id, "acme"), (job_b.id, "zeta")}

            def federated(text):
                return {(jid, tenant) for jid, tenant in want
                        if 'veles_sched_job_loss{job="%s",tenant="%s"}'
                        % (jid, tenant) in text}

            deadline = time.time() + 240
            while True:
                text = _get(base, "/metrics")
                if federated(text) == want:
                    break
                assert time.time() < deadline, \
                    "job series never federated (got %s):\n%s" \
                    % (federated(text), text[:3000])
                time.sleep(0.2)
            hist = json.loads(_get(
                base, "/history.json?series=veles_sched_job_loss"))
            with_points = {s["labels"].get("job")
                           for s in hist["series"] if s["points"]}
            assert {job_a.id, job_b.id} <= with_points, hist
            rows = {j["id"]: j for j in
                    json.loads(_get(base, "/jobs.json"))["jobs"]}
            assert rows[job_a.id]["metrics"].get("loss") is not None, \
                rows
            assert rows[job_b.id]["trace_id"] == job_b.trace_id
            print("sched federation OK: both gangs' live loss on "
                  "/metrics with {job,tenant} and in /history.json")

            # SIGKILL gang-b: the reap must leave a sched_job_failed
            # flight record carrying the job's trace id
            for proc in job_b.procs:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            sched.wait([job_b.id], timeout_s=60)
            assert job_b.state == "failed", job_b.state
            records = [r for r in sorted(os.listdir(flight_dir))
                       if "sched_job_failed" in r]
            assert records, os.listdir(flight_dir)
            record = flight.load_record(
                os.path.join(flight_dir, records[0]))
            assert record["context"]["trace_id"] == job_b.trace_id, \
                record["context"]
            assert record["context"]["job"]["id"] == job_b.id
            print("sched flight correlation OK: %s carries trace id %s"
                  % (records[0], job_b.trace_id))
        finally:
            control.stop()
            sched.stop(kill=True)


def main():
    if "--sched" in sys.argv:
        check_sched()
        print("sched observability smoke PASSED")
        return 0
    if "--cluster" in sys.argv:
        check_cluster()
        print("cluster observability smoke PASSED")
        return 0
    if "--flight" in sys.argv:
        with tempfile.TemporaryDirectory() as tmpdir:
            check_flight_record(tmpdir)
        print("flight-recorder smoke PASSED")
        return 0
    with tempfile.TemporaryDirectory() as tmpdir:
        check_trace(tmpdir)
    check_web_status()
    print("telemetry smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
