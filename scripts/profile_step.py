#!/usr/bin/env python3
"""Profile the fused AlexNet train step and print per-op attribution
(VERDICT r4 next #4: pin the MFU story from a trace, not ablations).

Captures a ``jax.profiler`` trace of steady-state compiled segments
(same discipline as bench.py's timed window: warm first, then trace),
parses the xplane protobuf, and aggregates the device plane's
synchronous op line ('XLA Ops', exclusive durations) three ways:

* top ops by device time;
* by SOURCE LINE (XLA carries ``source=veles_tpu/nn/<file>:<line>``
  per op — the repo's own layer attribution, no guessing);
* achieved FLOP/s and HBM GB/s per source bucket from the ``flops`` /
  ``bytes_accessed`` stats — the direct test of the bandwidth-floor
  claim in docs/PERF.md.

Usage: python scripts/profile_step.py [trace_dir] [--tune] [--reuse]
                                      [--attribution]

``--attribution`` skips the xplane machinery entirely and reports from
the telemetry registry instead (ISSUE 7): drives warmed compiled
segments, harvests ``Compiled.cost_analysis()`` through the cost book,
and prints the per-op attribution table (analytic FLOPs/bytes,
arithmetic intensity, measured ms, achieved TFLOP/s, roofline bound
verdict), the step MFU, the startup-phase breakdown and a memory
sample — the same numbers ``/profile.json`` serves live. Under
``VELES_OFFLOAD=1`` the trainer runs out-of-core and the table grows
one ``offload:h2d/g<k>`` / ``offload:d2h/g<k>`` roofline row per
streamed layer group (bytes moved, p50 ms, achieved GB/s), followed
by a transfer-vs-compute verdict naming a transfer-bound step. On non-TPU
hosts set ``VELES_PEAK_TFLOPS`` / ``VELES_HBM_GBPS`` to get MFU and
verdicts; without peaks the table still carries the absolute numbers.

``--tune`` first runs the kernel autotuner's search over the flagship
GEMM shapes (scripts/gemm_bench.py's shape list) so the traced step
runs with tuned dispatch — the before/after pair for docs/PERF.md is
``profile_step.py`` (before) vs ``profile_step.py --tune`` (after, or
any run with a warm cache). Every run ends with an autotune report:
mode, cache path, hit/miss counters and the entries consulted.

Env: VELES_PROFILE_SEGMENTS (default 2) — segments inside the trace.
"""

import collections
import glob
import logging
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

logging.disable(logging.WARNING)

N_TRAIN = int(os.environ.get("VELES_BENCH_NTRAIN", 2048))
BATCH = int(os.environ.get("VELES_BENCH_BATCH", 128))
SEGMENTS = int(os.environ.get("VELES_PROFILE_SEGMENTS", 2))
PRECISION = os.environ.get("VELES_BENCH_PRECISION", "bfloat16")
# flagship geometry by default; shrinkable so the CPU CI smoke can
# drive the identical code path in seconds instead of hours
SIDE = int(os.environ.get("VELES_BENCH_SIDE", 227))
CLASSES = int(os.environ.get("VELES_BENCH_CLASSES", 1000))


def build_trainer():
    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.alexnet import (ALEXNET_LAYERS,
                                          AlexNetWorkflow,
                                          SyntheticImageLoader)
    from veles_tpu.nn.precision import set_policy
    from veles_tpu.train import FusedTrainer

    set_policy(PRECISION)
    prng.get().seed(42)
    prng.get("loader").seed(43)
    wf = AlexNetWorkflow(
        DummyLauncher(),
        loader_factory=lambda w: SyntheticImageLoader(
            w, n_train=N_TRAIN, n_valid=BATCH, side=SIDE,
            n_classes=CLASSES, minibatch_size=BATCH, dtype="bfloat16"),
        layers=ALEXNET_LAYERS, max_epochs=1)
    wf.initialize(device=Device(backend=None))
    return FusedTrainer(wf)


def capture(trace_dir):
    import jax

    import bench  # repo-root bench.py: shared warm-up discipline

    trainer = build_trainer()
    # compile + settle OUTSIDE the trace
    params, states, idx, keys = bench.prepare_segment_run(
        trainer, warm=2, seed=0)
    t0 = time.time()
    with jax.profiler.trace(trace_dir):
        for _ in range(SEGMENTS):
            params, states, losses, _ = trainer._train_segment(
                params, states, idx, keys)
        float(losses[-1])
    wall = time.time() - t0
    print("traced %d segments (%d steps) in %.2fs"
          % (SEGMENTS, SEGMENTS * idx.shape[0], wall), file=sys.stderr)
    return wall, SEGMENTS * idx.shape[0]


def _load_xplanes(trace_dir):
    try:
        from xprof.protobuf import xplane_pb2
    except ImportError:
        # this environment's xprof wheel ships no xplane proto; the
        # tensorflow bundle's tsl copy is the same message
        from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        raise FileNotFoundError("no xplane.pb under %s" % trace_dir)
    spaces = []
    for path in paths:
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append(xs)
    return spaces


def _structural(name):
    # umbrella ops that CONTAIN the real work on the same line:
    # counting them would double every child
    return (name.startswith("%while") or name.startswith("jit_")
            or name.isdigit() or name.startswith("%call"))


def op_records(trace_dir):
    """[{name, dur_s, source, category, flops, bytes}] from the device
    plane's 'XLA Ops' line (host '/host:CPU' fallback for CPU runs)."""
    spaces = _load_xplanes(trace_dir)

    def collect(plane, line_filter):
        stat_names = {mid: m.name
                      for mid, m in plane.stat_metadata.items()}
        metas = {}
        for mid, meta in plane.event_metadata.items():
            stats = {}
            for st in meta.stats:
                key = stat_names.get(st.metadata_id)
                stats[key] = (st.str_value or st.ref_value or
                              st.int64_value)
            metas[mid] = (meta.name, stats)
        per_op = {}
        for line in plane.lines:
            if line_filter is not None and line.name != line_filter:
                continue
            for ev in line.events:
                name, stats = metas.get(ev.metadata_id,
                                        (str(ev.metadata_id), {}))
                if _structural(name):
                    continue
                rec = per_op.setdefault(name, {
                    "name": name, "dur_s": 0.0,
                    "source": str(stats.get("source", "")),
                    "category": str(stats.get("hlo_category", "")),
                    "flops": int(stats.get("flops", 0) or 0),
                    "bytes": int(stats.get("bytes_accessed", 0) or 0),
                    "calls": 0})
                rec["dur_s"] += ev.duration_ps / 1e12
                rec["calls"] += 1
        return list(per_op.values())

    for tier, line_filter in (("device", "XLA Ops"), ("host", None)):
        best = None
        for xs in spaces:
            for plane in xs.planes:
                is_device = ("TPU" in plane.name or
                             "/device:" in plane.name)
                want = (is_device if tier == "device"
                        else "/host:CPU" in plane.name)
                if not want:
                    continue
                recs = collect(plane, line_filter)
                total = sum(r["dur_s"] for r in recs)
                if recs and (best is None or total > best[1]):
                    best = (plane.name, total, recs)
        if best is not None:
            return best
    raise RuntimeError("no plane with events found")


def per_op_table(trace_dir):
    """(plane, total_s, [(name, dur_s, pct)]) — compat summary."""
    plane, total, recs = op_records(trace_dir)
    rows = [(r["name"], r["dur_s"], 100.0 * r["dur_s"] / total)
            for r in sorted(recs, key=lambda r: -r["dur_s"])]
    return plane, total, rows


def _source_bucket(rec):
    src = rec["source"]
    if "veles_tpu" in src:
        # veles_tpu/nn/normalization.py:34 -> nn/normalization.py:34
        return src.split("veles_tpu/", 1)[1]
    if src:
        return os.path.basename(src)
    cat = rec["category"] or "uncategorized"
    return "<no source: %s>" % cat


def autotune_report():
    """The tuner's end-of-run accounting (report mode — printed by
    every profile run so before/after MFU evidence carries its
    dispatch provenance)."""
    from veles_tpu.ops import autotune
    s = autotune.summary()
    print()
    print("autotune: mode=%s device=%s searches=%d hits=%d misses=%d"
          % (s["mode"], s["device"], s["searches"], s["hits"],
             s["misses"]))
    print("cache %s: %d entries" % (s["path"], len(s["entries"])))
    for key, entry in sorted(s["entries"].items()):
        print("  %s -> %s %s" % (key, entry.get("impl"),
                                 entry.get("config") or ""))


def _fmt(value, spec="%.2f", missing="-"):
    return missing if value is None else spec % value


def attribution_main():
    """The registry-sourced attribution report (no xplane parsing)."""
    import bench  # repo-root bench.py: shared warm-up discipline

    from veles_tpu.telemetry import profiler

    book = profiler.get_cost_book()
    trainer = build_trainer()
    # harvest + compile happen inside the first (warm) calls; the
    # timed calls below then observe steady-state segments
    params, states, idx, keys = bench.prepare_segment_run(
        trainer, warm=2, seed=0)
    for _ in range(SEGMENTS):
        t0 = time.perf_counter()
        params, states, losses, _ = trainer._train_segment(
            params, states, idx, keys)
        float(losses[-1])  # block: async dispatch time would be a lie
        elapsed = time.perf_counter() - t0
        book.observe_ms("train_segment", elapsed)
        book.record_step_mfu("train_segment", elapsed)

    report = profiler.profile_report()
    dev = report["device"]
    print("attribution (telemetry registry; %d batches/segment, "
          "batch %d, %s)" % (idx.shape[0], BATCH, PRECISION))
    print("device peaks: %s TFLOP/s, %s GB/s HBM (ridge %s FLOP/B)"
          % (_fmt(dev["peak_tflops"], "%.1f"),
             _fmt(dev["hbm_gbps"], "%.0f"),
             _fmt(dev["ridge_flops_per_byte"], "%.1f")))
    print()
    print("| op | GFLOP | MB | FLOP/B | calls | p50 ms | "
          "TFLOP/s | GB/s | bound |")
    print("|---|---|---|---|---|---|---|---|---|")
    for row in report["ops"]:
        print("| %s | %s | %s | %s | %d | %s | %s | %s | %s |" % (
            row["op"],
            _fmt(row.get("flops") and row["flops"] / 1e9, "%.2f"),
            _fmt(row.get("bytes") and row["bytes"] / 1e6, "%.1f"),
            _fmt(row.get("arithmetic_intensity"), "%.1f"),
            row.get("calls") or 0,
            _fmt(row.get("p50_ms"), "%.2f"),
            _fmt(row.get("achieved_tflops"), "%.2f"),
            _fmt(row.get("achieved_gbps"), "%.1f"),
            row.get("bound", "-")))
    print()
    off_rows = [r for r in report["ops"]
                if r["op"].startswith("offload:")]
    if off_rows:
        # out-of-core run (VELES_OFFLOAD=1): the CostBook carries one
        # roofline row per streamed group direction; name the verdict
        # the roofline table only implies — is the step transfer-bound?
        seg = next((r for r in report["ops"]
                    if r["op"] == "train_segment"), {})
        xfer_ms = sum((r.get("p50_ms") or 0.0) * (r.get("calls") or 0)
                      for r in off_rows) / max(SEGMENTS, 1)
        moved_mb = sum((r.get("bytes") or 0) * (r.get("calls") or 0)
                       for r in off_rows) / max(SEGMENTS, 1) / 1e6
        seg_ms = seg.get("p50_ms") or 0.0
        verdict = ("TRANSFER-bound" if seg_ms and xfer_ms > 0.5 * seg_ms
                   else "compute-bound")
        print("offload traffic: %.1f MB moved / %.1f ms transfer time "
              "per segment (%d h2d/d2h rows) vs segment p50 %.1f ms "
              "-> %s step" % (moved_mb, xfer_ms, len(off_rows),
                              seg_ms, verdict))
        print()
    mfu = report.get("step_mfu")
    print("step MFU: " + ("%.1f%%" % (mfu * 100.0) if mfu
                          else "n/a (no device peak known)"))
    print()
    print("startup phases:")
    phases = report["phases_ms"]
    total = sum(phases.values())
    for name, ms in phases.items():
        print("  %-18s %9.1f ms  %5.1f%%"
              % (name, ms, 100.0 * ms / total if total else 0.0))
    print("  %-18s %9.1f ms" % ("total", total))
    mem = report.get("memory") or {}
    for dev_label, m in sorted((mem.get("devices") or {}).items()):
        print("memory %s: live %.2f GB, peak %.2f GB, limit %.2f GB"
              % (dev_label, m.get("live_bytes", 0) / 2**30,
                 m.get("peak_bytes", 0) / 2**30,
                 m.get("limit_bytes", 0) / 2**30))
    if mem.get("host_rss_bytes"):
        print("memory host RSS: %.2f GB"
              % (mem["host_rss_bytes"] / 2**30))
    autotune_report()


def main():
    args = [a for a in sys.argv[1:]
            if a not in ("--reuse", "--tune", "--attribution")]
    reuse = "--reuse" in sys.argv
    if "--tune" in sys.argv:
        sys.path.insert(0, os.path.join(HERE, "scripts"))
        import gemm_bench
        import jax.numpy as jnp
        from veles_tpu.nn.precision import POLICIES
        os.environ.setdefault("VELES_AUTOTUNE", "search")
        # search with the policy's exact (compute, keep-or-accum)
        # dtype pair — the runtime linear_plan keys use both
        pol = POLICIES[PRECISION]
        gemm_bench.autotune_main(
            dtype=str(jnp.dtype(pol.compute_dtype)), batch=BATCH,
            out_dtype=str(jnp.dtype(pol.keep_dtype or
                                    pol.accum_dtype)))
    if "--attribution" in sys.argv:
        return attribution_main()
    trace_dir = (args[0] if args
                 else os.path.join("/tmp", "veles_profile_%d"
                                   % os.getpid()))
    if reuse:
        wall, steps = 0.0, SEGMENTS * (N_TRAIN // BATCH)
    else:
        wall, steps = capture(trace_dir)
    plane, total_s, recs = op_records(trace_dir)
    ms = 1e3 / steps  # per-step scale
    print("device plane: %s — %.3fs op time over %d steps "
          "(%.2f ms/step; wall %.2fs incl. host)"
          % (plane, total_s, steps, total_s * ms, wall))

    print()
    print("top ops (per step):")
    print("| op | source | ms/step | % | TFLOP/s | GB/s |")
    print("|---|---|---|---|---|---|")
    for r in sorted(recs, key=lambda r: -r["dur_s"])[:20]:
        # flops/bytes stats are per CALL; dur_s is summed over calls
        per_call = r["dur_s"] / max(r["calls"], 1)
        tf = r["flops"] / per_call / 1e12 if per_call else 0.0
        gb = r["bytes"] / per_call / 1e9 if per_call else 0.0
        print("| `%s` | %s | %.2f | %.1f%% | %.1f | %.0f |"
              % (r["name"].split(" = ")[0][:40],
                 _source_bucket(r), r["dur_s"] * ms,
                 100.0 * r["dur_s"] / total_s, tf, gb))

    print()
    print("by source line (layer attribution):")
    print("| source | ms/step | % | avg GB/s |")
    print("|---|---|---|---|")
    buckets = collections.defaultdict(lambda: [0.0, 0.0])
    for r in recs:
        b = buckets[_source_bucket(r)]
        b[0] += r["dur_s"]
        b[1] += r["bytes"] * r["calls"]
    for src, (secs, byts) in sorted(buckets.items(),
                                    key=lambda kv: -kv[1][0]):
        print("| %s | %.2f | %.1f%% | %.0f |"
              % (src, secs * ms, 100.0 * secs / total_s,
                 byts / secs / 1e9 if secs else 0.0))

    autotune_report()


if __name__ == "__main__":
    sys.exit(main())
