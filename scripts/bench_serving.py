#!/usr/bin/env python3
"""Serving benchmarks: batching engine, elastic scaling, cache, QoS.

``--scenario`` picks the regime (ISSUE 14 acceptance bars in bold):

* ``baseline`` (default) — the ISSUE 3 contract: at >= 32 concurrent
  HTTP clients the batched engine must deliver **>= 3x** the legacy
  sequential single-request throughput on the MNIST FC forward, and
  under 2x sustained capacity the overload path must 503 (never
  deadlock). Cache OFF so the engine itself is measured.
* ``burst`` — a **10x arrival-rate burst** against an autoscaling
  pool (min 1, max 4): sustained p95 must stay bounded, **zero
  clients hang**, and the autoscale reaction time (breach -> warmed
  replica serving) is measured from the registry histogram.
* ``diurnal`` — a ramp up/down client wave: the pool must grow with
  the wave and drain back down after it, zero hung clients.
* ``cache`` — repeat-heavy traffic (16 hot inputs) with the result
  cache on vs off: **>= 5x throughput** on the same traffic, and the
  cached responses are **bit-identical** to computed ones.
* ``multitenant`` — a greedy tenant (24 closed-loop clients) against
  a light tenant (2 clients) with equal weights: the greedy tenant
  sheds onto itself; the light tenant's requests keep flowing with a
  far lower shed rate.

The load generator always runs in a CHILD process (its own GIL; an
in-process generator would steal the server's interpreter lock and
measure itself). The child reads a JSON spec on stdin — phases of
``{seconds, clients, bodies, headers, path}`` — and prints per-phase
``{counts, elapsed, p50_ms, p95_ms}``; concurrent tenant groups are
separate child processes.

Usage: python scripts/bench_serving.py [--scenario S] [--quick] ...
Prints a markdown row + JSON blob (recorded in docs/PERF.md).
"""

import argparse
import base64
import json
import os
import subprocess
import sys
import threading
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)


def _build_model(layers=(4096, 4096)):
    """A serving-scale MNIST MLP (784 -> 4096 -> 4096 -> 10).

    The config-1 topology's 784x100 forward is ~0.2 ms — at that size
    any HTTP benchmark measures the Python request plumbing, not the
    engine. The wide variant's batch-1 forward is a few ms (real
    per-request model work to amortize), and XLA releases the GIL
    while it runs, so request handling overlaps compute exactly as in
    production."""
    import numpy

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.datasets import golden_digits
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist import MnistWorkflow
    from veles_tpu.serving.model_store import ServeableModel
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = MnistWorkflow(DummyLauncher(),
                       provider=golden_digits(n_train=600, n_valid=120),
                       layers=tuple(layers), minibatch_size=100,
                       max_epochs=1)
    wf.initialize(device=Device(backend=None))
    sample = numpy.zeros(wf.loader.minibatch_data.shape[1:],
                         numpy.float32).ravel()
    return ServeableModel.from_workflow(wf, name="mnist-fc"), sample


def _b64_body(sample, rid=None):
    body = {"input": base64.b64encode(
        sample.astype("float32").tobytes()).decode(),
        "codec": "base64", "shape": [sample.size], "type": "float32"}
    if rid is not None:
        body["id"] = rid
    return json.dumps(body)


def _hot_bodies(sample, n=16):
    """n distinct hot inputs: deterministic perturbations of the
    probe sample, so repeat-heavy traffic has a small key space."""
    import numpy
    rng = numpy.random.RandomState(7)
    return [_b64_body(sample + rng.rand(sample.size)
                      .astype(numpy.float32))
            for _ in range(n)]


# -- the child-process load generator ---------------------------------------


class _Client(object):
    """Persistent keep-alive client (what any real load driver uses —
    a fresh TCP connect per request would measure the kernel's SYN
    queue, not the serving engine)."""

    def __init__(self, port, timeout=60):
        import http.client
        self.conn = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=timeout)
        self.port = port
        self.timeout = timeout

    def post(self, body, path="/api", headers=None):
        try:
            h = {"Content-Type": "application/json"}
            if headers:
                h.update(headers)
            self.conn.request("POST", path, body=body, headers=h)
            resp = self.conn.getresponse()
            resp.read()
            return resp.status
        except Exception:
            try:
                self.conn.close()
            except Exception:
                pass
            import http.client as hc
            self.conn = hc.HTTPConnection("127.0.0.1", self.port,
                                          timeout=self.timeout)
            return -1

    def close(self):
        self.conn.close()


def _client_worker(port):
    """Load-generator body — runs inside a CHILD process (its own
    GIL). Reads the phase spec from stdin, prints per-phase results."""
    import collections
    import random

    spec = json.loads(sys.stdin.read())
    out = []
    for phase in spec["phases"]:
        bodies = phase["bodies"]
        path = phase.get("path", "/api")
        headers = phase.get("headers") or {}
        outcomes = collections.Counter()
        latencies = []
        lock = threading.Lock()
        stop = threading.Event()

        def worker(seed):
            rng = random.Random(seed)
            client = _Client(port)
            while not stop.is_set():
                body = bodies[rng.randrange(len(bodies))] \
                    if len(bodies) > 1 else bodies[0]
                t0 = time.perf_counter()
                status = client.post(body, path=path, headers=headers)
                dt = (time.perf_counter() - t0) * 1000.0
                with lock:
                    outcomes[status] += 1
                    latencies.append(dt)
            client.close()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(phase["clients"])]
        start = time.time()
        for t in threads:
            t.start()
        time.sleep(phase["seconds"])
        stop.set()
        for t in threads:
            t.join(timeout=90)
        elapsed = time.time() - start
        latencies.sort()

        def pct(q):
            if not latencies:
                return 0.0
            return latencies[min(len(latencies) - 1,
                                 int(q / 100.0 * len(latencies)))]

        out.append({"counts": {str(k): v
                               for k, v in outcomes.items()},
                    "elapsed": elapsed, "p50_ms": round(pct(50), 2),
                    "p95_ms": round(pct(95), 2)})
    print(json.dumps(out))


def _spawn(port, phases):
    """Start the load child; returns the Popen (stdin already fed)."""
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--client-worker",
         str(port)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE)
    proc.stdin.write(json.dumps({"phases": phases}).encode())
    proc.stdin.close()
    return proc


def _collect(proc, timeout):
    out = proc.stdout.read()
    rc = proc.wait(timeout=timeout)
    if rc != 0:
        raise RuntimeError("load child exited %d" % rc)
    return json.loads(out)


def _run_phases(port, phases):
    total = sum(p["seconds"] for p in phases)
    return _collect(_spawn(port, phases), timeout=total + 120)


def _qps(phase_result, status=200):
    return phase_result["counts"].get(str(status), 0) / \
        phase_result["elapsed"]


def _hung(phase_results):
    return sum(r["counts"].get("-1", 0) for r in phase_results)


# -- scenario: baseline (the PR 3 contract) ---------------------------------


def _start_legacy_service(model):
    """The pre-serving stack this engine replaces: RESTfulAPI +
    RestfulLoader with the reference's one-request-one-dispatch
    contract, serving the SAME weights — the honest baseline for the
    ISSUE 3 >= 3x bar."""
    import threading as _threading

    import numpy

    from veles_tpu.accelerated_units import AcceleratedWorkflow
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.restful import RestfulLoader
    from veles_tpu.nn.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.plumbing import Repeater
    from veles_tpu.restful_api import RESTfulAPI

    wf = AcceleratedWorkflow(DummyLauncher())
    repeater = Repeater(wf)
    repeater.link_from(wf.start_point)
    loader = RestfulLoader(wf, sample_shape=model.sample_shape,
                           feed_timeout=60)
    loader.link_from(repeater)
    prev, prev_attr = loader, "minibatch_data"
    units = []
    for i, (_, params) in enumerate(model.layers):
        width = params["weights"].shape[1]
        cls = All2AllSoftmax if i == len(model.layers) - 1 else All2AllTanh
        unit = cls(wf, output_sample_shape=(width,), name="l%d" % i)
        unit.link_from(prev)
        unit.link_attrs(prev, ("input", prev_attr))
        # serve the same trained weights the engine serves
        unit.weights.reset(numpy.array(params["weights"]))
        if "bias" in params:
            unit.bias.reset(numpy.array(params["bias"]))
        units.append(unit)
        prev, prev_attr = unit, "output"
    api = RESTfulAPI(wf, port=0, response_timeout=60)
    api.link_from(prev)
    api.link_attrs(prev, ("input", "output"))
    api.feed = loader.feed
    repeater.link_from(api)
    wf.initialize(device=Device(backend=None))
    thread = _threading.Thread(target=wf.run, daemon=True)
    thread.start()

    def stop():
        loader.finish()
        thread.join(timeout=30)
        api.stop()

    return api.address[1], stop


def run_baseline(quick=False, clients=32, replicas=1, max_batch=64,
                 window_ms=2.0):
    from veles_tpu.serving.frontend import ServingFrontend

    model, sample = _build_model()
    body = _b64_body(sample)
    seconds = 2.0 if quick else 8.0
    # baseline: the legacy one-request-one-dispatch service (its
    # natural mode is a sequential client; concurrency only queues
    # inside it) serving the same weights
    legacy_port, legacy_stop = _start_legacy_service(model)
    try:
        legacy = _run_phases(legacy_port, [
            {"seconds": 0.5, "clients": 1, "bodies": [body]},   # warm
            {"seconds": seconds, "clients": 1, "bodies": [body]}])[1]
    finally:
        legacy_stop()
    # cache OFF: this scenario measures the batching engine itself
    frontend = ServingFrontend(
        model, port=0, replicas=replicas, max_batch_size=max_batch,
        batch_timeout_ms=window_ms, max_queue=max(4 * clients, 128),
        response_timeout=60, cache_mb=0).start()
    try:
        results = _run_phases(frontend.port, [
            {"seconds": 0.5, "clients": 1, "bodies": [body]},   # warm
            {"seconds": seconds, "clients": 1, "bodies": [body]},
            {"seconds": seconds, "clients": clients, "bodies": [body]}])
        seq, conc = results[1], results[2]
        snap = frontend.metrics.snapshot()
    finally:
        frontend.stop()
    # overload regime: the admission bound is SMALLER than the burst
    # (that is when 503-shedding must engage), one replica so the
    # backlog builds under 2x+ sustained offered load
    overload_queue = 16
    overload_fe = ServingFrontend(
        model, port=0, replicas=1, max_batch_size=max_batch,
        batch_timeout_ms=window_ms, max_queue=overload_queue,
        response_timeout=60, warm=False, cache_mb=0).start()
    try:
        over = _run_phases(overload_fe.port, [
            {"seconds": max(seconds / 2, 2.0),
             "clients": 2 * overload_queue, "bodies": [body]}])[0]
    finally:
        overload_fe.stop()
    counts = {int(k): v for k, v in over["counts"].items()}
    ok, shed = counts.get(200, 0), counts.get(503, 0)
    hung = counts.get(-1, 0)
    total = sum(counts.values())
    overload = {"offered": total, "ok": ok, "shed_503": shed,
                "other": total - ok - shed - hung, "hung": hung}
    legacy_qps = _qps(legacy)
    result = {
        "scenario": "baseline",
        "legacy_sequential_qps": round(legacy_qps, 1),
        "sequential_qps": round(_qps(seq), 1),
        "concurrent_qps": round(_qps(conc), 1),
        "clients": clients,
        "speedup": round(_qps(conc) / max(legacy_qps, 1e-9), 2),
        "engine_speedup_vs_own_sequential": round(
            _qps(conc) / max(_qps(seq), 1e-9), 2),
        "replicas": replicas,
        "max_batch_size": max_batch,
        "batch_timeout_ms": window_ms,
        "mean_batch_size": snap["batches"]["mean_size"],
        "p95_ms": snap["endpoints"]["/api"]["p95_ms"],
        "overload": overload,
    }
    result["pass_speedup_3x"] = result["speedup"] >= 3.0
    result["pass_overload"] = (overload["shed_503"] > 0 and
                               overload["hung"] == 0 and
                               overload["other"] == 0)
    result["pass"] = result["pass_speedup_3x"] and result["pass_overload"]
    return result


# -- scenario: burst (10x arrival-rate step, autoscaling pool) --------------


def _autoscaled_frontend(model, max_queue=512, max_replicas=4,
                         fast_down=False):
    from veles_tpu.serving.frontend import ServingFrontend
    fe = ServingFrontend(
        model, port=0, replicas=1, max_batch_size=32,
        batch_timeout_ms=2.0, max_queue=max_queue, response_timeout=60,
        cache_mb=0, min_replicas=1, max_replicas=max_replicas,
        autoscale_interval_s=0.1)
    for entry in fe.entries.values():
        scaler = entry.autoscaler
        # the Python HTTP layer caps closed-loop qps well below the
        # engine's service rate on a CPU CI box, so the engine queue
        # stays shallow even under a 10x burst — the bench threshold
        # sits between the base (~1 outstanding) and burst (~4-6
        # outstanding) regimes instead of the production default
        scaler.up_queue_per_replica = 3.0
        scaler.up_for_s = 0.2           # bursts scale up FAST
        scaler.up_cooldown_s = 0.5
        if fast_down:                   # diurnal bench wants to SEE
            scaler.down_idle_for_s = 2.0   # the shrink inside its
            scaler.down_cooldown_s = 2.0   # measurement window
    return fe.start()


def _reaction_stats():
    from veles_tpu.telemetry.registry import get_registry
    hist = get_registry().get("veles_autoscale_reaction_s")
    if hist is None:
        return None
    series = hist.series()
    if not series or not any(c.count for _, c in series):
        return None
    child = max((c for _, c in series), key=lambda c: c.count)
    return {"count": child.count,
            "mean_s": round(child.sum / child.count, 3),
            "p95_s": round(child.percentile(95), 3)}


def run_burst(quick=False, base_clients=2, burst_factor=10):
    model, sample = _build_model()
    body = _b64_body(sample)
    base_s = 3.0 if quick else 8.0
    # the burst phase must OUTLAST the scale-up reaction: the new
    # replica warms every bucket before serving (the honest cold-start
    # cost the reaction metric exists to measure — ~seconds for the
    # wide model on CPU), so a burst shorter than that never observes
    # the grown pool
    burst_s = 10.0 if quick else 15.0
    fe = _autoscaled_frontend(model)
    try:
        phases = [
            {"seconds": 1.0, "clients": 1, "bodies": [body]},   # warm
            {"seconds": base_s, "clients": base_clients,
             "bodies": [body]},
            {"seconds": burst_s, "clients":
             base_clients * burst_factor, "bodies": [body]},
            {"seconds": max(base_s / 2, 2.0), "clients": base_clients,
             "bodies": [body]},
        ]
        results = _run_phases(fe.port, phases)
        # a scale-up committed during the burst may still be warming
        # (on a CPU CI box the wide model's bucket sweep takes longer
        # than the burst; on a real accelerator it lands in-burst) —
        # let it finish so the reaction time is recorded, but bail
        # fast when the burst never tripped the scaler at all
        deadline = time.monotonic() + 45.0
        scaler = fe.autoscaler
        while time.monotonic() < deadline and fe.pool.size() < 2:
            if scaler._breach_since is None and scaler._last_up is None:
                break               # nothing pending
            time.sleep(0.2)
        peak_replicas = fe.pool.size()
        reaction = _reaction_stats()
    finally:
        fe.stop()
    base, burst, after = results[1], results[2], results[3]
    result = {
        "scenario": "burst",
        "burst_factor": burst_factor,
        "base_qps": round(_qps(base), 1),
        "base_p95_ms": base["p95_ms"],
        "burst_qps": round(_qps(burst), 1),
        "burst_p95_ms": burst["p95_ms"],
        "after_p95_ms": after["p95_ms"],
        "burst_shed_503": burst["counts"].get("503", 0),
        "hung": _hung(results),
        "replicas_at_peak": peak_replicas,
        "autoscale_reaction": reaction,
    }
    # bounded: the burst p95 must stay within an order of magnitude of
    # the base p95 (closed-loop clients mean the queue can't run away;
    # what kills you without scaling is p95 exploding to the timeout)
    result["pass_p95_bounded"] = (
        burst["p95_ms"] <= max(10.0 * max(base["p95_ms"], 1.0), 500.0))
    result["pass_zero_hung"] = result["hung"] == 0
    result["pass_scaled_up"] = peak_replicas > 1 and reaction is not None
    result["pass"] = (result["pass_p95_bounded"] and
                      result["pass_zero_hung"] and
                      result["pass_scaled_up"])
    return result


# -- scenario: diurnal (ramp up, ramp down, pool follows) -------------------


def run_diurnal(quick=False):
    model, sample = _build_model()
    body = _b64_body(sample)
    dwell = 2.0 if quick else 5.0
    wave = [1, 4, 12, 20, 12, 4, 1]
    fe = _autoscaled_frontend(model, fast_down=True)
    try:
        sizes = []
        stop = threading.Event()

        def sampler():
            while not stop.wait(0.25):
                sizes.append(fe.pool.size())

        thread = threading.Thread(target=sampler, daemon=True)
        thread.start()
        results = _run_phases(fe.port, [
            {"seconds": dwell, "clients": n, "bodies": [body]}
            for n in wave])
        # the quiet tail: first wait out any scale-up still warming
        # (committed mid-wave, finishing after it on a CPU box), then
        # give the (bench-tuned) scale-down window a chance to drain
        # the pool back toward min
        deadline = time.monotonic() + 90.0
        scaler = fe.autoscaler
        while time.monotonic() < deadline and fe.pool.size() < 2:
            if scaler._breach_since is None and scaler._last_up is None:
                break               # the wave never tripped the scaler
            time.sleep(0.2)
        sizes.append(fe.pool.size())
        while time.monotonic() < deadline and fe.pool.size() > 1:
            time.sleep(0.5)
        stop.set()
        thread.join(timeout=5)
        final_replicas = fe.pool.size()
        peak_replicas = max(sizes + [final_replicas]) if sizes else 1
        reaction = _reaction_stats()
    finally:
        fe.stop()
    result = {
        "scenario": "diurnal",
        "wave_clients": wave,
        "qps_per_phase": [round(_qps(r), 1) for r in results],
        "p95_per_phase_ms": [r["p95_ms"] for r in results],
        "hung": _hung(results),
        "replicas_peak": peak_replicas,
        "replicas_final": final_replicas,
        "autoscale_reaction": reaction,
    }
    result["pass_zero_hung"] = result["hung"] == 0
    result["pass_scaled_up"] = peak_replicas > 1
    result["pass_scaled_down"] = final_replicas < peak_replicas
    result["pass"] = (result["pass_zero_hung"] and
                      result["pass_scaled_up"] and
                      result["pass_scaled_down"])
    return result


# -- scenario: cache (repeat-heavy traffic, on vs off) ----------------------


def _engine_throughput(model, rows, clients, seconds, cache):
    """Closed-loop submit/wait directly against the DynamicBatcher —
    the layer the cache actually removes work from. (On a CPU CI box
    the Python ``http.server`` frontend caps out near a few hundred
    qps regardless of compute, which HIDES the cache win behind
    request plumbing; the HTTP legs below are still reported so the
    end-to-end effect stays visible.)"""
    from veles_tpu.serving.engine import DynamicBatcher, EngineOverloaded
    from veles_tpu.serving.replica import ReplicaPool
    # warm=True: every bucket compiles through the staging-ring sweep
    # BEFORE the window — a cold bucket compiling mid-measurement
    # (seconds for the wide model) would swamp either leg
    pool = ReplicaPool(model, n_replicas=1, max_batch_size=32,
                       warm=True)
    batcher = DynamicBatcher(pool, batch_timeout_ms=2.0,
                             max_queue=max(4 * clients, 128),
                             cache=cache)
    import random
    done = [0]
    lock = threading.Lock()
    stop = threading.Event()

    def worker(seed):
        rng = random.Random(seed)
        while not stop.is_set():
            try:
                batcher.submit(rows[rng.randrange(len(rows))]) \
                    .result(timeout=60)
            except EngineOverloaded:
                continue
            with lock:
                done[0] += 1

    try:
        # settle: pay every bucket's compile before the timed window
        for row in rows:
            batcher.submit(row).result(timeout=120)
        if cache is not None:
            cache.invalidate()          # the timed window re-earns hits
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(timeout=90)
        elapsed = time.perf_counter() - t0
    finally:
        batcher.stop()
        pool.stop()
    return done[0] / elapsed


def run_cache(quick=False, clients=16, hot_inputs=16):
    import numpy

    from veles_tpu.serving.cache import ResultCache
    from veles_tpu.serving.engine import DynamicBatcher
    from veles_tpu.serving.frontend import ServingFrontend
    from veles_tpu.serving.replica import ReplicaPool

    model, sample = _build_model()
    bodies = _hot_bodies(sample, n=hot_inputs)
    rng = numpy.random.RandomState(7)
    rows = [sample + rng.rand(sample.size).astype(numpy.float32)
            for _ in range(hot_inputs)]    # the same hot set, decoded
    seconds = 2.0 if quick else 8.0

    # headline: engine-level throughput on the same repeat-heavy
    # traffic, cache off vs on — what the accelerator is spared
    engine_off = _engine_throughput(model, rows, clients, seconds,
                                    cache=None)
    on_cache = ResultCache(model="cache-bench")
    engine_on = _engine_throughput(model, rows, clients, seconds,
                                   cache=on_cache)
    engine_stats = on_cache.stats()

    # end-to-end: the same traffic through the HTTP frontend
    def measure_http(cache_mb):
        fe = ServingFrontend(
            model, port=0, replicas=1, max_batch_size=32,
            batch_timeout_ms=2.0, max_queue=max(4 * clients, 128),
            response_timeout=60, cache_mb=cache_mb).start()
        try:
            _run_phases(fe.port, [{"seconds": 0.5, "clients": 1,
                                   "bodies": bodies}])        # warm
            phase = _run_phases(fe.port, [
                {"seconds": seconds, "clients": clients,
                 "bodies": bodies}])[0]
        finally:
            fe.stop()
        return phase

    http_off = measure_http(cache_mb=0)
    http_on = measure_http(cache_mb=64)

    # bit-identity: the cached answer IS the computed answer — submit
    # the same row twice through a live engine and compare raw arrays
    pool = ReplicaPool(model, n_replicas=1, max_batch_size=8,
                       warm=False)
    batcher = DynamicBatcher(pool, batch_timeout_ms=1, max_queue=32,
                             cache=ResultCache(model="cache-bit"))
    try:
        x = sample + 0.25
        computed = batcher.submit(x).result(timeout=60)
        cached = batcher.submit(x).result(timeout=60)
        bit_identical = bool(numpy.array_equal(computed, cached))
    finally:
        batcher.stop()
        pool.stop()
    result = {
        "scenario": "cache",
        "clients": clients,
        "hot_inputs": hot_inputs,
        "engine_qps_cache_off": round(engine_off, 1),
        "engine_qps_cache_on": round(engine_on, 1),
        "speedup": round(engine_on / max(engine_off, 1e-9), 2),
        "engine_hit_ratio": engine_stats["hit_ratio"],
        "http_qps_cache_off": round(_qps(http_off), 1),
        "http_qps_cache_on": round(_qps(http_on), 1),
        "http_speedup": round(_qps(http_on) /
                              max(_qps(http_off), 1e-9), 2),
        "http_p95_off_ms": http_off["p95_ms"],
        "http_p95_on_ms": http_on["p95_ms"],
        "bit_identical": bit_identical,
        "hung": _hung([http_off, http_on]),
    }
    result["pass_speedup_5x"] = result["speedup"] >= 5.0
    result["pass_http_improves"] = (
        _qps(http_on) >= _qps(http_off) and
        http_on["p95_ms"] <= http_off["p95_ms"] * 1.1)
    result["pass"] = (result["pass_speedup_5x"] and bit_identical and
                      result["pass_http_improves"] and
                      result["hung"] == 0)
    return result


# -- scenario: multitenant (greedy vs light, weighted fairness) -------------


def run_multitenant(quick=False, greedy_clients=24, light_clients=2):
    from veles_tpu.serving.frontend import ServingFrontend

    model, sample = _build_model()
    body = _b64_body(sample)
    seconds = 3.0 if quick else 8.0
    fe = ServingFrontend(
        model, port=0, replicas=1, max_batch_size=16,
        batch_timeout_ms=2.0, max_queue=32, response_timeout=60,
        cache_mb=0,
        tenants={"greedy": {"weight": 1.0},
                 "light": {"weight": 1.0, "qos": "interactive"}},
    ).start()
    try:
        _run_phases(fe.port, [{"seconds": 0.5, "clients": 1,
                               "bodies": [body],
                               "headers": {"X-Tenant": "light"}}])
        greedy_proc = _spawn(fe.port, [
            {"seconds": seconds, "clients": greedy_clients,
             "bodies": [body], "headers": {"X-Tenant": "greedy"}}])
        light_proc = _spawn(fe.port, [
            {"seconds": seconds, "clients": light_clients,
             "bodies": [body], "headers": {"X-Tenant": "light"}}])
        greedy = _collect(greedy_proc, timeout=seconds + 120)[0]
        light = _collect(light_proc, timeout=seconds + 120)[0]
        tenants = fe.engine.admission.stats()["tenants"]
    finally:
        fe.stop()

    def shed_rate(phase):
        ok = phase["counts"].get("200", 0)
        shed = phase["counts"].get("503", 0)
        return shed / max(ok + shed, 1)

    result = {
        "scenario": "multitenant",
        "greedy_clients": greedy_clients,
        "light_clients": light_clients,
        "greedy_qps": round(_qps(greedy), 1),
        "light_qps": round(_qps(light), 1),
        "greedy_shed_rate": round(shed_rate(greedy), 3),
        "light_shed_rate": round(shed_rate(light), 3),
        "light_p95_ms": light["p95_ms"],
        "hung": _hung([greedy, light]),
        "tenants": {name: {k: t[k] for k in
                           ("qos", "share", "admitted", "shed")}
                    for name, t in tenants.items()},
    }
    # the fairness bar: the light tenant keeps flowing — its shed rate
    # is a fraction of the greedy tenant's, and it actually got served
    result["pass_light_served"] = _qps(light) > 0
    result["pass_fair"] = (result["light_shed_rate"] <=
                           max(0.5 * result["greedy_shed_rate"], 0.05))
    result["pass_zero_hung"] = result["hung"] == 0
    result["pass"] = (result["pass_light_served"] and
                      result["pass_fair"] and result["pass_zero_hung"])
    return result


# -- driver ------------------------------------------------------------------


SCENARIOS = {
    "baseline": run_baseline,
    "burst": run_burst,
    "diurnal": run_diurnal,
    "cache": run_cache,
    "multitenant": run_multitenant,
}


def run(quick=False, clients=32, replicas=1, max_batch=64,
        window_ms=2.0):
    """Back-compat entry (bench_all.py): the baseline scenario."""
    return run_baseline(quick=quick, clients=clients, replicas=replicas,
                        max_batch=max_batch, window_ms=window_ms)


def markdown_row(r):
    scenario = r.get("scenario", "baseline")
    if scenario == "baseline":
        return ("| serving mnist-fc | %.0f legacy / %.0f engine seq | "
                "%.0f @%d clients | %.1fx | mean batch %.1f | p95 %.1f "
                "ms | 503s %d / hung %d |" %
                (r["legacy_sequential_qps"], r["sequential_qps"],
                 r["concurrent_qps"], r["clients"], r["speedup"],
                 r["mean_batch_size"], r["p95_ms"],
                 r["overload"]["shed_503"], r["overload"]["hung"]))
    if scenario == "burst":
        reaction = r["autoscale_reaction"] or {}
        return ("| serving burst %dx | %.0f -> %.0f qps | p95 %.1f -> "
                "%.1f ms | replicas %d | react %.2fs | hung %d |" %
                (r["burst_factor"], r["base_qps"], r["burst_qps"],
                 r["base_p95_ms"], r["burst_p95_ms"],
                 r["replicas_at_peak"], reaction.get("mean_s", -1),
                 r["hung"]))
    if scenario == "diurnal":
        return ("| serving diurnal %s | replicas peak %d final %d | "
                "p95 max %.1f ms | hung %d |" %
                ("/".join(str(n) for n in r["wave_clients"]),
                 r["replicas_peak"], r["replicas_final"],
                 max(r["p95_per_phase_ms"]), r["hung"]))
    if scenario == "cache":
        return ("| serving cache %d hot | engine %.0f -> %.0f qps "
                "(%.1fx, hit %.0f%%) | http %.0f -> %.0f qps | "
                "bit-identical %s | hung %d |" %
                (r["hot_inputs"], r["engine_qps_cache_off"],
                 r["engine_qps_cache_on"], r["speedup"],
                 100 * r["engine_hit_ratio"], r["http_qps_cache_off"],
                 r["http_qps_cache_on"], r["bit_identical"],
                 r["hung"]))
    if scenario == "multitenant":
        return ("| serving multitenant %d vs %d | greedy %.0f qps "
                "shed %.0f%% | light %.0f qps shed %.0f%% p95 %.1f ms "
                "| hung %d |" %
                (r["greedy_clients"], r["light_clients"],
                 r["greedy_qps"], 100 * r["greedy_shed_rate"],
                 r["light_qps"], 100 * r["light_shed_rate"],
                 r["light_p95_ms"], r["hung"]))
    return "| %s | (unknown scenario) |" % scenario


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--client-worker":
        _client_worker(int(sys.argv[2]))
        return 0
    parser = argparse.ArgumentParser()
    parser.add_argument("--scenario", default="baseline",
                        choices=sorted(SCENARIOS))
    parser.add_argument("--quick", action="store_true",
                        help="short windows (CI smoke)")
    parser.add_argument("--clients", type=int, default=32)
    parser.add_argument("--replicas", type=int, default=1,
                        help="1 by default: on small hosts two "
                             "replicas' XLA pools thrash each other; "
                             "raise on real accelerators")
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--window-ms", type=float, default=2.0)
    args = parser.parse_args()
    if args.scenario == "baseline":
        result = run_baseline(quick=args.quick, clients=args.clients,
                              replicas=args.replicas,
                              max_batch=args.max_batch,
                              window_ms=args.window_ms)
    else:
        result = SCENARIOS[args.scenario](quick=args.quick)
    print(markdown_row(result))
    print(json.dumps(result, indent=2), file=sys.stderr)
    print("ACCEPTANCE: %s" % ("PASS" if result["pass"] else "FAIL"),
          file=sys.stderr)
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
