#!/usr/bin/env python3
"""Serving throughput: dynamic batching vs sequential single requests.

The acceptance bar for the serving engine (ISSUE 3): at >= 32
concurrent HTTP clients the batched engine must deliver >= 3x the
sequential single-request throughput on the MNIST FC forward, and under
2x sustained capacity the overload path must return 503 (never
deadlock).

Three phases against one in-process ``ServingFrontend`` (real HTTP,
loopback):

1. **sequential** — one client, one request in flight: the old
   one-request-one-dispatch service shape (every request pays a full
   forward dispatch plus the batcher window alone).
2. **concurrent** — N threads hammering the same endpoint: requests
   coalesce into padded batches, one jitted forward per batch.
3. **overload** — 2x the measured capacity offered for a few seconds
   with a small admission bound: counts 200/503, asserts every request
   got an HTTP answer.

Usage: python scripts/bench_serving.py [--quick] [--clients 32]
Prints a markdown row + JSON blob (recorded in docs/PERF.md).
"""

import argparse
import json
import os
import sys
import threading
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)


def _build_model(layers=(4096, 4096)):
    """A serving-scale MNIST MLP (784 -> 4096 -> 4096 -> 10).

    The config-1 topology's 784x100 forward is ~0.2 ms — at that size
    any HTTP benchmark measures the Python request plumbing, not the
    engine. The wide variant's batch-1 forward is a few ms (real
    per-request model work to amortize), and XLA releases the GIL
    while it runs, so request handling overlaps compute exactly as in
    production."""
    import numpy

    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.datasets import golden_digits
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.models.mnist import MnistWorkflow
    from veles_tpu.serving.model_store import ServeableModel
    prng.get().seed(1234)
    prng.get("loader").seed(1235)
    wf = MnistWorkflow(DummyLauncher(),
                       provider=golden_digits(n_train=600, n_valid=120),
                       layers=tuple(layers), minibatch_size=100,
                       max_epochs=1)
    wf.initialize(device=Device(backend=None))
    sample = numpy.zeros(wf.loader.minibatch_data.shape[1:],
                         numpy.float32).ravel()
    return ServeableModel.from_workflow(wf, name="mnist-fc"), sample


class _Client(object):
    """Persistent keep-alive client (what any real load driver uses —
    a fresh TCP connect per request would measure the kernel's SYN
    queue, not the serving engine)."""

    def __init__(self, port, timeout=60):
        import http.client
        self.conn = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=timeout)
        self.port = port
        self.timeout = timeout

    def post(self, body):
        import http.client
        try:
            self.conn.request("POST", "/api", body=body,
                              headers={"Content-Type":
                                       "application/json"})
            resp = self.conn.getresponse()
            resp.read()
            return resp.status
        except Exception:
            try:
                self.conn.close()
            except Exception:
                pass
            import http.client as hc
            self.conn = hc.HTTPConnection("127.0.0.1", self.port,
                                          timeout=self.timeout)
            return -1

    def close(self):
        self.conn.close()


def _client_worker(port, seconds, clients):
    """Load-generator body — runs inside a CHILD process (its own GIL;
    an in-process load generator would steal the server's interpreter
    lock and measure itself). Prints per-status counts as JSON."""
    import collections
    outcomes = collections.Counter()
    lock = threading.Lock()
    stop = threading.Event()

    def worker():
        client = _Client(port)
        while not stop.is_set():
            status = client.post(CLIENT_BODY)
            with lock:
                outcomes[status] += 1
        client.close()

    threads = [threading.Thread(target=worker) for _ in range(clients)]
    start = time.time()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=90)
    elapsed = time.time() - start
    print(json.dumps({"counts": {str(k): v for k, v in outcomes.items()},
                      "elapsed": elapsed}))


CLIENT_BODY = None  # set in the child from stdin


def _spawn_load(port, body, seconds, clients):
    """Run the load generator in a subprocess; returns (counts, qps)."""
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--client-worker",
         str(port), str(seconds), str(clients)],
        input=body.encode("utf-8"), stdout=subprocess.PIPE,
        timeout=seconds + 120, check=True)
    out = json.loads(proc.stdout)
    counts = {int(k): v for k, v in out["counts"].items()}
    return counts, sum(counts.values()) / out["elapsed"]


def _sequential(port, body, seconds):
    counts, qps = _spawn_load(port, body, seconds, clients=1)
    assert counts.get(200), "sequential baseline got no 200s: %s" % counts
    return qps


def _start_legacy_service(model):
    """The pre-serving stack this engine replaces: RESTfulAPI +
    RestfulLoader with the reference's one-request-one-dispatch
    contract, serving the SAME weights — the honest baseline for the
    ISSUE's >= 3x bar."""
    import threading as _threading

    import numpy

    from veles_tpu.accelerated_units import AcceleratedWorkflow
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher
    from veles_tpu.loader.restful import RestfulLoader
    from veles_tpu.nn.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.plumbing import Repeater
    from veles_tpu.restful_api import RESTfulAPI

    wf = AcceleratedWorkflow(DummyLauncher())
    repeater = Repeater(wf)
    repeater.link_from(wf.start_point)
    loader = RestfulLoader(wf, sample_shape=model.sample_shape,
                           feed_timeout=60)
    loader.link_from(repeater)
    prev, prev_attr = loader, "minibatch_data"
    units = []
    for i, (_, params) in enumerate(model.layers):
        width = params["weights"].shape[1]
        cls = All2AllSoftmax if i == len(model.layers) - 1 else All2AllTanh
        unit = cls(wf, output_sample_shape=(width,), name="l%d" % i)
        unit.link_from(prev)
        unit.link_attrs(prev, ("input", prev_attr))
        # serve the same trained weights the engine serves
        unit.weights.reset(numpy.array(params["weights"]))
        if "bias" in params:
            unit.bias.reset(numpy.array(params["bias"]))
        units.append(unit)
        prev, prev_attr = unit, "output"
    api = RESTfulAPI(wf, port=0, response_timeout=60)
    api.link_from(prev)
    api.link_attrs(prev, ("input", "output"))
    api.feed = loader.feed
    repeater.link_from(api)
    wf.initialize(device=Device(backend=None))
    thread = _threading.Thread(target=wf.run, daemon=True)
    thread.start()

    def stop():
        loader.finish()
        thread.join(timeout=30)
        api.stop()

    return api.address[1], stop


def _concurrent(port, body, seconds, clients):
    counts, _ = _spawn_load(port, body, seconds, clients)
    elapsed_qps = counts.get(200, 0)
    return elapsed_qps / seconds


def _overload(port, body, seconds, clients=32):
    """Hammer with ~2x the admission bound in flight; every request
    must get an HTTP answer (200 or an immediate 503) — the engine may
    shed but must never deadlock or hang a client."""
    counts, _ = _spawn_load(port, body, seconds, clients)
    ok = counts.get(200, 0)
    shed = counts.get(503, 0)
    hung = counts.get(-1, 0)
    total = sum(counts.values())
    return {"offered": total, "ok": ok, "shed_503": shed,
            "other": total - ok - shed - hung, "hung": hung}


def run(quick=False, clients=32, replicas=1, max_batch=64,
        window_ms=2.0):
    from veles_tpu.serving.frontend import ServingFrontend
    import base64

    model, sample = _build_model()
    # base64 is the production codec: C-speed decode instead of JSON
    # float parsing, so the bench measures the engine, not json.loads
    body = json.dumps({
        "input": base64.b64encode(
            sample.astype("float32").tobytes()).decode(),
        "codec": "base64", "shape": [len(sample)], "type": "float32"})
    seconds = 2.0 if quick else 8.0
    # baseline: the legacy one-request-one-dispatch service (its
    # natural mode is a sequential client; concurrency only queues
    # inside it) serving the same weights
    legacy_port, legacy_stop = _start_legacy_service(model)
    try:
        _sequential(legacy_port, body, 0.5)     # settle/warm
        legacy_qps = _sequential(legacy_port, body, seconds)
    finally:
        legacy_stop()
    frontend = ServingFrontend(
        model, port=0, replicas=replicas, max_batch_size=max_batch,
        batch_timeout_ms=window_ms, max_queue=max(4 * clients, 128),
        response_timeout=60).start()
    try:
        _sequential(frontend.port, body, 0.5)   # settle/warm HTTP
        seq_qps = _sequential(frontend.port, body, seconds)
        conc_qps = _concurrent(frontend.port, body, seconds, clients)
        snap = frontend.metrics.snapshot()
    finally:
        frontend.stop()
    # overload regime: the admission bound is SMALLER than the burst
    # (that is when 503-shedding must engage), one replica so the
    # backlog builds under 2x+ sustained offered load
    overload_queue = 16
    overload_fe = ServingFrontend(
        model, port=0, replicas=1, max_batch_size=max_batch,
        batch_timeout_ms=window_ms, max_queue=overload_queue,
        response_timeout=60, warm=False).start()
    try:
        overload = _overload(overload_fe.port, body,
                             max(seconds / 2, 2.0),
                             clients=2 * overload_queue)
    finally:
        overload_fe.stop()
    result = {
        "legacy_sequential_qps": round(legacy_qps, 1),
        "sequential_qps": round(seq_qps, 1),
        "concurrent_qps": round(conc_qps, 1),
        "clients": clients,
        "speedup": round(conc_qps / max(legacy_qps, 1e-9), 2),
        "engine_speedup_vs_own_sequential": round(
            conc_qps / max(seq_qps, 1e-9), 2),
        "replicas": replicas,
        "max_batch_size": max_batch,
        "batch_timeout_ms": window_ms,
        "mean_batch_size": snap["batches"]["mean_size"],
        "p95_ms": snap["endpoints"]["/api"]["p95_ms"],
        "overload": overload,
    }
    result["pass_speedup_3x"] = result["speedup"] >= 3.0
    result["pass_overload"] = (overload["shed_503"] > 0 and
                               overload["hung"] == 0 and
                               overload["other"] == 0)
    return result


def markdown_row(r):
    return ("| serving mnist-fc | %.0f legacy / %.0f engine seq | "
            "%.0f @%d clients | %.1fx | mean batch %.1f | p95 %.1f ms "
            "| 503s %d / hung %d |" %
            (r["legacy_sequential_qps"], r["sequential_qps"],
             r["concurrent_qps"], r["clients"], r["speedup"],
             r["mean_batch_size"], r["p95_ms"],
             r["overload"]["shed_503"], r["overload"]["hung"]))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--client-worker":
        global CLIENT_BODY
        CLIENT_BODY = sys.stdin.read()
        _client_worker(int(sys.argv[2]), float(sys.argv[3]),
                       int(sys.argv[4]))
        return 0
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="short windows (CI smoke)")
    parser.add_argument("--clients", type=int, default=32)
    parser.add_argument("--replicas", type=int, default=1,
                        help="1 by default: on small hosts two "
                             "replicas' XLA pools thrash each other; "
                             "raise on real accelerators")
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--window-ms", type=float, default=2.0)
    args = parser.parse_args()
    result = run(quick=args.quick, clients=args.clients,
                 replicas=args.replicas, max_batch=args.max_batch,
                 window_ms=args.window_ms)
    print(markdown_row(result))
    print(json.dumps(result, indent=2), file=sys.stderr)
    ok = result["pass_speedup_3x"] and result["pass_overload"]
    print("ACCEPTANCE: %s" % ("PASS" if ok else "FAIL"), file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
