#!/usr/bin/env python3
"""Input-pipeline overlap bench (ISSUE 8): a deliberately throttled
loader, streamed out-of-core, synchronous vs prefetched.

Every leg trains the SAME seeded workflow with the dataset forced
out-of-core (tiny ``VELES_SHARD_MB``) and a fixed per-shard host-ETL
sleep injected (``--etl-ms`` -> ``VELES_ETL_THROTTLE_MS``) — the
"loader is the bottleneck" scenario. Legs differ ONLY in pipeline
shape:

* ``sync``   — ``VELES_PREFETCH=0``: ETL+transfer inline on the step
  thread (the pre-pipeline behavior);
* ``double`` — depth 2, 1 worker: the default double-buffer (ETL for
  shard N+1 hides behind shard N's compute);
* ``deep``   — depth 4, 4 workers: ETL parallelism on top, for when a
  single worker's ETL is slower than compute.

Per leg: step-thread input wait (``veles_step_input_wait_ms`` sum /
p50), starvation fraction, wall time and the final loss — which must
be IDENTICAL across legs (the pipeline must not change the math; the
bench asserts it). Prints one JSON line per leg and a ``summary`` line
with the sync/deep wait ratio — the committed docs/PERF.md r10 table.

Usage::

    JAX_PLATFORMS=cpu python scripts/input_bench.py [--etl-ms 30]
        [--epochs 2] [--config fc|conv]
"""

import argparse
import json
import logging
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

logging.disable(logging.WARNING)


def build_workflow(config, epochs):
    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyLauncher

    prng.get().seed(42)
    prng.get("loader").seed(43)
    if config == "fc":
        import numpy
        from veles_tpu.models.mnist import MnistWorkflow

        rng = numpy.random.RandomState(7)

        def provider():
            x = rng.rand(4200, 12, 12).astype(numpy.float32)
            y = (x.reshape(len(x), -1).sum(1) > 72).astype(numpy.int32)
            return x[:4000], y[:4000], x[4000:], y[4000:]

        wf = MnistWorkflow(DummyLauncher(), provider=provider,
                           layers=(128,), minibatch_size=200,
                           learning_rate=0.05, max_epochs=epochs)
    elif config == "conv":
        from veles_tpu.models.alexnet import (AlexNetWorkflow,
                                              SyntheticImageLoader,
                                              small_alexnet_layers)
        wf = AlexNetWorkflow(
            DummyLauncher(),
            loader_factory=lambda w: SyntheticImageLoader(
                w, n_train=1024, n_valid=128, side=32, n_classes=10,
                minibatch_size=128),
            layers=small_alexnet_layers(n_classes=10),
            max_epochs=epochs)
    else:
        raise SystemExit("unknown --config %r" % config)
    wf.initialize(device=Device(backend=None))
    return wf


def run_leg(name, config, epochs, depth, workers):
    from veles_tpu.loader import prefetch
    from veles_tpu.telemetry.registry import get_registry
    from veles_tpu.train import FusedTrainer

    registry = get_registry()
    for metric in ("veles_step_input_wait_ms", "veles_prefetch_etl_ms",
                   "veles_prefetch_h2d_ms",
                   "veles_input_starvation_fraction"):
        family = registry.get(metric)
        if family is not None:
            family.reset()
    wf = build_workflow(config, epochs)
    trainer = FusedTrainer(wf, stream=True, prefetch_depth=depth,
                           prefetch_workers=workers)
    assert trainer.streaming, "leg must run out-of-core"
    start = time.time()
    history = trainer.train()
    wall = time.time() - start
    wait = registry.get("veles_step_input_wait_ms").labels()
    gauge = registry.get("veles_input_starvation_fraction")
    train_starve = {labels["phase"]: child.value
                    for labels, child in gauge.series()}.get("train")
    row = {
        "leg": name, "config": config, "depth": depth,
        "workers": workers, "epochs": len(history),
        "shards": wait.count,
        "input_wait_ms": round(wait.sum, 1),
        "input_wait_p50_ms": round(wait.percentile(50), 2),
        "train_starvation": round(train_starve or 0.0, 3),
        "wall_s": round(wall, 2),
        "final_loss": round(
            history[-1]["validation"]["normalized"], 6),
        "batches_per_shard": trainer._batches_per_shard,
    }
    prefetch.shutdown_all()
    print(json.dumps(row), flush=True)
    return row


def main():
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n")[0])
    parser.add_argument("--etl-ms", type=float, default=30.0,
                        help="injected host-ETL sleep per shard")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--config", default="fc",
                        choices=("fc", "conv"))
    parser.add_argument("--shard-mb", type=float, default=0.25,
                        help="forced shard size (keeps it out-of-core)")
    parser.add_argument("--min-ratio", type=float, default=0.0,
                        help="fail unless sync/deep wait ratio >= this "
                             "(the CI overlap guard)")
    args = parser.parse_args()

    os.environ["VELES_ETL_THROTTLE_MS"] = str(args.etl_ms)
    os.environ["VELES_SHARD_MB"] = str(args.shard_mb)

    legs = [("sync", 0, 1), ("double", 2, 1), ("deep", 4, 4)]
    rows = [run_leg(name, args.config, args.epochs, depth, workers)
            for name, depth, workers in legs]

    losses = {r["final_loss"] for r in rows}
    if len(losses) != 1:
        raise SystemExit("pipeline changed the math: losses %r" % losses)
    sync, deep = rows[0], rows[-1]
    ratio = sync["input_wait_ms"] / max(deep["input_wait_ms"], 1e-9)
    print(json.dumps({
        "leg": "summary", "etl_ms": args.etl_ms,
        "sync_wait_ms": sync["input_wait_ms"],
        "double_wait_ms": rows[1]["input_wait_ms"],
        "deep_wait_ms": deep["input_wait_ms"],
        "wait_ratio_sync_over_deep": round(ratio, 2),
        "loss_match": True,
    }), flush=True)
    if args.min_ratio and ratio < args.min_ratio:
        raise SystemExit(
            "overlap regressed: sync/deep input-wait ratio %.2f < %.1f"
            % (ratio, args.min_ratio))
    return 0


if __name__ == "__main__":
    sys.exit(main())
