#!/usr/bin/env python3
"""Distributed-overhead benchmark: master + slave fused segments vs
standalone (VERDICT r4 next #1 — BASELINE config 5's single-host
analog; reference protocol ``veles/server.py:659`` / ``client.py:405``,
``manualrst_veles_distributed_training.rst:14-27``).

The protocol's distributed cost per job is ONE weight push (master →
slave), the segment's compute, and ONE delta pull (slave → master);
the shm fast path makes both exchanges a pickle-encode + memcpy on
the same host. Whether that is ≤5% of a step therefore depends on the
ratio of exchange bytes/s to compute samples/s — so this script
measures the pieces separately and honestly:

* ``--cpu-protocol`` — master + 1 and 2 CPU slaves vs CPU standalone
  on a conv config whose weights are small: isolates SCHEDULING +
  framing + shm machinery overhead (the ≤5% protocol claim, and the
  2-slave leg shows scheduler overhead does not grow).
* ``shmbench`` — wire-encode + decode + memcpy of the REAL AlexNet-227
  parameter set (the per-job exchange payload) on this host: the
  numerator of the exchange-cost ratio on ANY same-host deployment.
  Reports three codecs side by side — the r5 full-pickle baseline,
  the out-of-band array framing (this repo's default shm path), and
  the ``--exchange-dtype bfloat16`` delta push — with per-phase times
  and the speedup vs pickle (docs/PERF.md r6).
* default (chip) — standalone vs master+1 slave on the chip with the
  MNIST-FC config (config 1; weights 0.32 MB). NOTE on this
  environment: the chip is reached through a tunneled relay measured
  at ~5 MB/s device→host, ~16 MB/s host→device, ~146 ms round trip
  (scripts/bench_all output table in docs/PERF.md) — per-job exchange
  of AlexNet-scale weights costs ~65 s against 1.6 s of epoch
  compute, so the flagship's distributed-vs-standalone ratio here
  measures the tunnel, not the protocol. On hardware with a local
  PCIe-attached chip the shmbench + compute numbers give the real
  ratio; the FC chip leg still exercises the full path end-to-end on
  the chip.

Methodology: every leg timestamps each epoch as its stats land (10 Hz
poll of ``decision.epoch_history``); throughput is over epochs 2..N so
epoch 1 absorbs the XLA compile identically everywhere.
"""

import json
import logging
import os
import subprocess
import sys
import threading
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

logging.disable(logging.WARNING)

EPOCHS = int(os.environ.get("VELES_DIST_EPOCHS", 12))
SEGMENT = int(os.environ.get("VELES_DIST_SEGMENT", 64))
CONFIG = os.environ.get("VELES_DIST_CONFIG", "fc")
PRECISION = os.environ.get("VELES_BENCH_PRECISION", "bfloat16")


def _build(launcher):
    from veles_tpu import prng
    from veles_tpu.nn.precision import set_policy
    set_policy(PRECISION)
    prng.get().seed(42)
    prng.get("loader").seed(43)
    if CONFIG == "fc":
        from veles_tpu.datasets import golden_digits
        from veles_tpu.models.mnist import MnistWorkflow
        # VELES_DIST_MB: the GSPMD e2e pair overrides the minibatch to
        # one the 8-way batch axis divides (512); both of its legs use
        # the same value so the comparison stays fair
        mb = int(os.environ.get("VELES_DIST_MB", "0") or 0) or 500
        return MnistWorkflow(
            launcher, provider=golden_digits(n_train=12000,
                                             n_valid=500),
            layers=(100,), minibatch_size=mb, max_epochs=EPOCHS)
    if CONFIG == "smallconv":
        from veles_tpu.models.alexnet import (AlexNetWorkflow,
                                              SyntheticImageLoader,
                                              small_alexnet_layers)
        return AlexNetWorkflow(
            launcher,
            loader_factory=lambda w: SyntheticImageLoader(
                w, n_train=2048, n_valid=128, side=64, n_classes=100,
                minibatch_size=128, dtype="bfloat16"),
            layers=small_alexnet_layers(n_classes=100),
            max_epochs=EPOCHS)
    raise SystemExit("unknown VELES_DIST_CONFIG %r" % CONFIG)


def _samples_per_epoch():
    return {"fc": 12500, "smallconv": 2176}[CONFIG]


def _timed_run(launcher, wf):
    stamps = []
    t0 = time.time()
    done = threading.Event()

    def poll():
        seen = 0
        while not done.is_set():
            n = len(wf.decision.epoch_history)
            now = time.time() - t0
            while seen < n:
                stamps.append(now)
                seen += 1
            done.wait(0.1)
        n = len(wf.decision.epoch_history)
        while seen < n:
            stamps.append(time.time() - t0)
            seen += 1

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    launcher.run()
    done.set()
    poller.join(timeout=5)
    return time.time() - t0, stamps


def _steady_rate(stamps, samples_per_epoch):
    """samples/s over epochs 2..N (epoch 1 absorbs the compile)."""
    if len(stamps) < 3:
        raise RuntimeError("need >=3 epochs for a steady window: %s"
                           % stamps)
    dt = stamps[-1] - stamps[0]
    return (len(stamps) - 1) * samples_per_epoch / dt


def run_standalone():
    from veles_tpu.launcher import Launcher
    launcher = Launcher(graphics=False)
    wf = _build(launcher)
    launcher.initialize()
    elapsed, stamps = _timed_run(launcher, wf)
    rate = _steady_rate(stamps, _samples_per_epoch())
    print("standalone[%s]: %d epochs in %.1fs, stamps %s (mode=%s)"
          % (CONFIG, len(stamps), elapsed,
             " ".join("%.1f" % s for s in stamps),
             launcher.run_mode_used), file=sys.stderr)
    print(json.dumps({
        "leg": "standalone", "config": CONFIG,
        "elapsed_s": round(elapsed, 2), "epochs": len(stamps),
        "samples_per_sec": round(rate, 1)}))


def run_master(n_slaves, port=0):
    from veles_tpu.launcher import Launcher
    chaos = os.environ.get("VELES_DIST_CHAOS")
    launcher = Launcher(
        listen_address="127.0.0.1:%d" % port, graphics=False,
        segment_size=SEGMENT,
        heartbeat_timeout=float(os.environ.get("VELES_DIST_HBT", 10.0)))
    _build(launcher)
    launcher.initialize()
    # auto-resume (VELES_AUTO_RESUME) may have replaced the built
    # workflow with the restored one — the launcher's is authoritative
    wf = launcher.workflow
    print("PORT=%d" % launcher._server.address[1], file=sys.stderr,
          flush=True)
    if launcher._resumed_from:
        print("EVENT resumed t=%.6f n=%d" %
              (time.time(), len(wf.decision.epoch_history)),
              file=sys.stderr, flush=True)
    deadline = time.time() + 900
    while len(launcher._server.snapshot_slaves()) < n_slaves:
        if time.time() > deadline:
            raise RuntimeError("slaves did not connect within 900s")
        time.sleep(0.2)
    if chaos:
        _start_chaos_watchers(launcher, chaos)
    elapsed, stamps = _timed_run(launcher, wf)
    epochs = len(wf.decision.epoch_history)
    print("master[%s, %d slaves]: %d epochs in %.1fs, stamps %s"
          % (CONFIG, n_slaves, epochs, elapsed,
             " ".join("%.1f" % s for s in stamps)), file=sys.stderr)
    out = {"leg": "distributed_%d_slave" % n_slaves, "config": CONFIG,
           "elapsed_s": round(elapsed, 2), "epochs": epochs}
    if not chaos:
        # bench legs NEED the steady rate (the orchestrators index
        # it); _steady_rate raises its clear >=3-epochs error here
        # instead of a downstream KeyError
        out["samples_per_sec"] = round(
            _steady_rate(stamps, _samples_per_epoch()), 1)
    elif len(stamps) >= 3:
        out["samples_per_sec"] = round(
            _steady_rate(stamps, _samples_per_epoch()), 1)
    print(json.dumps(out))


def _counter_total(name):
    from veles_tpu.telemetry.registry import get_registry
    family = get_registry().get(name)
    if family is None:
        return 0.0
    return sum(child.value for _, child in family.series())


def _hist_count(name, **labels):
    from veles_tpu.telemetry.registry import get_registry
    family = get_registry().get(name)
    if family is None:
        return 0
    total = 0
    for series_labels, child in family.series():
        if all(series_labels.get(k) == v for k, v in labels.items()):
            total += child.count
    return total


def _start_chaos_watchers(launcher, kind):
    """Announce chaos-relevant transitions on stderr, timestamped with
    the shared wall clock so the parent can compute time-to-X against
    the moment it injected the fault."""

    def watch_straggler():
        scorer = launcher._server.health
        while True:
            for sid, row in scorer.table().items():
                if row["state"] == "straggler":
                    print("EVENT straggler sid=%s t=%.6f score=%.2f"
                          % (sid, time.time(), row["score"]),
                          file=sys.stderr, flush=True)
                    return
            time.sleep(0.05)

    def watch_kill():
        # a SIGKILL'd slave's sockets close from the kernel: the drop
        # surfaces on the drops counter (the _serve finally classifies
        # a no-goodbye mid-run disconnect as a death even if the kill
        # landed on an idle instant), recovery as the first resolved
        # result after the requeue (veles_recovery_ms{event=requeue})
        drops_base = _counter_total("veles_slave_drops_total")
        requeue_base = _counter_total("veles_jobs_requeued_total")
        drop_seen = None
        while True:
            now = time.time()
            if drop_seen is None and \
                    _counter_total("veles_slave_drops_total") > drops_base:
                print("EVENT drop t=%.6f" % now,
                      file=sys.stderr, flush=True)
                drop_seen = now
            if drop_seen is not None and (
                    _hist_count("veles_recovery_ms", event="requeue") > 0
                    or (_counter_total("veles_jobs_requeued_total") ==
                        requeue_base and now - drop_seen > 0.5)):
                # still-zero requeues a beat AFTER the drop (the drop
                # counter increments before the requeue accounting, so
                # a same-poll read could race it) = the victim held
                # nothing: recovery is trivially immediate
                print("EVENT recovered t=%.6f" % now,
                      file=sys.stderr, flush=True)
                return
            time.sleep(0.02)

    def watch_epochs():
        seen = 0
        while True:
            n = len(launcher.workflow.decision.epoch_history)
            while seen < n:
                seen += 1
                print("EVENT epoch n=%d t=%.6f" % (seen, time.time()),
                      file=sys.stderr, flush=True)
            time.sleep(0.05)

    def watch_state():
        # periodic one-line scheduler state: when a chaos leg wedges,
        # THIS is the line that says which side is withholding
        while True:
            try:
                wf = launcher.workflow
                loader, decision = wf.loader, wf.decision
                slaves = launcher._server.snapshot_slaves()
                print("EVENT state t=%.6f ep=%s off=%s open=%s "
                      "buckets=%s failed=%d pending=%s inflight=%s "
                      "hist=%d hasdata=%s nomore=%s" %
                      (time.time(), loader.epoch_number,
                       loader._global_offset,
                       getattr(decision, "_next_close_epoch_", None),
                       sorted(getattr(decision, "_epoch_buckets_",
                                      None) or ()),
                       len(loader.failed_minibatches),
                       {s: len(j)
                        for s, j in dict(loader._pending_).items()},
                       {s.id: len(s.jobs_in_flight) for s in slaves},
                       len(decision.epoch_history),
                       decision.has_data_for_slave,
                       launcher._server.no_more_jobs),
                      file=sys.stderr, flush=True)
            except Exception:
                # racing live dicts (no locks held on purpose): a torn
                # read must not kill the diagnostic stream
                pass
            time.sleep(2.0)

    print("EVENT running t=%.6f" % time.time(), file=sys.stderr,
          flush=True)
    watchers = {"straggler": [watch_straggler],
                "kill": [watch_kill, watch_epochs],
                "master-restart": [watch_epochs, watch_state]}[kind]
    for target in watchers:
        threading.Thread(target=target, daemon=True).start()


def run_slave(port):
    from veles_tpu.launcher import Launcher
    launcher = Launcher(master_address="127.0.0.1:%d" % port,
                        graphics=False,
                        heartbeat_interval=float(
                            os.environ.get("VELES_DIST_HB", 2.0)))
    _build(launcher)
    launcher.initialize()
    launcher.run()
    print(json.dumps({"leg": "slave", "ok": True}))


def _payload_shrink():
    """``VELES_DIST_PAYLOAD_SHRINK``: divide the large fc dims of the
    exchange payload by this factor (CI quick mode — the flagship
    249.5 MB set stacked 8-wide for the GSPMD merge leg would not fit
    a shared runner). Both the shm and the GSPMD legs read it, so the
    compared cycles always carry the SAME payload."""
    try:
        return max(1, int(os.environ.get("VELES_DIST_PAYLOAD_SHRINK",
                                         "1")))
    except ValueError:
        return 1


def _alexnet_payload(rng, scale=1.0):
    """The real AlexNet-227 stored parameter set (conv kernels + fc
    trunk), f32; conv1 is (ky, kx, 3, 96) — the s2d regrouping happens
    at apply time, never in the exchanged arrays."""
    import numpy
    shapes = [(11, 11, 3, 96), (96,), (5, 5, 96, 256), (256,),
              (3, 3, 256, 384), (384,), (3, 3, 384, 384), (384,),
              (3, 3, 384, 256), (256,), (9216, 4096), (4096,),
              (4096, 4096), (4096,), (4096, 1000), (1000,)]
    shrink = _payload_shrink()
    if shrink > 1:
        shapes = [tuple(d // shrink if d >= 1024 else d for d in s)
                  for s in shapes]
    return {"w%d" % i: (rng.randn(*s) * scale).astype(numpy.float32)
            for i, s in enumerate(shapes)}


def run_shmbench():
    """Per-job weight-exchange cost at FLAGSHIP scale on this host:
    encode the real AlexNet-227 parameter set, memcpy through ONE
    reused SharedMemory segment, copy out, decode — the full shm
    fast-path payload cycle, no device involved. Three codecs:

    * ``pickle``  — the r5 baseline (full pickle byte-string both ways);
    * ``oob``     — out-of-band framing: skeleton pickle + raw array
      buffers memcpy'd straight into the segment, decode =
      zero-copy ``frombuffer`` views (this PR's default shm path);
    * ``delta16`` — oob + ``--exchange-dtype bfloat16`` steady-state
      delta push (half the bytes; the first full push is excluded,
      it happens once per slave connection).

    The segment is allocated once and reused across cycles, like the
    Protocol's double-buffered segments in a real run. Reports the
    best-of-N cycle per codec and the speedups over pickle.
    """
    import pickle
    from multiprocessing import shared_memory

    import numpy

    from veles_tpu.parallel import wire

    cycles = int(os.environ.get("VELES_SHMBENCH_CYCLES", 5))
    rng = numpy.random.RandomState(0)
    payload = _alexnet_payload(rng)
    # a second weight state one SGD-sized step away, so delta cycles
    # encode a real nonzero delta every time
    stepped = {k: v + 0.001 * rng.randn(*v.shape).astype(numpy.float32)
               for k, v in payload.items()}
    total_mb = sum(a.nbytes for a in payload.values()) / 1e6

    def cycle_pickle(seg, tree):
        t0 = time.time()
        blob = wire.RAW + pickle.dumps(tree, protocol=4)
        t1 = time.time()
        seg.buf[:len(blob)] = blob
        t2 = time.time()
        out = bytes(seg.buf[:len(blob)])
        t3 = time.time()
        wire.decode(out)
        t4 = time.time()
        return (t1 - t0, t2 - t1, t3 - t2, t4 - t3), len(blob)

    def cycle_oob(seg, tree):
        t0 = time.time()
        chunks = wire.encode_chunks(tree)
        t1 = time.time()
        pos = 0
        for part in chunks.parts:
            seg.buf[pos:pos + len(part)] = part
            pos += len(part)
        t2 = time.time()
        out = bytes(seg.buf[:pos])
        t3 = time.time()
        tree = wire.decode(out)
        # touch one element per leaf so lazy views cannot hide work
        for arr in tree.values():
            arr.ravel()[0]
        t4 = time.time()
        return (t1 - t0, t2 - t1, t3 - t2, t4 - t3), chunks.nbytes

    def run_leg(fn, seg, trees):
        best, wire_bytes = None, 0
        for i in range(cycles):
            times, nbytes = fn(seg, trees[i % len(trees)])
            if best is None or sum(times) < sum(best):
                best, wire_bytes = times, nbytes
        return best, wire_bytes

    # pickle baseline sizing: tag + full pickle
    probe = wire.RAW + pickle.dumps(payload, protocol=4)
    seg = shared_memory.SharedMemory(create=True,
                                     size=len(probe) + (1 << 20))
    rows = {}
    try:
        rows["pickle"] = run_leg(cycle_pickle, seg, [payload, stepped])
        rows["oob"] = run_leg(cycle_oob, seg, [payload, stepped])

        enc = wire.DeltaEncoder(dtype="bfloat16")
        dec = wire.DeltaDecoder()
        # untimed first full push primes both codecs' bases to
        # ``payload``; starting the flip at ``stepped`` makes every
        # timed cycle carry a real full-size delta (starting at
        # ``payload`` would make cycle 0 an all-leaves-skipped no-op)
        dec.decode(wire.decode(wire.encode_chunks(
            enc.encode(payload)).join()))
        flip = [stepped, payload]

        def cycle_delta(seg, tree):
            t0 = time.time()
            chunks = wire.encode_chunks(enc.encode(tree))
            t1 = time.time()
            pos = 0
            for part in chunks.parts:
                seg.buf[pos:pos + len(part)] = part
                pos += len(part)
            t2 = time.time()
            out = bytes(seg.buf[:pos])
            t3 = time.time()
            dec.decode(wire.decode(out))
            t4 = time.time()
            return (t1 - t0, t2 - t1, t3 - t2, t4 - t3), chunks.nbytes

        rows["delta16"] = run_leg(cycle_delta, seg, flip)
    finally:
        seg.close()
        seg.unlink()

    report = {"leg": "shmbench", "payload_mb": round(total_mb, 1),
              "cycles": cycles}
    base = sum(rows["pickle"][0])
    for name, (times, wire_bytes) in rows.items():
        enc_s, in_s, out_s, dec_s = times
        cyc = sum(times)
        report[name] = {
            "encode_s": round(enc_s, 4), "shm_in_s": round(in_s, 4),
            "shm_out_s": round(out_s, 4), "decode_s": round(dec_s, 4),
            "full_cycle_s": round(cyc, 4),
            "wire_mb": round(wire_bytes / 1e6, 1),
            "mb_per_s": round(total_mb / cyc, 0),
            "speedup_vs_pickle": round(base / cyc, 2)}
    print(json.dumps(report))


def run_gspmd_merge():
    """The GSPMD gradient-merge cycle at exchange-payload scale
    (ISSUE 15): the same parameter set ``shmbench`` pushes through the
    PR 2 shm wire, but merged the launcher-SPMD way — every device of
    the 8-way CPU mesh holds its own full-size partial gradient (the
    per-slave delta of the coordinator protocol) and ONE jitted
    reduction, partitioned over the named ``batch`` axis, merges them
    with a compiler-inserted all-reduce. No pickling, no memcpy, no
    decode: the whole "exchange" is the collective. Reports the
    best-of-N blocked cycle plus the compiled program's
    collective-bytes estimate (the ISSUE 15 CostBook surface).

    Run under ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
    (the orchestrator forces it); numbers on a CPU mesh measure the
    machinery's overhead honestly — all 8 "devices" share the same
    cores — while on a real pod the same program rides ICI."""
    import numpy

    import jax
    import jax.numpy as jnp

    from veles_tpu.parallel.gspmd import BATCH_AXIS, gspmd_mesh
    from veles_tpu.parallel.mesh import named_sharding
    from veles_tpu.telemetry import profiler

    cycles = int(os.environ.get("VELES_SHMBENCH_CYCLES", 5))
    mesh = gspmd_mesh()
    n_dev = mesh.shape[BATCH_AXIS]
    rng = numpy.random.RandomState(0)
    payload = _alexnet_payload(rng, scale=0.001)
    total_mb = sum(a.nbytes for a in payload.values()) / 1e6
    part_spec = named_sharding(mesh, BATCH_AXIS)
    repl = named_sharding(mesh)

    def put_stacked(arr):
        # each device's shard of the stacked dim IS its local partial
        # gradient — a zero-copy broadcast view feeds the per-shard
        # slices, so host memory holds ONE copy however wide the mesh
        stacked = numpy.broadcast_to(arr, (n_dev,) + arr.shape)
        return jax.device_put(stacked, part_spec)

    parts = {k: put_stacked(v) for k, v in payload.items()}

    def merge(tree):
        return {k: jnp.sum(v, axis=0) for k, v in tree.items()}

    jit_merge = jax.jit(merge, out_shardings=repl)
    jax.block_until_ready(jit_merge(parts))  # compile outside the clock
    best = None
    for _ in range(cycles):
        t0 = time.time()
        jax.block_until_ready(jit_merge(parts))
        dt = time.time() - t0
        best = dt if best is None or dt < best else best
    coll = profiler.collective_bytes_estimate(
        jit_merge.lower(parts).compile()) or {}
    print(json.dumps({
        "leg": "gspmd_merge", "payload_mb": round(total_mb, 1),
        "devices": n_dev, "cycles": cycles,
        "full_cycle_s": round(best, 4),
        "mb_per_s": round(total_mb / best, 0),
        "collective_bytes_mb": round(coll.get("bytes", 0) / 1e6, 1),
        "collectives": coll.get("count", 0)}))


def orchestrate_gspmd():
    """``--gspmd`` (ISSUE 15): the exchange/merge-cycle comparison —
    the PR 2 shm wire codecs vs the compiler-inserted collective on
    the forced-8-device CPU mesh, same payload — plus (unless
    ``VELES_GSPMD_E2E=0``) an end-to-end standalone-vs-GSPMD training
    pair on the FC config so the whole launcher path stays exercised."""
    shrink = _payload_shrink()
    shm = _drain(_spawn("shmbench", tpu=False), "shmbench")
    merge = _drain(_spawn(
        "gspmd-merge", tpu=False,
        extra_env={"XLA_FLAGS":
                   "--xla_force_host_platform_device_count=8"}),
        "gspmd-merge")
    oob_s = shm["oob"]["full_cycle_s"]
    pickle_s = shm["pickle"]["full_cycle_s"]
    merge_s = merge["full_cycle_s"]
    table = {
        "mode": "gspmd", "config": CONFIG,
        "payload_mb": merge["payload_mb"],
        "payload_shrink": shrink,
        "shm_pickle_cycle_s": pickle_s,
        "shm_oob_cycle_s": oob_s,
        "shm_delta16_cycle_s": shm["delta16"]["full_cycle_s"],
        "gspmd_merge_cycle_s": merge_s,
        "gspmd_speedup_vs_oob": round(oob_s / merge_s, 2),
        "gspmd_speedup_vs_pickle": round(pickle_s / merge_s, 2),
        "collective_bytes_mb": merge["collective_bytes_mb"],
        "collectives": merge["collectives"],
    }
    if os.environ.get("VELES_GSPMD_E2E", "1") not in ("0", "off"):
        # both e2e legs under the SAME forced-8-device env: fused uses
        # one of the 8 virtual devices, GSPMD shards over all — on a
        # CPU mesh the ratio measures partitioning overhead (the
        # devices share cores), on a pod it measures scaling
        env = {"VELES_DIST_CONFIG": CONFIG, "VELES_DIST_MB": "512",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
        alone = _drain(_spawn("standalone", tpu=False, extra_env=env),
                       "standalone")
        gspmd = _drain(_spawn(
            "standalone", tpu=False,
            extra_env=dict(env, VELES_GSPMD="auto"), tag="gspmd"),
            "gspmd")
        table["standalone_samples_per_sec"] = alone["samples_per_sec"]
        table["gspmd_samples_per_sec"] = gspmd["samples_per_sec"]
        table["gspmd_vs_fused_ratio"] = round(
            gspmd["samples_per_sec"] / alone["samples_per_sec"], 3)
    print(json.dumps(table))


# -- orchestration ---------------------------------------------------------


#: overall ceiling on any single leg — a hung-but-alive subprocess
#: must fail the harness loudly instead of blocking it forever
LEG_TIMEOUT = float(os.environ.get("VELES_DIST_TIMEOUT", 1800))


def _spawn(mode, *args, tpu, extra_env=None, tag=None, argv=None):
    """Start a leg subprocess with BACKGROUND pipe pumps: stderr lines
    are forwarded (tagged) as they arrive and stdout lines collected —
    so a slave producing >64 KB of output can never fill its pipe and
    deadlock the harness against a blocked master. ``argv`` overrides
    the default ``python bench_distributed.py <mode>`` command (the
    spmd-kill leg launches elastic supervisors through the SAME pump
    machinery — EVENT lines land in ``proc.events`` either way)."""
    env = dict(os.environ)
    if not tpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["VELES_TPU_BACKEND"] = "cpu"
    env.update(extra_env or {})
    cmd = list(argv) if argv is not None else (
        [sys.executable, os.path.abspath(__file__), mode] +
        [str(a) for a in args])
    proc = subprocess.Popen(
        cmd,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    proc.tag = tag or mode
    proc.out_lines = []
    proc.port = None
    proc.port_seen = threading.Event()
    proc.events = []

    def pump_err():
        for line in proc.stderr:
            if line.startswith("PORT="):
                proc.port = int(line.split("=", 1)[1].strip())
                proc.port_seen.set()
            elif line.startswith("EVENT "):
                # "EVENT <name> k=v ..." announcements (chaos legs)
                parts = line.split()
                proc.events.append(
                    (parts[1], dict(p.split("=", 1) for p in parts[2:]
                                    if "=" in p)))
            sys.stderr.write("[%s] %s" % (proc.tag, line))
        proc.port_seen.set()  # EOF: unblock _wait_port on early death

    def pump_out():
        for line in proc.stdout:
            proc.out_lines.append(line)

    proc.pumps = [threading.Thread(target=pump_err, daemon=True),
                  threading.Thread(target=pump_out, daemon=True)]
    for t in proc.pumps:
        t.start()
    return proc


def _wait_port(proc, timeout=900):
    proc.port_seen.wait(timeout)
    if proc.port is None:
        if proc.poll() is None:
            # hung before binding: don't orphan it holding the device
            proc.kill()
            proc.wait()
        raise RuntimeError("master died or hung before binding")
    return proc.port


def _drain(proc, tag, timeout=None):
    """Wait for a leg (bounded), join its pumps, parse the last JSON
    stdout line. The pipe pumps already ran in the background, so this
    cannot deadlock on full pipes; the timeout covers a leg that hangs
    while alive."""
    try:
        proc.wait(timeout=LEG_TIMEOUT if timeout is None else timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        raise RuntimeError("%s leg hung; killed after %.0fs"
                           % (tag, LEG_TIMEOUT if timeout is None
                              else timeout))
    for t in proc.pumps:
        t.join(timeout=10)
    payload = None
    for line in proc.out_lines:
        try:
            payload = json.loads(line)
        except ValueError:
            sys.stderr.write("[%s] %s" % (tag, line))
    if proc.returncode != 0:
        raise RuntimeError("%s leg failed (rc=%d)"
                           % (tag, proc.returncode))
    return payload


def _one_round(n_slaves, tpu_slave, config):
    env = {"VELES_DIST_CONFIG": config}
    master = _spawn("master", n_slaves, tpu=False, extra_env=env)
    port = _wait_port(master)
    slaves = [_spawn("slave", port, tpu=tpu_slave, extra_env=env,
                     tag="slave%d" % i)
              for i in range(n_slaves)]

    # a slave dying at startup would leave the master waiting and the
    # parent blocked on it with the slave's stderr never surfaced —
    # watch the slaves and kill the master if one dies while it runs
    def watchdog():
        while master.poll() is None:
            for i, s in enumerate(slaves):
                if s.poll() not in (None, 0):
                    sys.stderr.write("slave%d died (rc=%s); killing "
                                     "the master leg\n"
                                     % (i, s.returncode))
                    master.kill()
                    return
            time.sleep(1.0)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        dist = _drain(master, "master")
    finally:
        # always surface slave output, even when the master leg failed
        for i, s in enumerate(slaves):
            if s.poll() is None and master.poll() is not None:
                s.kill()
            try:
                # slaves exit right after the master; anything still
                # alive here is wedged — bound the wait tightly
                _drain(s, "slave%d" % i, timeout=60)
            except RuntimeError as e:
                sys.stderr.write("%s\n" % e)
    return dist


def orchestrate_cpu_protocol():
    env = {"VELES_DIST_CONFIG": "smallconv"}
    alone = _drain(_spawn("standalone", tpu=False, extra_env=env),
                   "standalone")
    one = _one_round(1, tpu_slave=False, config="smallconv")
    two = _one_round(2, tpu_slave=False, config="smallconv")
    table = {
        "mode": "cpu_protocol", "config": "smallconv",
        "standalone_samples_per_sec": alone["samples_per_sec"],
        "distributed_1slave_samples_per_sec": one["samples_per_sec"],
        "distributed_2slave_samples_per_sec": two["samples_per_sec"],
        "overhead_1slave_pct": round(
            100 * (1 - one["samples_per_sec"] /
                   alone["samples_per_sec"]), 1),
        "segment_size": SEGMENT, "epochs": EPOCHS,
    }
    print(json.dumps(table))


def _wait_event(proc, name, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for event, attrs in list(proc.events):
            if event == name:
                return attrs
        if proc.poll() is not None:
            raise RuntimeError("%s died (rc=%s) before EVENT %s"
                               % (proc.tag, proc.returncode, name))
        time.sleep(0.02)
    raise RuntimeError("no EVENT %s within %.0fs" % (name, timeout))


def orchestrate_chaos_straggler():
    """``--chaos straggler`` (ROADMAP item 5's first chaos piece):
    master + 2 CPU slaves on the FC config; once the run is in steady
    state, SIGSTOP one slave mid-epoch and measure how long the
    master's health scorer takes to flag it as a straggler. The
    contract (ISSUE 9): detection within 3 heartbeat intervals (plus a
    0.75 s grace for signal delivery + evaluation cadence)."""
    import signal

    hb = float(os.environ.get("VELES_DIST_HB", 0.5))
    env = {"VELES_DIST_CONFIG": "fc", "VELES_DIST_HB": str(hb),
           "VELES_DIST_CHAOS": "straggler"}
    master = _spawn("master", 2, tpu=False, extra_env=env)
    try:
        port = _wait_port(master)
        slaves = [_spawn("slave", port, tpu=False, extra_env=env,
                         tag="slave%d" % i) for i in range(2)]
        _wait_event(master, "running", 900)
        # let the scorer learn each slave's beat cadence (gap EWMA
        # needs a few observed intervals) and the epoch get going
        time.sleep(max(4 * hb, 1.0))
        victim = slaves[1]
        t_pause = time.time()
        os.kill(victim.pid, signal.SIGSTOP)
        try:
            attrs = _wait_event(master, "straggler", 60)
        finally:
            os.kill(victim.pid, signal.SIGCONT)
        detect_s = float(attrs["t"]) - t_pause
        intervals = detect_s / hb
        budget_s = 3 * hb + 0.75
        report = {"mode": "chaos_straggler", "config": "fc",
                  "heartbeat_interval_s": hb,
                  "time_to_detection_s": round(detect_s, 3),
                  "heartbeat_intervals": round(intervals, 2),
                  "budget_s": budget_s,
                  "straggler": attrs.get("sid"),
                  "score": float(attrs.get("score", 0.0))}
        print(json.dumps(report))
        if detect_s > budget_s:
            raise SystemExit(
                "straggler detected after %.2fs (> %.2fs = 3 heartbeat "
                "intervals + grace)" % (detect_s, budget_s))
        print("chaos straggler leg PASSED: flagged %s in %.2fs "
              "(%.1f heartbeat intervals)"
              % (attrs.get("sid"), detect_s, intervals),
              file=sys.stderr)
    finally:
        # detection is the artifact; the paused epoch is not worth
        # waiting out — tear the legs down
        for proc in [master] + [s for s in locals().get("slaves", [])]:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def orchestrate_chaos_kill():
    """``--chaos kill`` (ISSUE 12): master + 2 CPU slaves on the FC
    config; once the run is in steady state, SIGKILL one slave
    MID-EPOCH. The master must requeue the dead slave's in-flight
    jobs onto the survivor and complete EVERY epoch; the leg measures
    time-to-drop (fault -> jobs requeued) and time-to-recovery
    (fault -> first post-fault result merged)."""
    import signal

    hb = float(os.environ.get("VELES_DIST_HB", 0.5))
    env = {"VELES_DIST_CONFIG": "fc", "VELES_DIST_HB": str(hb),
           "VELES_DIST_HBT": os.environ.get("VELES_DIST_HBT", "2.0"),
           "VELES_DIST_CHAOS": "kill"}
    master = _spawn("master", 2, tpu=False, extra_env=env)
    slaves = []
    try:
        port = _wait_port(master)
        slaves = [_spawn("slave", port, tpu=False, extra_env=env,
                         tag="slave%d" % i) for i in range(2)]
        _wait_event(master, "running", 900)
        # let the run reach steady state, then kill INSIDE an epoch
        # (epochs are served continuously, so any instant is mid-some-
        # epoch once the first job landed)
        _wait_event(master, "epoch", 900)
        victim = slaves[1]
        t_kill = time.time()
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait()
        drop = _wait_event(master, "drop", 60)
        recovered = _wait_event(master, "recovered", 120)
        dist = _drain(master, "master")
        survivor = _drain(slaves[0], "slave0", timeout=60)
        detect_s = float(drop["t"]) - t_kill
        recovery_s = float(recovered["t"]) - t_kill
        report = {"mode": "chaos_kill", "config": "fc",
                  "heartbeat_interval_s": hb,
                  "time_to_drop_s": round(detect_s, 3),
                  "time_to_recovery_s": round(recovery_s, 3),
                  "epochs_completed": dist["epochs"],
                  "epochs_expected": EPOCHS,
                  "survivor_ok": bool(survivor and survivor.get("ok"))}
        print(json.dumps(report))
        if dist["epochs"] != EPOCHS:
            raise SystemExit(
                "kill-mid-epoch run completed %d/%d epochs — the "
                "recovery plane lost work" % (dist["epochs"], EPOCHS))
        print("chaos kill leg PASSED: drop in %.2fs, recovery in "
              "%.2fs, %d/%d epochs with the survivor"
              % (detect_s, recovery_s, dist["epochs"], EPOCHS),
              file=sys.stderr)
    finally:
        for proc in [master] + slaves:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def orchestrate_chaos_master_restart():
    """``--chaos master-restart`` (ISSUE 12): the master checkpoints
    every closed epoch into an auto-resume directory; after the first
    snapshot it is SIGKILL'd and a replacement master starts on the
    SAME port. The slaves must re-handshake through exponential
    backoff (VELES_RECONNECT_S) and the restored run must complete
    every remaining epoch — zero hung processes."""
    import signal
    import tempfile

    hb = float(os.environ.get("VELES_DIST_HB", 0.5))
    snapdir = tempfile.mkdtemp(prefix="veles_chaos_resume_")
    env = {"VELES_DIST_CONFIG": "fc", "VELES_DIST_HB": str(hb),
           "VELES_DIST_HBT": os.environ.get("VELES_DIST_HBT", "2.0"),
           "VELES_DIST_CHAOS": "master-restart",
           "VELES_AUTO_RESUME": snapdir}
    # the reconnect budget must cover the replacement master's startup
    # (~20 s CPU init) but stay BELOW the drain timeout: a slave whose
    # last job outlives the master's end-of-run drain grace redials
    # for the full budget before exiting
    slave_env = dict(env, VELES_RECONNECT_S="60")
    master1 = _spawn("master", 2, tpu=False, extra_env=env)
    master2 = None
    slaves = []
    try:
        port = _wait_port(master1)
        slaves = [_spawn("slave", port, tpu=False, extra_env=slave_env,
                         tag="slave%d" % i) for i in range(2)]
        _wait_event(master1, "running", 900)
        first = _wait_event(master1, "epoch", 900)
        # the snapshot lands in result_sink right after the close the
        # EVENT announced — wait for the artifact itself
        deadline = time.time() + 60
        while not any("_current" in name
                      for name in os.listdir(snapdir)):
            if time.time() > deadline:
                raise RuntimeError("no snapshot appeared in %s"
                                   % snapdir)
            time.sleep(0.1)
        t_kill = time.time()
        os.kill(master1.pid, signal.SIGKILL)
        master1.wait()
        master2 = _spawn("master", 2, port, tpu=False, extra_env=env,
                         tag="master2")
        resumed = _wait_event(master2, "resumed", 300)
        dist = _drain(master2, "master2")
        slave_oks = []
        for i, proc in enumerate(slaves):
            # > the 60 s reconnect budget: a slave whose final compile
            # outlived the master's drain grace exits within budget
            leg = _drain(proc, "slave%d" % i, timeout=120)
            slave_oks.append(bool(leg and leg.get("ok")))
        recovery_s = float(resumed["t"]) - t_kill
        report = {"mode": "chaos_master_restart", "config": "fc",
                  "heartbeat_interval_s": hb,
                  "epochs_before_kill": int(first["n"]),
                  "resumed_with_epochs": int(resumed["n"]),
                  "time_to_resume_s": round(recovery_s, 3),
                  "epochs_completed": dist["epochs"],
                  "epochs_expected": EPOCHS,
                  "slaves_reconnected": slave_oks}
        print(json.dumps(report))
        if dist["epochs"] != EPOCHS or not all(slave_oks):
            raise SystemExit(
                "master-restart run completed %d/%d epochs, slaves "
                "ok=%s" % (dist["epochs"], EPOCHS, slave_oks))
        print("chaos master-restart leg PASSED: resumed with %d "
              "epoch(s) in %.2fs, finished %d/%d, both slaves "
              "reconnected and exited cleanly"
              % (int(resumed["n"]), recovery_s, dist["epochs"],
                 EPOCHS), file=sys.stderr)
    finally:
        for proc in [master1, master2] + slaves:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
        import shutil
        shutil.rmtree(snapdir, ignore_errors=True)


def orchestrate_chaos_spmd_kill():
    """``--chaos spmd-kill`` (ISSUE 13): the SPMD-mesh analog of
    ``--chaos kill``. A rendezvous anchor plus TWO supervised
    ``jax.distributed`` DP worker processes (4 virtual CPU devices
    each, one 8-way data mesh) train the demo config with per-epoch
    sharded checkpoints; once the first epoch's generation commits,
    rank 1's SUPERVISOR and worker are both SIGKILLed (a whole-host
    loss — detection is the kernel-closed rendezvous socket). The
    surviving supervisor must kill its wedged worker, re-form the
    mesh at world size 1, restore the last complete generation and
    finish EVERY epoch; measured are time-to-reform (kill -> new
    generation running) and the server's break->formed recovery."""
    import signal
    import tempfile

    from veles_tpu.parallel.elastic import RendezvousServer

    epochs = int(os.environ.get("VELES_DIST_EPOCHS", 6))
    workdir = tempfile.mkdtemp(prefix="veles_spmd_chaos_")
    snaps = os.path.join(workdir, "snaps")
    outs = [os.path.join(workdir, "h%d.json" % i) for i in range(2)]
    env_base = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env_base["PYTHONPATH"] = HERE + (
        os.pathsep + env_base["PYTHONPATH"]
        if env_base.get("PYTHONPATH") else "")
    server = RendezvousServer(expected=2, min_workers=1, settle_s=0.5,
                              heartbeat_timeout_s=3.0).start()
    addr = "%s:%d" % server.address
    procs = []

    def worker_pid(proc, gen):
        for name, kv in proc.events:
            if name == "spmd_worker" and kv.get("gen") == str(gen):
                return int(kv["pid"])
        return None

    try:
        for i in range(2):
            cmd = [sys.executable, "-m",
                   "veles_tpu.parallel.elastic", "supervise",
                   "--rdzv", addr, "--member", "h%d" % i,
                   "--snapshots", snaps,
                   "--max-restarts", "3" if i == 0 else "0",
                   "--worker-env", "JAX_PLATFORMS=cpu",
                   "--worker-env",
                   "XLA_FLAGS=--xla_force_host_platform_device_count=4",
                   "--", sys.executable, "-m",
                   "veles_tpu.parallel.elastic", "worker-demo",
                   "--out", outs[i], "--epochs", str(epochs),
                   "--epoch-sleep", "0.5"]
            procs.append(_spawn("supervise", tpu=False,
                                extra_env=env_base,
                                tag="sup%d" % i, argv=cmd))
        # wait for the first post-epoch generation to COMMIT, so the
        # kill provably lands mid-run with a restorable checkpoint
        deadline = time.time() + 600
        while time.time() < deadline:
            done = [d for d in (os.listdir(snaps)
                                if os.path.isdir(snaps) else [])
                    if d.endswith(".shards") and
                    int(d.split(".")[-2]) >= 1 and
                    os.path.exists(os.path.join(snaps, d,
                                                "MANIFEST.json"))]
            if done:
                break
            if any(p.poll() is not None for p in procs):
                raise SystemExit("a supervisor died before the first "
                                 "checkpoint committed")
            time.sleep(0.1)
        else:
            raise SystemExit("no epoch-1 checkpoint within 600s")
        victim_worker = worker_pid(procs[1], 0)
        t_kill = time.time()
        os.kill(procs[1].pid, signal.SIGKILL)  # the "host" dies...
        if victim_worker:
            try:  # ...taking its worker process group with it
                os.killpg(victim_worker, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        procs[1].wait()
        print("EVENT spmd_kill t=%.6f" % t_kill, file=sys.stderr,
              flush=True)
        # survivor re-forms at world size 1
        while time.time() < deadline and not (
                server.generation >= 1 and server.phase in
                ("running", "done")):
            time.sleep(0.05)
        t_reform = time.time()
        rc0 = procs[0].wait(timeout=600)
        total_s = time.time() - t_kill
        history = json.load(open(outs[0]))
        report = {"mode": "chaos_spmd_kill", "epochs": epochs,
                  "time_to_reform_s": round(t_reform - t_kill, 3),
                  "reform_recovery_s":
                      round(server.last_recovery_s or -1, 3),
                  "kill_to_completion_s": round(total_s, 3),
                  "epochs_completed": len(history),
                  "world_after": server.world_size,
                  "participants_lost": server.lost_total,
                  "survivor_rc": rc0}
        print(json.dumps(report))
        if rc0 != 0:
            raise SystemExit("surviving supervisor exited rc=%d" % rc0)
        if len(history) != epochs:
            raise SystemExit(
                "spmd kill run completed %d/%d epochs — the recovery "
                "plane lost work" % (len(history), epochs))
        if server.world_size != 1 or server.lost_total < 1:
            raise SystemExit("mesh did not re-form at world size 1")
        print("chaos spmd-kill leg PASSED: re-formed at world 1 in "
              "%.2fs (server break->formed %.2fs), %d/%d epochs after "
              "restore" % (t_reform - t_kill,
                           server.last_recovery_s or -1,
                           len(history), epochs), file=sys.stderr)
    finally:
        server.stop()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        # orphaned workers die with their process groups
        for proc in procs:
            for gen in range(0, 8):
                pid = worker_pid(proc, gen)
                if pid:
                    try:
                        os.killpg(pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError,
                            OSError):
                        pass


def orchestrate_chip():
    env = {"VELES_DIST_CONFIG": CONFIG}
    alone = _drain(_spawn("standalone", tpu=True, extra_env=env),
                   "standalone")
    dist = _one_round(1, tpu_slave=True, config=CONFIG)
    table = {
        "mode": "chip", "config": CONFIG,
        "standalone_samples_per_sec": alone["samples_per_sec"],
        "distributed_1slave_samples_per_sec": dist["samples_per_sec"],
        "overhead_pct": round(
            100 * (1 - dist["samples_per_sec"] /
                   alone["samples_per_sec"]), 1),
        "segment_size": SEGMENT, "epochs": EPOCHS,
    }
    print(json.dumps(table))


def main():
    if os.environ.get("VELES_DIST_DEBUG"):
        import faulthandler
        faulthandler.dump_traceback_later(
            int(os.environ.get("VELES_DIST_DEBUG")), repeat=True,
            file=sys.stderr)
    if len(sys.argv) < 2:
        orchestrate_chip()
    elif sys.argv[1] == "--cpu-protocol":
        orchestrate_cpu_protocol()
    elif sys.argv[1] == "--gspmd":
        orchestrate_gspmd()
    elif sys.argv[1] == "gspmd-merge":
        run_gspmd_merge()
    elif sys.argv[1] == "--chaos":
        kind = sys.argv[2] if len(sys.argv) > 2 else "straggler"
        if kind == "straggler":
            orchestrate_chaos_straggler()
        elif kind == "kill":
            orchestrate_chaos_kill()
        elif kind == "master-restart":
            orchestrate_chaos_master_restart()
        elif kind == "spmd-kill":
            orchestrate_chaos_spmd_kill()
        else:
            raise SystemExit("unknown chaos kind %r" % kind)
    elif sys.argv[1] == "standalone":
        run_standalone()
    elif sys.argv[1] == "master":
        run_master(int(sys.argv[2]) if len(sys.argv) > 2 else 1,
                   int(sys.argv[3]) if len(sys.argv) > 3 else 0)
    elif sys.argv[1] == "slave":
        run_slave(int(sys.argv[2]))
    elif sys.argv[1] == "shmbench":
        run_shmbench()
    else:
        raise SystemExit("unknown mode %r" % sys.argv[1])


if __name__ == "__main__":
    sys.exit(main())
