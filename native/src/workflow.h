// Inference workflow: the loaded unit chain + arena-planned buffers.
// Mirrors libVeles Workflow::Initialize/Run (libVeles/src/workflow.cc:
// 73-123): Initialize packs unit output buffers into one arena via the
// MemoryOptimizer, Run executes the chain (batch-sharded on the
// ThreadPoolEngine).
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "engine.h"
#include "unit.h"

namespace veles_native {

class Workflow {
 public:
  explicit Workflow(std::shared_ptr<ThreadPoolEngine> engine = nullptr);

  void AddUnit(std::unique_ptr<Unit> unit);

  // Propagates shapes through the chain and plans the arena.
  void Initialize(const Shape& input_shape);

  // input: batch x input_size floats; returns batch x output_size.
  std::vector<float> Run(const float* input, int64_t batch) const;

  const Shape& input_shape() const { return input_shape_; }
  const Shape& output_shape() const;
  int64_t input_size() const { return ShapeSize(input_shape_); }
  int64_t output_size() const { return ShapeSize(output_shape()); }
  size_t unit_count() const { return units_.size(); }
  int64_t arena_size() const { return arena_size_; }

  std::string name;
  std::string checksum;

 private:
  std::shared_ptr<ThreadPoolEngine> engine_;
  std::vector<std::unique_ptr<Unit>> units_;
  std::vector<int64_t> offsets_;  // per-unit output offset in the arena
  Shape input_shape_;
  int64_t arena_size_ = 0;
  bool initialized_ = false;
};

}  // namespace veles_native
