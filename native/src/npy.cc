#include "npy.h"

#include <cstring>
#include <sstream>
#include <stdexcept>

namespace veles_native {
namespace {

const char kMagic[] = "\x93NUMPY";

// pulls 'key': value out of the python-dict-literal header
std::string HeaderField(const std::string& header, const std::string& key) {
  size_t at = header.find("'" + key + "'");
  if (at == std::string::npos) {
    throw std::runtime_error("npy header missing " + key);
  }
  at = header.find(':', at);
  size_t end = at + 1;
  int depth = 0;
  while (end < header.size()) {
    char c = header[end];
    if (c == '(' || c == '[') ++depth;
    if (c == ')' || c == ']') --depth;
    if ((c == ',' || c == '}') && depth <= 0) break;
    ++end;
  }
  std::string value = header.substr(at + 1, end - at - 1);
  // trim
  size_t a = value.find_first_not_of(" \t");
  size_t b = value.find_last_not_of(" \t");
  return a == std::string::npos ? "" : value.substr(a, b - a + 1);
}

template <typename T>
void Convert(const char* payload, int64_t count, std::vector<float>* out) {
  out->resize(count);
  const T* typed = reinterpret_cast<const T*>(payload);
  for (int64_t i = 0; i < count; ++i) {
    (*out)[i] = static_cast<float>(typed[i]);
  }
}

}  // namespace

NpyArray ParseNpy(const std::vector<char>& bytes) {
  if (bytes.size() < 10 || std::memcmp(bytes.data(), kMagic, 6) != 0) {
    throw std::runtime_error("not a .npy file");
  }
  uint8_t major = bytes[6];
  size_t header_len, header_at;
  if (major == 1) {
    header_len = static_cast<uint8_t>(bytes[8]) |
                 (static_cast<uint8_t>(bytes[9]) << 8);
    header_at = 10;
  } else if (major == 2 || major == 3) {
    if (bytes.size() < 12) {
      throw std::runtime_error("truncated .npy header");
    }
    uint32_t len;
    std::memcpy(&len, bytes.data() + 8, 4);
    header_len = len;
    header_at = 12;
  } else {
    throw std::runtime_error("unsupported .npy version");
  }
  if (header_at + header_len > bytes.size()) {
    throw std::runtime_error("truncated .npy header");
  }
  std::string header(bytes.data() + header_at, header_len);

  if (HeaderField(header, "fortran_order").find("True") !=
      std::string::npos) {
    throw std::runtime_error("fortran-order .npy not supported");
  }

  NpyArray result;
  std::string shape = HeaderField(header, "shape");
  std::stringstream ss(shape);
  char c;
  int64_t dim;
  while (ss >> c) {
    if (c == '(' || c == ',' || c == ')') continue;
    ss.putback(c);
    if (ss >> dim) result.shape.push_back(dim);
  }

  std::string descr = HeaderField(header, "descr");
  // strip quotes
  size_t q1 = descr.find('\'');
  size_t q2 = descr.rfind('\'');
  if (q1 != std::string::npos && q2 > q1) {
    descr = descr.substr(q1 + 1, q2 - q1 - 1);
  }
  if (!descr.empty() && descr[0] == '>') {
    throw std::runtime_error("big-endian .npy not supported");
  }
  std::string kind = descr.substr(descr.find_first_not_of("<=|"));

  const char* payload = bytes.data() + header_at + header_len;
  int64_t count = result.size();
  int64_t avail = static_cast<int64_t>(bytes.size()) -
                  static_cast<int64_t>(header_at + header_len);
  auto need = [&](int64_t bytes_per) {
    if (count * bytes_per > avail) {
      throw std::runtime_error("truncated .npy payload");
    }
  };
  if (kind == "f4") {
    need(4);
    Convert<float>(payload, count, &result.data);
  } else if (kind == "f8") {
    need(8);
    Convert<double>(payload, count, &result.data);
  } else if (kind == "i8") {
    need(8);
    Convert<int64_t>(payload, count, &result.data);
  } else if (kind == "i4") {
    need(4);
    Convert<int32_t>(payload, count, &result.data);
  } else if (kind == "i2") {
    need(2);
    Convert<int16_t>(payload, count, &result.data);
  } else if (kind == "i1") {
    need(1);
    Convert<int8_t>(payload, count, &result.data);
  } else if (kind == "u1") {
    need(1);
    Convert<uint8_t>(payload, count, &result.data);
  } else {
    throw std::runtime_error("unsupported .npy dtype: " + descr);
  }
  return result;
}

std::vector<char> WriteNpy(const std::vector<int64_t>& shape,
                           const float* data) {
  std::string shape_str = "(";
  for (size_t i = 0; i < shape.size(); ++i) {
    shape_str += std::to_string(shape[i]);
    shape_str += ", ";
  }
  if (shape.size() > 1) shape_str.resize(shape_str.size() - 1);  // keep ','
  shape_str += ")";
  std::string header = "{'descr': '<f4', 'fortran_order': False, 'shape': " +
                       shape_str + ", }";
  size_t total = 10 + header.size() + 1;
  size_t pad = (64 - total % 64) % 64;
  header += std::string(pad, ' ');
  header += '\n';

  int64_t count = 1;
  for (int64_t d : shape) count *= d;
  std::vector<char> out(10 + header.size() + count * sizeof(float));
  std::memcpy(out.data(), kMagic, 6);
  out[6] = 1;
  out[7] = 0;
  out[8] = static_cast<char>(header.size() & 0xFF);
  out[9] = static_cast<char>((header.size() >> 8) & 0xFF);
  std::memcpy(out.data() + 10, header.data(), header.size());
  std::memcpy(out.data() + 10 + header.size(), data,
              count * sizeof(float));
  return out;
}

}  // namespace veles_native
