#include "tar.h"

#include <sys/stat.h>

#include <cstring>
#include <dirent.h>
#include <fstream>
#include <stdexcept>

namespace veles_native {
namespace {

std::vector<char> ReadFile(const std::string& path) {
  std::ifstream in(path, std::ios::binary);
  if (!in) throw std::runtime_error("cannot open " + path);
  return std::vector<char>(std::istreambuf_iterator<char>(in),
                           std::istreambuf_iterator<char>());
}

int64_t ParseOctal(const char* field, size_t len) {
  int64_t value = 0;
  for (size_t i = 0; i < len && field[i]; ++i) {
    char c = field[i];
    if (c == ' ') continue;
    if (c < '0' || c > '7') break;
    value = value * 8 + (c - '0');
  }
  return value;
}

Archive ReadTar(const std::string& path) {
  std::vector<char> bytes = ReadFile(path);
  Archive archive;
  size_t at = 0;
  while (at + 512 <= bytes.size()) {
    const char* header = bytes.data() + at;
    if (header[0] == '\0') break;  // end-of-archive zero block
    std::string name(header, strnlen(header, 100));
    int64_t size = ParseOctal(header + 124, 12);
    char type = header[156];
    at += 512;
    if (at + size > bytes.size()) {
      throw std::runtime_error("truncated tar member " + name);
    }
    if (type == '0' || type == '\0') {  // regular file
      archive[name] = std::vector<char>(bytes.begin() + at,
                                        bytes.begin() + at + size);
    }
    at += (size + 511) / 512 * 512;  // payload is 512-padded
  }
  if (archive.empty()) {
    throw std::runtime_error("empty or invalid tar: " + path);
  }
  return archive;
}

Archive ReadDirectory(const std::string& path) {
  Archive archive;
  DIR* dir = opendir(path.c_str());
  if (dir == nullptr) throw std::runtime_error("cannot open " + path);
  struct dirent* entry;
  while ((entry = readdir(dir)) != nullptr) {
    std::string name = entry->d_name;
    if (name == "." || name == "..") continue;
    std::string full = path + "/" + name;
    struct stat st;
    if (stat(full.c_str(), &st) == 0 && S_ISREG(st.st_mode)) {
      archive[name] = ReadFile(full);
    }
  }
  closedir(dir);
  if (archive.empty()) {
    throw std::runtime_error("empty package directory: " + path);
  }
  return archive;
}

}  // namespace

Archive ReadPackage(const std::string& path) {
  struct stat st;
  if (stat(path.c_str(), &st) != 0) {
    throw std::runtime_error("no such package: " + path);
  }
  return S_ISDIR(st.st_mode) ? ReadDirectory(path) : ReadTar(path);
}

}  // namespace veles_native
