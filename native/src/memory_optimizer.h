// Static memory planner (libVeles/src/memory_optimizer.cc): unit
// output buffers are live intervals [time_start, time_finish) with a
// size; the planner packs them into one arena by first-fit offset
// assignment over conflicting intervals and returns the arena size.
#pragma once

#include <cstdint>
#include <vector>

namespace veles_native {

struct MemoryNode {
  int64_t time_start = 0;   // first step writing the buffer
  int64_t time_finish = 0;  // last step reading it (exclusive end)
  int64_t value = 0;        // floats needed
  int64_t position = -1;    // assigned arena offset (output)
};

class MemoryOptimizer {
 public:
  // Assigns node positions; returns the total arena size (floats).
  int64_t Optimize(std::vector<MemoryNode>* nodes) const;
};

}  // namespace veles_native
