// Built-in inference units (the libZnicz role: All2All/Conv/Pooling/
// activations — cf. docs/source/manualrst_veles_algorithms.rst).
//
// Numerics deliberately mirror veles_tpu/nn/*.py so the native runtime
// reproduces the JAX forward pass: LeCun-scaled tanh, softplus "relu"
// with the 15.0 clamp, max-subtracted softmax, NHWC/HWIO convolution,
// full-window average pooling, AlexNet cross-channel LRN.

#include <algorithm>
#include <cmath>
#include <cstring>
#include <stdexcept>

#include "unit.h"

namespace veles_native {
namespace {

// ---------------------------------------------------------------- activations

using ActFn = float (*)(float);

float ActLinear(float x) { return x; }
float ActTanh(float x) { return 1.7159f * std::tanh(0.6666f * x); }
float ActSigmoid(float x) { return 1.0f / (1.0f + std::exp(-x)); }
float ActReluSoft(float x) {
  // Znicz "RELU": log(1+exp(x)), clamped like the Python side
  return x > 15.0f ? x : std::log1p(std::exp(std::min(x, 15.0f)));
}
float ActReluStrict(float x) { return std::max(x, 0.0f); }
float ActLeakyRelu(float x) { return x >= 0.0f ? x : 0.01f * x; }
float ActLog(float x) { return std::log(x + std::sqrt(x * x + 1.0f)); }

ActFn ActivationByName(const std::string& name) {
  if (name == "linear" || name.empty()) return ActLinear;
  if (name == "tanh") return ActTanh;
  if (name == "sigmoid") return ActSigmoid;
  if (name == "relu") return ActReluSoft;
  if (name == "strict_relu") return ActReluStrict;
  if (name == "leaky_relu") return ActLeakyRelu;
  if (name == "log") return ActLog;
  throw std::runtime_error("unknown activation: " + name);
}

// sincos works on channel indices (odd -> sin, even -> cos), so it
// can't be a scalar ActFn; applied over rows whose last dim is known
void ApplySinCos(float* data, int64_t count, int64_t last_dim) {
  for (int64_t i = 0; i < count; ++i) {
    data[i] = (i % last_dim) % 2 == 1 ? std::sin(data[i])
                                      : std::cos(data[i]);
  }
}

void Softmax(float* row, int64_t n) {
  float mx = row[0];
  for (int64_t i = 1; i < n; ++i) mx = std::max(mx, row[i]);
  float sum = 0.0f;
  for (int64_t i = 0; i < n; ++i) {
    row[i] = std::exp(row[i] - mx);
    sum += row[i];
  }
  for (int64_t i = 0; i < n; ++i) row[i] /= sum;
}

// ------------------------------------------------------------------- All2All

class All2AllUnit : public Unit {
 public:
  const char* Name() const override { return "All2All"; }

  Shape Initialize(const Shape& input_shape) override {
    input_shape_ = input_shape;
    const NpyArray* w = Array("weights");
    if (w == nullptr || w->shape.size() != 2) {
      throw std::runtime_error("All2All needs 2-D weights");
    }
    in_features_ = w->shape[0];
    out_features_ = w->shape[1];
    if (ShapeSize(input_shape) != in_features_) {
      throw std::runtime_error("All2All input/weights shape mismatch");
    }
    activation_name_ = StrParam("activation", "linear");
    if (activation_name_ != "softmax" && activation_name_ != "sincos") {
      act_ = ActivationByName(activation_name_);
    }
    output_shape_ = IntListParam("output_sample_shape");
    if (output_shape_.empty()) output_shape_ = {out_features_};
    if (ShapeSize(output_shape_) != out_features_) {
      throw std::runtime_error("output_sample_shape/weights mismatch");
    }
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    const float* w = Array("weights")->data.data();
    const NpyArray* bias = Array("bias");
    for (int64_t b = 0; b < batch; ++b) {
      float* out_row = output + b * out_features_;
      const float* in_row = input + b * in_features_;
      if (bias != nullptr) {
        std::memcpy(out_row, bias->data.data(),
                    out_features_ * sizeof(float));
      } else {
        std::fill(out_row, out_row + out_features_, 0.0f);
      }
      // i-k-j: streams the weight rows, accumulates into out_row
      for (int64_t k = 0; k < in_features_; ++k) {
        float x = in_row[k];
        if (x == 0.0f) continue;
        const float* w_row = w + k * out_features_;
        for (int64_t j = 0; j < out_features_; ++j) {
          out_row[j] += x * w_row[j];
        }
      }
      if (activation_name_ == "softmax") {
        Softmax(out_row, out_features_);
      } else if (activation_name_ == "sincos") {
        ApplySinCos(out_row, out_features_, out_features_);
      } else {
        for (int64_t j = 0; j < out_features_; ++j) {
          out_row[j] = act_(out_row[j]);
        }
      }
    }
  }

 private:
  int64_t in_features_ = 0, out_features_ = 0;
  std::string activation_name_;
  ActFn act_ = ActLinear;
};

// ---------------------------------------------------------------------- Conv

class ConvUnit : public Unit {
 public:
  const char* Name() const override { return "Conv"; }

  Shape Initialize(const Shape& input_shape) override {
    input_shape_ = input_shape;
    // grayscale HW -> HWC with one channel (matches the Python x[..., None])
    h_ = input_shape[0];
    w_ = input_shape[1];
    c_ = input_shape.size() >= 3 ? input_shape[2] : 1;
    const NpyArray* w = Array("weights");
    if (w == nullptr || w->shape.size() != 4) {
      throw std::runtime_error("Conv needs HWIO weights");
    }
    ky_ = w->shape[0];
    kx_ = w->shape[1];
    if (w->shape[2] != c_) {
      throw std::runtime_error("Conv channels mismatch");
    }
    n_kernels_ = w->shape[3];
    auto sliding = IntListParam("sliding");
    sx_ = sliding.size() > 0 ? sliding[0] : 1;
    sy_ = sliding.size() > 1 ? sliding[1] : 1;
    ResolvePadding();
    out_h_ = (h_ + pad_top_ + pad_bottom_ - ky_) / sy_ + 1;
    out_w_ = (w_ + pad_left_ + pad_right_ - kx_) / sx_ + 1;
    if (out_h_ <= 0 || out_w_ <= 0) {
      throw std::runtime_error("Conv output would be empty");
    }
    activation_ = ActivationByName(StrParam("activation", "linear"));
    output_shape_ = {out_h_, out_w_, n_kernels_};
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    const float* weights = Array("weights")->data.data();
    const NpyArray* bias = Array("bias");
    int64_t in_size = h_ * w_ * c_;
    int64_t out_size = out_h_ * out_w_ * n_kernels_;
    for (int64_t b = 0; b < batch; ++b) {
      const float* x = input + b * in_size;
      float* y = output + b * out_size;
      for (int64_t oy = 0; oy < out_h_; ++oy) {
        for (int64_t ox = 0; ox < out_w_; ++ox) {
          float* cell = y + (oy * out_w_ + ox) * n_kernels_;
          if (bias != nullptr) {
            std::memcpy(cell, bias->data.data(),
                        n_kernels_ * sizeof(float));
          } else {
            std::fill(cell, cell + n_kernels_, 0.0f);
          }
          for (int64_t fy = 0; fy < ky_; ++fy) {
            int64_t iy = oy * sy_ + fy - pad_top_;
            if (iy < 0 || iy >= h_) continue;
            for (int64_t fx = 0; fx < kx_; ++fx) {
              int64_t ix = ox * sx_ + fx - pad_left_;
              if (ix < 0 || ix >= w_) continue;
              const float* px = x + (iy * w_ + ix) * c_;
              const float* wk = weights + ((fy * kx_ + fx) * c_) *
                                              n_kernels_;
              for (int64_t ci = 0; ci < c_; ++ci) {
                float v = px[ci];
                if (v == 0.0f) continue;
                const float* w_row = wk + ci * n_kernels_;
                for (int64_t k = 0; k < n_kernels_; ++k) {
                  cell[k] += v * w_row[k];
                }
              }
            }
          }
          for (int64_t k = 0; k < n_kernels_; ++k) {
            cell[k] = activation_(cell[k]);
          }
        }
      }
    }
  }

 private:
  void ResolvePadding() {
    pad_left_ = pad_top_ = pad_right_ = pad_bottom_ = 0;
    auto it = params_.find("padding");
    if (it == params_.end()) return;
    if (it->second.is_string()) {
      const std::string& mode = it->second.as_string();
      if (mode == "VALID") return;
      if (mode == "SAME") {
        // XLA SAME: out = ceil(in / stride), pad split low-first
        int64_t out_h = (h_ + sy_ - 1) / sy_;
        int64_t out_w = (w_ + sx_ - 1) / sx_;
        int64_t total_h =
            std::max<int64_t>((out_h - 1) * sy_ + ky_ - h_, 0);
        int64_t total_w =
            std::max<int64_t>((out_w - 1) * sx_ + kx_ - w_, 0);
        pad_top_ = total_h / 2;
        pad_bottom_ = total_h - pad_top_;
        pad_left_ = total_w / 2;
        pad_right_ = total_w - pad_left_;
        return;
      }
      throw std::runtime_error("unknown padding mode: " + mode);
    }
    auto pads = IntListParam("padding");  // [left, top, right, bottom]
    if (pads.size() == 4) {
      pad_left_ = pads[0];
      pad_top_ = pads[1];
      pad_right_ = pads[2];
      pad_bottom_ = pads[3];
    }
  }

  int64_t h_ = 0, w_ = 0, c_ = 0;
  int64_t ky_ = 0, kx_ = 0, n_kernels_ = 0;
  int64_t sx_ = 1, sy_ = 1;
  int64_t pad_left_ = 0, pad_top_ = 0, pad_right_ = 0, pad_bottom_ = 0;
  int64_t out_h_ = 0, out_w_ = 0;
  ActFn activation_ = ActLinear;
};

// ------------------------------------------------------------------- pooling

enum class PoolKind { Max, MaxAbs, Avg };

template <PoolKind kKind>
class PoolingUnit : public Unit {
 public:
  const char* Name() const override { return "Pooling"; }

  Shape Initialize(const Shape& input_shape) override {
    input_shape_ = input_shape;
    h_ = input_shape[0];
    w_ = input_shape[1];
    c_ = input_shape.size() >= 3 ? input_shape[2] : 1;
    kx_ = static_cast<int64_t>(Param("kx", 2));
    ky_ = static_cast<int64_t>(Param("ky", 2));
    auto sliding = IntListParam("sliding");
    sx_ = sliding.size() > 0 ? sliding[0] : kx_;
    sy_ = sliding.size() > 1 ? sliding[1] : ky_;
    out_h_ = (h_ - ky_) / sy_ + 1;
    out_w_ = (w_ - kx_) / sx_ + 1;
    if (out_h_ <= 0 || out_w_ <= 0) {
      throw std::runtime_error("pooling output would be empty");
    }
    output_shape_ = {out_h_, out_w_, c_};
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    int64_t in_size = h_ * w_ * c_;
    int64_t out_size = out_h_ * out_w_ * c_;
    for (int64_t b = 0; b < batch; ++b) {
      const float* x = input + b * in_size;
      float* y = output + b * out_size;
      for (int64_t oy = 0; oy < out_h_; ++oy) {
        for (int64_t ox = 0; ox < out_w_; ++ox) {
          for (int64_t ci = 0; ci < c_; ++ci) {
            float mx = -INFINITY, mn = INFINITY, sum = 0.0f;
            for (int64_t fy = 0; fy < ky_; ++fy) {
              for (int64_t fx = 0; fx < kx_; ++fx) {
                float v = x[((oy * sy_ + fy) * w_ + ox * sx_ + fx) * c_ +
                            ci];
                mx = std::max(mx, v);
                mn = std::min(mn, v);
                sum += v;
              }
            }
            float result;
            if constexpr (kKind == PoolKind::Max) {
              result = mx;
            } else if constexpr (kKind == PoolKind::MaxAbs) {
              result = mx >= -mn ? mx : mn;
            } else {
              result = sum / static_cast<float>(kx_ * ky_);
            }
            y[(oy * out_w_ + ox) * c_ + ci] = result;
          }
        }
      }
    }
  }

 private:
  int64_t h_ = 0, w_ = 0, c_ = 0;
  int64_t kx_ = 2, ky_ = 2, sx_ = 2, sy_ = 2;
  int64_t out_h_ = 0, out_w_ = 0;
};

// ----------------------------------------------------------------------- LRN

class LrnUnit : public Unit {
 public:
  const char* Name() const override { return "LRNormalizerForward"; }

  Shape Initialize(const Shape& input_shape) override {
    input_shape_ = input_shape;
    output_shape_ = input_shape;
    k_ = static_cast<float>(Param("k", 2.0));
    alpha_ = static_cast<float>(Param("alpha", 1e-4));
    beta_ = static_cast<float>(Param("beta", 0.75));
    n_ = static_cast<int64_t>(Param("n", 5));
    channels_ = input_shape.back();
    pixels_ = ShapeSize(input_shape) / channels_;
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    int64_t half = n_ / 2;
    int64_t size = pixels_ * channels_;
    for (int64_t b = 0; b < batch; ++b) {
      const float* x = input + b * size;
      float* y = output + b * size;
      for (int64_t p = 0; p < pixels_; ++p) {
        const float* px = x + p * channels_;
        float* py = y + p * channels_;
        for (int64_t ci = 0; ci < channels_; ++ci) {
          // the JAX reference sums exactly n shifted slices of a
          // half=n/2 zero-padded axis: window = [ci-half, ci-half+n-1]
          // (asymmetric for even n) — mirror that, not ci±half
          float window = 0.0f;
          int64_t lo = std::max<int64_t>(0, ci - half);
          int64_t hi = std::min(channels_ - 1, ci - half + n_ - 1);
          for (int64_t j = lo; j <= hi; ++j) {
            window += px[j] * px[j];
          }
          py[ci] = px[ci] / std::pow(k_ + alpha_ * window, beta_);
        }
      }
    }
  }

 private:
  float k_ = 2.0f, alpha_ = 1e-4f, beta_ = 0.75f;
  int64_t n_ = 5, channels_ = 0, pixels_ = 0;
};

// ------------------------------------------------------ activation / identity

class ActivationUnitImpl : public Unit {
 public:
  const char* Name() const override { return "ActivationUnit"; }

  Shape Initialize(const Shape& input_shape) override {
    input_shape_ = input_shape;
    output_shape_ = input_shape;
    name_ = StrParam("activation", "linear");
    if (name_ != "sincos") {
      act_ = ActivationByName(name_);
    }
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    int64_t count = batch * ShapeSize(input_shape_);
    if (name_ == "sincos") {
      std::memcpy(output, input, count * sizeof(float));
      ApplySinCos(output, count, input_shape_.back());
      return;
    }
    for (int64_t i = 0; i < count; ++i) output[i] = act_(input[i]);
  }

 private:
  std::string name_;
  ActFn act_ = ActLinear;
};

// ----------------------------------------------------- MultiHeadAttention

class AttentionUnit : public Unit {
 public:
  const char* Name() const override { return "MultiHeadAttention"; }

  Shape Initialize(const Shape& input_shape) override {
    input_shape_ = input_shape;
    if (input_shape.size() != 2) {
      throw std::runtime_error("attention needs (seq, dim) samples");
    }
    seq_ = input_shape[0];
    dim_ = input_shape[1];
    const NpyArray* w = Array("weights");
    if (w == nullptr || w->shape.size() != 3 || w->shape[0] != 4 ||
        w->shape[1] != dim_ || w->shape[2] != dim_) {
      throw std::runtime_error("attention needs (4, dim, dim) weights");
    }
    heads_ = static_cast<int64_t>(Param("heads", 4));
    if (heads_ <= 0 || dim_ % heads_ != 0) {
      throw std::runtime_error("dim not divisible by heads");
    }
    head_dim_ = dim_ / heads_;
    causal_ = Param("causal", 0) != 0;
    residual_ = Param("residual", 1) != 0;
    output_shape_ = input_shape;
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    const float* w = Array("weights")->data.data();
    const NpyArray* bias = Array("bias");
    const float* b = bias != nullptr ? bias->data.data() : nullptr;
    const float scale = 1.0f / std::sqrt(
        static_cast<float>(head_dim_));
    const int64_t plane = seq_ * dim_;
    std::vector<float> q(plane), k(plane), v(plane), merged(plane);
    std::vector<float> scores(seq_);
    for (int64_t bi = 0; bi < batch; ++bi) {
      const float* x = input + bi * plane;
      Project(x, w + 0 * dim_ * dim_, b ? b + 0 * dim_ : nullptr,
              q.data());
      Project(x, w + 1 * dim_ * dim_, b ? b + 1 * dim_ : nullptr,
              k.data());
      Project(x, w + 2 * dim_ * dim_, b ? b + 2 * dim_ : nullptr,
              v.data());
      for (int64_t h = 0; h < heads_; ++h) {
        const int64_t off = h * head_dim_;
        for (int64_t i = 0; i < seq_; ++i) {
          const float* qi = q.data() + i * dim_ + off;
          const int64_t limit = causal_ ? i + 1 : seq_;
          for (int64_t j = 0; j < limit; ++j) {
            const float* kj = k.data() + j * dim_ + off;
            float dot = 0.0f;
            for (int64_t d = 0; d < head_dim_; ++d) dot += qi[d] * kj[d];
            scores[j] = dot * scale;
          }
          Softmax(scores.data(), limit);
          float* out_i = merged.data() + i * dim_ + off;
          std::fill(out_i, out_i + head_dim_, 0.0f);
          for (int64_t j = 0; j < limit; ++j) {
            const float* vj = v.data() + j * dim_ + off;
            const float p = scores[j];
            for (int64_t d = 0; d < head_dim_; ++d) out_i[d] += p * vj[d];
          }
        }
      }
      float* out = output + bi * plane;
      Project(merged.data(), w + 3 * dim_ * dim_,
              b ? b + 3 * dim_ : nullptr, out);
      if (residual_) {
        for (int64_t i = 0; i < plane; ++i) out[i] += x[i];
      }
    }
  }

 private:
  // (seq, dim) x (dim, dim) + bias -> (seq, dim)
  void Project(const float* x, const float* w, const float* bias,
               float* out) const {
    for (int64_t s = 0; s < seq_; ++s) {
      float* row = out + s * dim_;
      if (bias != nullptr) {
        std::memcpy(row, bias, dim_ * sizeof(float));
      } else {
        std::fill(row, row + dim_, 0.0f);
      }
      const float* xin = x + s * dim_;
      for (int64_t kk = 0; kk < dim_; ++kk) {
        const float xv = xin[kk];
        if (xv == 0.0f) continue;
        const float* w_row = w + kk * dim_;
        for (int64_t j = 0; j < dim_; ++j) row[j] += xv * w_row[j];
      }
    }
  }

  int64_t seq_ = 0, dim_ = 0, heads_ = 0, head_dim_ = 0;
  bool causal_ = false, residual_ = true;
};

// ------------------------------------------------------------------ MoE

// Switch-style top-1 mixture-of-experts FFN; numerics mirror
// veles_tpu/nn/moe.py::MoEForward's dense path: capacity pools PER
// SAMPLE (batch-composition-independent inference), first-come
// capacity, strict-relu hidden, gate-probability scaled output,
// optional residual.
class MoEUnit : public Unit {
 public:
  const char* Name() const override { return "MoE"; }

  Shape Initialize(const Shape& input_shape) override {
    if (input_shape.empty()) {
      throw std::runtime_error("moe needs (..., dim) samples");
    }
    dim_ = input_shape.back();
    tokens_per_sample_ = 1;
    for (size_t i = 0; i + 1 < input_shape.size(); ++i) {
      tokens_per_sample_ *= input_shape[i];
    }
    n_experts_ = static_cast<int64_t>(Param("n_experts", 0));
    const NpyArray* router = Array("weights");
    if (router == nullptr || router->shape.size() != 2 ||
        router->shape[0] != dim_ || router->shape[1] != n_experts_) {
      throw std::runtime_error("moe needs (dim, n_experts) router");
    }
    const NpyArray* up = Array("up");
    if (up == nullptr || up->shape.size() != 3 ||
        up->shape[0] != n_experts_ || up->shape[1] != dim_) {
      throw std::runtime_error("moe needs (E, dim, hidden) up");
    }
    hidden_ = up->shape[2];
    const NpyArray* down = Array("down");
    if (down == nullptr || down->shape.size() != 3 ||
        down->shape[0] != n_experts_ || down->shape[1] != hidden_ ||
        down->shape[2] != dim_) {
      throw std::runtime_error("moe needs (E, hidden, dim) down");
    }
    // keep double: float(0.9) = 0.89999997 would shift the ceil below
    // by one and drop a token the Python side keeps
    capacity_factor_ = Param("capacity_factor", 1.25);
    residual_ = Param("residual", 1) != 0;
    output_shape_ = input_shape;
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    const float* router = Array("weights")->data.data();
    const float* up = Array("up")->data.data();
    const float* down = Array("down")->data.data();
    // ceil(T * cf / E), at least 1 — per SAMPLE, like the Python
    // dense path (the engine calls Execute per sample, but a batched
    // caller must see identical routing)
    const int64_t capacity = std::max<int64_t>(
        1, static_cast<int64_t>(
               std::ceil(static_cast<double>(tokens_per_sample_) *
                         capacity_factor_ / n_experts_)));
    std::vector<float> logits(n_experts_);
    std::vector<float> h(hidden_);
    std::vector<int64_t> used(n_experts_);
    for (int64_t bi = 0; bi < batch; ++bi) {
      std::fill(used.begin(), used.end(), 0);
      for (int64_t ti = 0; ti < tokens_per_sample_; ++ti) {
      const int64_t t = bi * tokens_per_sample_ + ti;
      const float* x = input + t * dim_;
      float* out = output + t * dim_;
      for (int64_t e = 0; e < n_experts_; ++e) {
        float dot = 0.0f;
        for (int64_t d = 0; d < dim_; ++d) {
          dot += x[d] * router[d * n_experts_ + e];
        }
        logits[e] = dot;
      }
      Softmax(logits.data(), n_experts_);
      int64_t expert = 0;
      for (int64_t e = 1; e < n_experts_; ++e) {
        if (logits[e] > logits[expert]) expert = e;
      }
      const float gate = logits[expert];
      const bool kept = used[expert]++ < capacity;
      if (!kept) {
        std::fill(out, out + dim_, 0.0f);
      } else {
        const float* w_up = up + expert * dim_ * hidden_;
        const float* w_dn = down + expert * hidden_ * dim_;
        std::fill(h.begin(), h.end(), 0.0f);
        for (int64_t d = 0; d < dim_; ++d) {
          const float xv = x[d];
          if (xv == 0.0f) continue;
          const float* row = w_up + d * hidden_;
          for (int64_t j = 0; j < hidden_; ++j) h[j] += xv * row[j];
        }
        for (int64_t j = 0; j < hidden_; ++j) {
          h[j] = std::max(h[j], 0.0f);  // jax.nn.relu
        }
        std::fill(out, out + dim_, 0.0f);
        for (int64_t j = 0; j < hidden_; ++j) {
          const float hv = h[j] * gate;
          if (hv == 0.0f) continue;
          const float* row = w_dn + j * dim_;
          for (int64_t d = 0; d < dim_; ++d) out[d] += hv * row[d];
        }
      }
      if (residual_) {
        for (int64_t d = 0; d < dim_; ++d) out[d] += x[d];
      }
      }
    }
  }

 private:
  int64_t dim_ = 0, tokens_per_sample_ = 1, n_experts_ = 0, hidden_ = 0;
  double capacity_factor_ = 1.25;
  bool residual_ = true;
};

class IdentityUnit : public Unit {
 public:
  const char* Name() const override { return "Identity"; }

  Shape Initialize(const Shape& input_shape) override {
    input_shape_ = input_shape;
    output_shape_ = input_shape;
    return output_shape_;
  }

  void Execute(const float* input, float* output,
               int64_t batch) const override {
    std::memcpy(output, input,
                batch * ShapeSize(input_shape_) * sizeof(float));
  }
};

template <typename T>
std::unique_ptr<Unit> Make() {
  return std::make_unique<T>();
}

}  // namespace

void RegisterBuiltinUnits() {
  UnitFactory& f = UnitFactory::Instance();
  for (const char* name :
       {"All2All", "All2AllTanh", "All2AllRELU", "All2AllStrictRELU",
        "All2AllSigmoid", "All2AllSoftmax"}) {
    f.Register(name, Make<All2AllUnit>);
  }
  for (const char* name :
       {"Conv", "ConvTanh", "ConvRELU", "ConvStrictRELU", "ConvSigmoid"}) {
    f.Register(name, Make<ConvUnit>);
  }
  f.Register("MaxPooling", Make<PoolingUnit<PoolKind::Max>>);
  f.Register("MaxAbsPooling", Make<PoolingUnit<PoolKind::MaxAbs>>);
  f.Register("AvgPooling", Make<PoolingUnit<PoolKind::Avg>>);
  f.Register("LRNormalizerForward", Make<LrnUnit>);
  f.Register("ActivationUnit", Make<ActivationUnitImpl>);
  f.Register("DropoutForward", Make<IdentityUnit>);
  f.Register("MultiHeadAttentionForward", Make<AttentionUnit>);
  f.Register("MoEForward", Make<MoEUnit>);
  // stable uuid5(namespace, class name) ids matching the Python-side
  // UnitRegistry (veles_tpu/unit_registry.py); regenerate with:
  //   python -c "import uuid; ns=uuid.UUID('6ba7b812-9dad-11d1-80b4-
  //   00c04fd430c8'); print(uuid.uuid5(ns, 'All2All'))" etc.
  f.RegisterUuid("566dfbe9-c8bb-537c-bb78-c7aaa8a26c68", "All2All");
  f.RegisterUuid("33faa373-fa85-505a-9ecc-ff8ccceec52a", "All2AllTanh");
  f.RegisterUuid("1b65bb92-db95-5208-a23c-866194ea7160", "All2AllRELU");
  f.RegisterUuid("d1e6ae9f-5298-50be-82db-27dd0c0d10c3",
                 "All2AllStrictRELU");
  f.RegisterUuid("865cf10f-495b-5238-9cb6-c2f9464f2ce2",
                 "All2AllSigmoid");
  f.RegisterUuid("e3f0f557-d763-54a6-ab02-13700a47f98d",
                 "All2AllSoftmax");
  f.RegisterUuid("70497426-380b-558a-9812-b21bc9af9115", "Conv");
  f.RegisterUuid("d8b6ba41-4e7e-52fb-a607-e4a7d2be6e63", "ConvTanh");
  f.RegisterUuid("7a3a1752-5e26-5f63-898b-e29cc9c395c2", "ConvRELU");
  f.RegisterUuid("b0cf5c0d-c376-5657-af07-c77d728ce85d",
                 "ConvStrictRELU");
  f.RegisterUuid("1cb00dfb-daf2-57bb-95a9-bebecb4c9699", "ConvSigmoid");
  f.RegisterUuid("c5384cdb-2799-5687-b15d-c30e3268b499", "MaxPooling");
  f.RegisterUuid("b2a139d6-81ae-50ee-bf9c-381d0aa20054",
                 "MaxAbsPooling");
  f.RegisterUuid("40ddab7d-d9b6-57cb-aeaf-32c6df4a4bb0", "AvgPooling");
  f.RegisterUuid("fce7f45f-8c02-57d8-b193-ef6c29278a6c",
                 "LRNormalizerForward");
  f.RegisterUuid("de91869f-3aa3-50d3-bf9d-e27ffc6ce77a",
                 "ActivationUnit");
  f.RegisterUuid("be4621cf-8dde-51b6-ad4d-9e7a1ded811b",
                 "DropoutForward");
  f.RegisterUuid("794d6e18-a610-5449-8002-e65c30c7b62e",
                 "MultiHeadAttentionForward");
  f.RegisterUuid("8c3ba037-c08e-529e-837b-42f4c1929bd5", "MoEForward");
}

}  // namespace veles_native
