#include "json.h"

#include <cctype>
#include <cstdlib>

namespace veles_native {
namespace {

class Parser {
 public:
  explicit Parser(const std::string& text) : text_(text) {}

  JsonValue Parse() {
    JsonValue v = ParseValue();
    SkipWs();
    if (pos_ != text_.size()) Fail("trailing characters");
    return v;
  }

 private:
  [[noreturn]] void Fail(const char* what) {
    throw std::runtime_error(std::string("JSON parse error at ") +
                             std::to_string(pos_) + ": " + what);
  }

  void SkipWs() {
    while (pos_ < text_.size() &&
           std::isspace(static_cast<unsigned char>(text_[pos_]))) {
      ++pos_;
    }
  }

  char Peek() {
    if (pos_ >= text_.size()) Fail("unexpected end");
    return text_[pos_];
  }

  char Next() {
    char c = Peek();
    ++pos_;
    return c;
  }

  void Consume(const char* literal) {
    for (const char* p = literal; *p; ++p) {
      if (pos_ >= text_.size() || text_[pos_] != *p) Fail("bad literal");
      ++pos_;
    }
  }

  JsonValue ParseValue() {
    SkipWs();
    switch (Peek()) {
      case '{': return ParseObject();
      case '[': return ParseArray();
      case '"': return JsonValue(ParseString());
      case 't': Consume("true"); return JsonValue(true);
      case 'f': Consume("false"); return JsonValue(false);
      case 'n': Consume("null"); return JsonValue();
      default: return ParseNumber();
    }
  }

  JsonValue ParseObject() {
    Consume("{");
    JsonObject obj;
    SkipWs();
    if (Peek() == '}') { ++pos_; return JsonValue(std::move(obj)); }
    while (true) {
      SkipWs();
      std::string key = ParseString();
      SkipWs();
      Consume(":");
      obj.emplace(std::move(key), ParseValue());
      SkipWs();
      char c = Next();
      if (c == '}') break;
      if (c != ',') Fail("expected , or }");
    }
    return JsonValue(std::move(obj));
  }

  JsonValue ParseArray() {
    Consume("[");
    JsonArray arr;
    SkipWs();
    if (Peek() == ']') { ++pos_; return JsonValue(std::move(arr)); }
    while (true) {
      arr.push_back(ParseValue());
      SkipWs();
      char c = Next();
      if (c == ']') break;
      if (c != ',') Fail("expected , or ]");
    }
    return JsonValue(std::move(arr));
  }

  std::string ParseString() {
    if (Next() != '"') Fail("expected string");
    std::string out;
    while (true) {
      char c = Next();
      if (c == '"') break;
      if (c == '\\') {
        char esc = Next();
        switch (esc) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (pos_ + 4 > text_.size()) Fail("bad \\u escape");
            unsigned code = std::stoul(text_.substr(pos_, 4), nullptr, 16);
            pos_ += 4;
            // UTF-8 encode (surrogate pairs folded to U+FFFD is fine
            // for this runtime's ASCII-dominated metadata)
            if (code < 0x80) {
              out += static_cast<char>(code);
            } else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: Fail("bad escape");
        }
      } else {
        out += c;
      }
    }
    return out;
  }

  JsonValue ParseNumber() {
    size_t start = pos_;
    if (Peek() == '-') ++pos_;
    while (pos_ < text_.size() &&
           (std::isdigit(static_cast<unsigned char>(text_[pos_])) ||
            text_[pos_] == '.' || text_[pos_] == 'e' || text_[pos_] == 'E' ||
            text_[pos_] == '+' || text_[pos_] == '-')) {
      ++pos_;
    }
    if (pos_ == start) Fail("expected number");
    return JsonValue(std::strtod(text_.c_str() + start, nullptr));
  }

  const std::string& text_;
  size_t pos_ = 0;
};

}  // namespace

JsonValue ParseJson(const std::string& text) { return Parser(text).Parse(); }

}  // namespace veles_native
