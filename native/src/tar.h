// Minimal POSIX-tar (ustar) reader — the package container.
//
// The reference linked libarchive (libVeles/src/workflow_archive.cc);
// packages here are written by Python's tarfile with no compression,
// so 100 lines of ustar parsing replace the dependency. Also supports
// plain directories (a package can be an unpacked folder).
#pragma once

#include <map>
#include <string>
#include <vector>

namespace veles_native {

// member name -> raw bytes
using Archive = std::map<std::string, std::vector<char>>;

// Reads a .tar file or a directory into memory; throws on error.
Archive ReadPackage(const std::string& path);

}  // namespace veles_native
