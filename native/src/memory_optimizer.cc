#include "memory_optimizer.h"

#include <algorithm>

namespace veles_native {

namespace {
constexpr int64_t kAlign = 16;  // floats; keeps SIMD-friendly rows

int64_t AlignUp(int64_t v) { return (v + kAlign - 1) / kAlign * kAlign; }
}  // namespace

int64_t MemoryOptimizer::Optimize(std::vector<MemoryNode>* nodes) const {
  // big-first first-fit: classic interval-graph offset assignment,
  // same strategy family as the reference's optimizer
  std::vector<MemoryNode*> order;
  for (MemoryNode& node : *nodes) order.push_back(&node);
  std::sort(order.begin(), order.end(),
            [](const MemoryNode* a, const MemoryNode* b) {
              return a->value > b->value;
            });
  int64_t arena = 0;
  for (MemoryNode* node : order) {
    // collect [offset, end) spans of already-placed conflicting nodes
    std::vector<std::pair<int64_t, int64_t>> taken;
    for (const MemoryNode* other : order) {
      if (other == node || other->position < 0) continue;
      bool overlap = node->time_start < other->time_finish &&
                     other->time_start < node->time_finish;
      if (overlap) {
        taken.emplace_back(other->position,
                           other->position + AlignUp(other->value));
      }
    }
    std::sort(taken.begin(), taken.end());
    int64_t at = 0;
    for (const auto& span : taken) {
      if (at + AlignUp(node->value) <= span.first) break;  // fits in gap
      at = std::max(at, span.second);
    }
    node->position = at;
    arena = std::max(arena, at + AlignUp(node->value));
  }
  return arena;
}

}  // namespace veles_native
