#include "workflow.h"

#include <stdexcept>

#include "memory_optimizer.h"

namespace veles_native {

Workflow::Workflow(std::shared_ptr<ThreadPoolEngine> engine)
    : engine_(engine ? std::move(engine)
                     : std::make_shared<ThreadPoolEngine>()) {}

void Workflow::AddUnit(std::unique_ptr<Unit> unit) {
  units_.push_back(std::move(unit));
  initialized_ = false;
}

void Workflow::Initialize(const Shape& input_shape) {
  if (units_.empty()) throw std::runtime_error("workflow has no units");
  input_shape_ = input_shape;
  Shape shape = input_shape;
  std::vector<MemoryNode> nodes(units_.size());
  for (size_t i = 0; i < units_.size(); ++i) {
    shape = units_[i]->Initialize(shape);
    // unit i's output lives from step i until step i+1 consumed it;
    // the final output lives to the end (it is returned)
    nodes[i].time_start = static_cast<int64_t>(i);
    nodes[i].time_finish = static_cast<int64_t>(
        i + 1 == units_.size() ? units_.size() + 1 : i + 2);
    nodes[i].value = ShapeSize(shape);  // per-sample floats
  }
  arena_size_ = MemoryOptimizer().Optimize(&nodes);
  offsets_.clear();
  for (const MemoryNode& node : nodes) offsets_.push_back(node.position);
  initialized_ = true;
}

const Shape& Workflow::output_shape() const {
  if (units_.empty()) throw std::runtime_error("workflow has no units");
  return units_.back()->output_shape();
}

std::vector<float> Workflow::Run(const float* input, int64_t batch) const {
  if (!initialized_) throw std::runtime_error("Initialize() first");
  std::vector<float> result(batch * output_size());
  // one arena per worker shard (not per sample): scratch is reused
  // across the shard's samples, which is the memory planner's point
  engine_->ParallelShards(batch, [&](int64_t begin, int64_t end) {
    std::vector<float> arena(arena_size_);
    for (int64_t b = begin; b < end; ++b) {
      const float* current = input + b * input_size();
      for (size_t i = 0; i < units_.size(); ++i) {
        float* out = i + 1 == units_.size()
                         ? result.data() + b * output_size()
                         : arena.data() + offsets_[i];
        units_[i]->Execute(current, out, 1);
        current = out;
      }
    }
  });
  return result;
}

}  // namespace veles_native
