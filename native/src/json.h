// Minimal JSON value + recursive-descent parser.
//
// The reference runtime used vendored rapidjson
// (libVeles/src/workflow_loader.cc); this image ships no JSON library,
// so the runtime carries its own ~250-line parser. Full JSON: objects,
// arrays, strings (with \uXXXX), numbers, true/false/null.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace veles_native {

class JsonValue;
using JsonObject = std::map<std::string, JsonValue>;
using JsonArray = std::vector<JsonValue>;

class JsonValue {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  JsonValue() : type_(Type::Null) {}
  explicit JsonValue(bool b) : type_(Type::Bool), bool_(b) {}
  explicit JsonValue(double d) : type_(Type::Number), num_(d) {}
  explicit JsonValue(std::string s)
      : type_(Type::String), str_(std::move(s)) {}
  explicit JsonValue(JsonArray a)
      : type_(Type::Array), arr_(std::make_shared<JsonArray>(std::move(a))) {}
  explicit JsonValue(JsonObject o)
      : type_(Type::Object),
        obj_(std::make_shared<JsonObject>(std::move(o))) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool() const { Expect(Type::Bool); return bool_; }
  double as_double() const { Expect(Type::Number); return num_; }
  int64_t as_int() const {
    Expect(Type::Number);
    return static_cast<int64_t>(num_);
  }
  const std::string& as_string() const { Expect(Type::String); return str_; }
  const JsonArray& as_array() const { Expect(Type::Array); return *arr_; }
  const JsonObject& as_object() const { Expect(Type::Object); return *obj_; }

  // object lookup; throws std::out_of_range when missing
  const JsonValue& at(const std::string& key) const {
    return as_object().at(key);
  }
  bool contains(const std::string& key) const {
    return is_object() && obj_->count(key) > 0;
  }

 private:
  void Expect(Type t) const {
    if (type_ != t) throw std::runtime_error("JSON type mismatch");
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0.0;
  std::string str_;
  std::shared_ptr<JsonArray> arr_;
  std::shared_ptr<JsonObject> obj_;
};

// Parses a complete JSON document; throws std::runtime_error on error.
JsonValue ParseJson(const std::string& text);

}  // namespace veles_native
