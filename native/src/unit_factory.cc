#include <stdexcept>

#include "unit.h"

namespace veles_native {

UnitFactory& UnitFactory::Instance() {
  static UnitFactory factory;
  static bool initialized = false;
  if (!initialized) {
    initialized = true;  // set first: RegisterBuiltinUnits re-enters
    RegisterBuiltinUnits();
  }
  return factory;
}

void UnitFactory::Register(const std::string& class_name, Constructor ctor) {
  ctors_[class_name] = std::move(ctor);
}

void UnitFactory::RegisterUuid(const std::string& uuid,
                               const std::string& class_name) {
  uuid_to_name_[uuid] = class_name;
}

std::unique_ptr<Unit> UnitFactory::Create(const std::string& key) const {
  auto it = ctors_.find(key);
  if (it == ctors_.end()) {
    auto uuid_it = uuid_to_name_.find(key);
    if (uuid_it != uuid_to_name_.end()) {
      it = ctors_.find(uuid_it->second);
    }
  }
  if (it == ctors_.end()) {
    throw std::runtime_error("unknown unit type: " + key);
  }
  return it->second();
}

std::vector<std::string> UnitFactory::Known() const {
  std::vector<std::string> names;
  for (const auto& kv : ctors_) names.push_back(kv.first);
  return names;
}

}  // namespace veles_native
