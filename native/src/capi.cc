// C API for ctypes bindings (veles_tpu/export/native.py). pybind11 is
// not in this image, so the boundary is plain C: opaque handle, float
// buffers, error strings copied into caller storage.

#include <cstring>
#include <memory>
#include <string>

#include "workflow_loader.h"

namespace {

struct Handle {
  std::unique_ptr<veles_native::Workflow> workflow;
};

void CopyError(const std::string& message, char* err, int errlen) {
  if (err != nullptr && errlen > 0) {
    std::strncpy(err, message.c_str(), errlen - 1);
    err[errlen - 1] = '\0';
  }
}

}  // namespace

extern "C" {

void* vt_load(const char* path, char* err, int errlen) {
  try {
    auto handle = std::make_unique<Handle>();
    handle->workflow = veles_native::LoadWorkflow(path);
    return handle.release();
  } catch (const std::exception& e) {
    CopyError(e.what(), err, errlen);
    return nullptr;
  }
}

void vt_free(void* handle) { delete static_cast<Handle*>(handle); }

int64_t vt_input_size(void* handle) {
  return static_cast<Handle*>(handle)->workflow->input_size();
}

int64_t vt_output_size(void* handle) {
  return static_cast<Handle*>(handle)->workflow->output_size();
}

int vt_unit_count(void* handle) {
  return static_cast<int>(
      static_cast<Handle*>(handle)->workflow->unit_count());
}

// output must hold batch * vt_output_size floats; returns 0 on success
int vt_run(void* handle, const float* input, int64_t batch, float* output,
           char* err, int errlen) {
  try {
    auto* wf = static_cast<Handle*>(handle)->workflow.get();
    std::vector<float> result = wf->Run(input, batch);
    std::memcpy(output, result.data(), result.size() * sizeof(float));
    return 0;
  } catch (const std::exception& e) {
    CopyError(e.what(), err, errlen);
    return 1;
  }
}

}  // extern "C"
