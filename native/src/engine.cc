#include "engine.h"

#include <algorithm>

namespace veles_native {

ThreadPoolEngine::ThreadPoolEngine(int workers) {
  if (workers <= 0) {
    workers = std::max(1u, std::thread::hardware_concurrency());
  }
  for (int i = 0; i < workers; ++i) {
    threads_.emplace_back([this] { WorkerLoop(); });
  }
}

ThreadPoolEngine::~ThreadPoolEngine() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    shutdown_ = true;
  }
  work_cv_.notify_all();
  for (std::thread& t : threads_) t.join();
}

void ThreadPoolEngine::WorkerLoop() {
  while (true) {
    std::function<void()> task;
    {
      std::unique_lock<std::mutex> lock(mutex_);
      work_cv_.wait(lock, [this] { return shutdown_ || !queue_.empty(); });
      if (queue_.empty()) {
        if (shutdown_) return;
        continue;
      }
      task = std::move(queue_.front());
      queue_.pop();
    }
    task();
    {
      std::lock_guard<std::mutex> lock(mutex_);
      if (--outstanding_ == 0) done_cv_.notify_all();
    }
  }
}

void ThreadPoolEngine::ParallelFor(
    int64_t count, const std::function<void(int64_t)>& fn) {
  ParallelShards(count, [&fn](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) fn(i);
  });
}

void ThreadPoolEngine::ParallelShards(
    int64_t count, const std::function<void(int64_t, int64_t)>& fn) {
  if (count <= 0) return;
  int64_t shards =
      std::min<int64_t>(count, static_cast<int64_t>(threads_.size()));
  if (shards <= 1) {
    fn(0, count);
    return;
  }
  {
    std::lock_guard<std::mutex> lock(mutex_);
    outstanding_ += shards;
    for (int64_t s = 0; s < shards; ++s) {
      int64_t begin = count * s / shards;
      int64_t end = count * (s + 1) / shards;
      queue_.push([&fn, begin, end] { fn(begin, end); });
    }
  }
  work_cv_.notify_all();
  std::unique_lock<std::mutex> lock(mutex_);
  done_cv_.wait(lock, [this] { return outstanding_ == 0; });
}

}  // namespace veles_native
