// CLI runner: veles_native_run <package.tar|dir> <input.npy> <output.npy>
//
// The standalone-inference entry the reference's libVeles offered to
// embedded apps: load an exported package, run the forward pass on a
// batch from a .npy file, write the result as .npy.

#include <cstdio>
#include <fstream>
#include <iterator>

#include "npy.h"
#include "workflow_loader.h"

int main(int argc, char** argv) {
  if (argc != 4) {
    std::fprintf(stderr,
                 "usage: %s <package.tar|package-dir> <input.npy> "
                 "<output.npy>\n",
                 argv[0]);
    return 2;
  }
  try {
    auto workflow = veles_native::LoadWorkflow(argv[1]);

    std::ifstream in(argv[2], std::ios::binary);
    if (!in) throw std::runtime_error(std::string("cannot open ") + argv[2]);
    std::vector<char> bytes((std::istreambuf_iterator<char>(in)),
                            std::istreambuf_iterator<char>());
    veles_native::NpyArray input = veles_native::ParseNpy(bytes);
    if (input.shape.empty()) throw std::runtime_error("scalar input");
    int64_t batch = input.shape[0];
    int64_t sample = input.size() / batch;
    if (sample != workflow->input_size()) {
      throw std::runtime_error(
          "input sample size " + std::to_string(sample) +
          " != workflow input " + std::to_string(workflow->input_size()));
    }

    std::vector<float> output = workflow->Run(input.data.data(), batch);

    std::vector<int64_t> out_shape = {batch};
    for (int64_t d : workflow->output_shape()) out_shape.push_back(d);
    std::vector<char> blob = veles_native::WriteNpy(out_shape,
                                                    output.data());
    std::ofstream out(argv[3], std::ios::binary);
    out.write(blob.data(), blob.size());
    std::fprintf(stderr, "%s: %lld samples -> %s\n",
                 workflow->name.c_str(), static_cast<long long>(batch),
                 argv[3]);
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
