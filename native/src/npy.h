// .npy reader/writer (the reference's numpy_array_loader,
// libVeles/src/numpy_array_loader.cc): header parse, little-endian
// f4/f8/i1/i2/i4/i8/u1 payloads converted to float32, C order only.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace veles_native {

struct NpyArray {
  std::vector<int64_t> shape;
  std::vector<float> data;  // converted to float32

  int64_t size() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
};

// Parses a complete .npy byte buffer; throws std::runtime_error.
NpyArray ParseNpy(const std::vector<char>& bytes);

// Serializes float32 data as .npy (v1.0 header).
std::vector<char> WriteNpy(const std::vector<int64_t>& shape,
                           const float* data);

}  // namespace veles_native
