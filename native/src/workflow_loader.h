// Package loader: contents.json + @NNNN .npy members -> Workflow.
// Mirrors libVeles WorkflowLoader::Load (src/workflow_loader.cc:42-133).
#pragma once

#include <memory>
#include <string>

#include "workflow.h"

namespace veles_native {

std::unique_ptr<Workflow> LoadWorkflow(
    const std::string& package_path,
    std::shared_ptr<ThreadPoolEngine> engine = nullptr);

}  // namespace veles_native
