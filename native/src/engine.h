// Execution engine (libVeles/src/engine.h ThreadPoolEngine): a fixed
// thread pool draining a work queue. The inference chain is sequential
// per sample, so the pool's job here is batch-parallelism: Execute
// calls are sharded across workers when the batch is large enough.
#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace veles_native {

class ThreadPoolEngine {
 public:
  explicit ThreadPoolEngine(int workers = 0);
  ~ThreadPoolEngine();

  // Runs fn(i) for i in [0, count) across the pool and waits.
  void ParallelFor(int64_t count, const std::function<void(int64_t)>& fn);

  // Runs fn(begin, end) once per worker shard — lets callers hoist
  // per-shard scratch allocations out of the element loop.
  void ParallelShards(
      int64_t count,
      const std::function<void(int64_t, int64_t)>& fn);

  int workers() const { return static_cast<int>(threads_.size()); }

 private:
  void WorkerLoop();

  std::vector<std::thread> threads_;
  std::mutex mutex_;
  std::condition_variable work_cv_, done_cv_;
  std::queue<std::function<void()>> queue_;
  int64_t outstanding_ = 0;
  bool shutdown_ = false;
};

}  // namespace veles_native
