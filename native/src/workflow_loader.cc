#include "workflow_loader.h"

#include <stdexcept>

#include "json.h"
#include "npy.h"
#include "tar.h"

namespace veles_native {
namespace {

// "@0000_64x10" -> member "@0000_64x10.npy"
bool IsArrayRef(const JsonValue& value) {
  return value.is_string() && !value.as_string().empty() &&
         value.as_string()[0] == '@';
}

}  // namespace

std::unique_ptr<Workflow> LoadWorkflow(
    const std::string& package_path,
    std::shared_ptr<ThreadPoolEngine> engine) {
  Archive archive = ReadPackage(package_path);
  auto contents_it = archive.find("contents.json");
  if (contents_it == archive.end()) {
    throw std::runtime_error("package has no contents.json");
  }
  JsonValue contents = ParseJson(std::string(
      contents_it->second.begin(), contents_it->second.end()));

  const JsonValue& wf_json = contents.at("workflow");
  auto workflow = std::make_unique<Workflow>(std::move(engine));
  workflow->name =
      wf_json.contains("name") ? wf_json.at("name").as_string() : "";
  workflow->checksum = wf_json.contains("checksum")
                           ? wf_json.at("checksum").as_string()
                           : "";

  for (const JsonValue& unit_json : wf_json.at("units").as_array()) {
    const JsonValue& cls = unit_json.at("class");
    const std::string& cls_name = cls.at("name").as_string();
    std::unique_ptr<Unit> unit;
    // class name first; the exported uuid5 id is the fallback key
    // (both are registered — libVeles keyed on UUID only). A miss on
    // both reports the CLASS name, which is the actionable one.
    try {
      unit = UnitFactory::Instance().Create(cls_name);
    } catch (const std::runtime_error&) {
      try {
        if (cls.contains("uuid") && cls.at("uuid").is_string()) {
          unit =
              UnitFactory::Instance().Create(cls.at("uuid").as_string());
        }
      } catch (const std::runtime_error&) {
      }
      if (!unit) {
        throw std::runtime_error("unknown unit type: " + cls_name);
      }
    }
    for (const auto& kv : unit_json.at("data").as_object()) {
      if (IsArrayRef(kv.second)) {
        std::string member = kv.second.as_string() + ".npy";
        auto it = archive.find(member);
        if (it == archive.end()) {
          throw std::runtime_error("missing package member " + member);
        }
        unit->SetArray(kv.first, ParseNpy(it->second));
      } else {
        unit->SetParameter(kv.first, kv.second);
      }
    }
    workflow->AddUnit(std::move(unit));
  }

  if (contents.contains("input_shape") &&
      contents.at("input_shape").is_array()) {
    const JsonArray& dims = contents.at("input_shape").as_array();
    Shape shape;
    // first dim of the recorded minibatch shape is the batch — skip it
    for (size_t i = 1; i < dims.size(); ++i) {
      shape.push_back(dims[i].as_int());
    }
    if (!shape.empty()) workflow->Initialize(shape);
  }
  return workflow;
}

}  // namespace veles_native
