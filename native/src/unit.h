// Inference unit interface + factory registry.
//
// Mirrors libVeles's Unit/UnitFactory (libVeles/inc/veles/unit.h,
// src/unit_factory.cc:40-65): units are constructed by UUID or class
// name, receive properties (scalars, lists, arrays) from the package
// loader, compute their output shape from the input shape, and execute
// batch-at-a-time on float32 buffers.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "json.h"
#include "npy.h"

namespace veles_native {

// sample shape, excluding the batch dimension
using Shape = std::vector<int64_t>;

inline int64_t ShapeSize(const Shape& shape) {
  int64_t n = 1;
  for (int64_t d : shape) n *= d;
  return n;
}

class Unit {
 public:
  virtual ~Unit() = default;

  virtual const char* Name() const = 0;

  // Scalar/array property assignment (the libVeles SetParameter
  // contract). Arrays arrive resolved from @NNNN members.
  virtual void SetParameter(const std::string& name, const JsonValue& value) {
    params_[name] = value;
  }
  virtual void SetArray(const std::string& name, NpyArray array) {
    arrays_[name] = std::move(array);
  }

  // Shape propagation; called once before execution.
  virtual Shape Initialize(const Shape& input_shape) = 0;

  // input: batch x ShapeSize(input_shape), output: batch x output size.
  virtual void Execute(const float* input, float* output,
                       int64_t batch) const = 0;

  const Shape& output_shape() const { return output_shape_; }
  const Shape& input_shape() const { return input_shape_; }

 protected:
  double Param(const std::string& name, double fallback) const {
    auto it = params_.find(name);
    return it == params_.end() ? fallback : it->second.as_double();
  }
  std::string StrParam(const std::string& name,
                       const std::string& fallback) const {
    auto it = params_.find(name);
    return it == params_.end() || !it->second.is_string()
               ? fallback
               : it->second.as_string();
  }
  std::vector<int64_t> IntListParam(const std::string& name) const {
    std::vector<int64_t> out;
    auto it = params_.find(name);
    if (it != params_.end() && it->second.is_array()) {
      for (const auto& v : it->second.as_array()) {
        out.push_back(v.as_int());
      }
    }
    return out;
  }
  const NpyArray* Array(const std::string& name) const {
    auto it = arrays_.find(name);
    return it == arrays_.end() ? nullptr : &it->second;
  }

  std::map<std::string, JsonValue> params_;
  std::map<std::string, NpyArray> arrays_;
  Shape input_shape_, output_shape_;
};

class UnitFactory {
 public:
  using Constructor = std::function<std::unique_ptr<Unit>()>;

  static UnitFactory& Instance();

  void Register(const std::string& class_name, Constructor ctor);
  // also register the stable UUID exported by the Python side
  void RegisterUuid(const std::string& uuid, const std::string& class_name);

  // by class name or UUID; throws std::runtime_error when unknown
  std::unique_ptr<Unit> Create(const std::string& key) const;
  std::vector<std::string> Known() const;

 private:
  std::map<std::string, Constructor> ctors_;
  std::map<std::string, std::string> uuid_to_name_;
};

// defined in units.cc: registers every built-in unit type
void RegisterBuiltinUnits();

}  // namespace veles_native
