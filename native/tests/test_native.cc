// Native runtime self-tests (the libVeles/tests/ role, without gtest:
// plain asserts, exit code = failure count).

#include <cassert>
#include <cmath>
#include <cstdio>
#include <vector>

#include "json.h"
#include "memory_optimizer.h"
#include "npy.h"
#include "unit.h"
#include "workflow.h"

using namespace veles_native;

static int failures = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,    \
                   #cond);                                            \
      ++failures;                                                     \
    }                                                                 \
  } while (0)

static void TestJson() {
  JsonValue v = ParseJson(
      "{\"a\": [1, 2.5, -3], \"s\": \"x\\ny\", \"t\": true, "
      "\"n\": null, \"nested\": {\"k\": \"@0001_2x3\"}}");
  CHECK(v.at("a").as_array().size() == 3);
  CHECK(v.at("a").as_array()[1].as_double() == 2.5);
  CHECK(v.at("a").as_array()[2].as_int() == -3);
  CHECK(v.at("s").as_string() == "x\ny");
  CHECK(v.at("t").as_bool());
  CHECK(v.at("n").is_null());
  CHECK(v.at("nested").at("k").as_string() == "@0001_2x3");
  bool threw = false;
  try {
    ParseJson("{broken");
  } catch (const std::runtime_error&) {
    threw = true;
  }
  CHECK(threw);
}

static void TestNpyRoundtrip() {
  std::vector<float> data = {1.5f, -2.0f, 3.25f, 0.0f, 5.0f, -6.5f};
  std::vector<char> blob = WriteNpy({2, 3}, data.data());
  NpyArray back = ParseNpy(blob);
  CHECK(back.shape == std::vector<int64_t>({2, 3}));
  for (int i = 0; i < 6; ++i) CHECK(back.data[i] == data[i]);
}

static void TestMemoryOptimizer() {
  // three sequential buffers: 0 and 2 don't overlap -> may share
  std::vector<MemoryNode> nodes = {
      {0, 2, 100, -1}, {1, 3, 50, -1}, {2, 4, 100, -1}};
  int64_t arena = MemoryOptimizer().Optimize(&nodes);
  for (const MemoryNode& n : nodes) CHECK(n.position >= 0);
  // conflicting pairs must not overlap in the arena
  auto end = [](const MemoryNode& n) { return n.position + n.value; };
  CHECK(nodes[0].position >= end(nodes[1]) ||
        nodes[1].position >= end(nodes[0]));
  CHECK(nodes[1].position >= end(nodes[2]) ||
        nodes[2].position >= end(nodes[1]));
  // arena smaller than the no-sharing total (0 and 2 alias)
  CHECK(arena < 100 + 50 + 100);
}

static void TestAll2AllSoftmax() {
  auto unit = UnitFactory::Instance().Create("All2AllSoftmax");
  NpyArray weights;
  weights.shape = {2, 3};
  weights.data = {1, 0, 0, 0, 1, 0};  // maps (x0,x1) -> (x0,x1,0) logits
  unit->SetArray("weights", std::move(weights));
  unit->SetParameter("activation", JsonValue(std::string("softmax")));
  Shape out = unit->Initialize({2});
  CHECK(out == Shape({3}));
  float input[2] = {1.0f, 2.0f};
  float output[3];
  unit->Execute(input, output, 1);
  float sum = output[0] + output[1] + output[2];
  CHECK(std::fabs(sum - 1.0f) < 1e-5f);
  CHECK(output[1] > output[0] && output[0] > output[2]);
}

static void TestConvIdentityKernel() {
  auto unit = UnitFactory::Instance().Create("Conv");
  NpyArray weights;  // 1x1 conv, identity over channels=1
  weights.shape = {1, 1, 1, 1};
  weights.data = {2.0f};
  unit->SetArray("weights", std::move(weights));
  Shape out = unit->Initialize({3, 3, 1});
  CHECK(out == Shape({3, 3, 1}));
  std::vector<float> x = {1, 2, 3, 4, 5, 6, 7, 8, 9};
  std::vector<float> y(9);
  unit->Execute(x.data(), y.data(), 1);
  for (int i = 0; i < 9; ++i) CHECK(y[i] == 2.0f * x[i]);
}

static void TestPoolingAndChain() {
  Workflow wf;
  {
    auto pool = UnitFactory::Instance().Create("MaxPooling");
    pool->SetParameter("kx", JsonValue(2.0));
    pool->SetParameter("ky", JsonValue(2.0));
    wf.AddUnit(std::move(pool));
  }
  {
    auto act = UnitFactory::Instance().Create("ActivationUnit");
    act->SetParameter("activation",
                      JsonValue(std::string("strict_relu")));
    wf.AddUnit(std::move(act));
  }
  wf.Initialize({4, 4, 1});
  CHECK(wf.output_shape() == Shape({2, 2, 1}));
  std::vector<float> x(16);
  for (int i = 0; i < 16; ++i) x[i] = static_cast<float>(i - 8);
  std::vector<float> y = wf.Run(x.data(), 1);
  // max pool of [-8..7] 4x4 -> {-3, -1, 5, 7}, relu -> {0, 0, 5, 7}
  CHECK(y.size() == 4);
  CHECK(y[0] == 0.0f && y[1] == 0.0f && y[2] == 5.0f && y[3] == 7.0f);
}

static void TestBatchSharding() {
  Workflow wf;
  auto act = UnitFactory::Instance().Create("ActivationUnit");
  act->SetParameter("activation", JsonValue(std::string("tanh")));
  wf.AddUnit(std::move(act));
  wf.Initialize({8});
  std::vector<float> x(64 * 8);
  for (size_t i = 0; i < x.size(); ++i) x[i] = 0.001f * i;
  std::vector<float> y = wf.Run(x.data(), 64);
  for (size_t i = 0; i < x.size(); ++i) {
    float expect = 1.7159f * std::tanh(0.6666f * x[i]);
    CHECK(std::fabs(y[i] - expect) < 1e-6f);
  }
}

int main() {
  TestJson();
  TestNpyRoundtrip();
  TestMemoryOptimizer();
  TestAll2AllSoftmax();
  TestConvIdentityKernel();
  TestPoolingAndChain();
  TestBatchSharding();
  if (failures == 0) std::printf("all native tests passed\n");
  return failures;
}
