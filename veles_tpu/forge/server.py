"""Forge server (``veles/forge/forge_server.py:103-427``)."""

import io
import json
import os
import re
import shutil
import tarfile
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.config import root
from veles_tpu.logger import Logger

#: model/version names must stay inside the storage tree
_SAFE_NAME = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


def validate_name(name):
    if not name or not _SAFE_NAME.match(name) or ".." in name:
        raise ValueError("invalid name: %r" % (name,))
    return name


#: model-repository browser (the role of the reference's
#: ``web/projects/forge`` app): lists models from the JSON API, click
#: for version history + manifest, direct /fetch download links. All
#: rendering goes through createElement/textContent — model names and
#: descriptions are uploader-controlled and must never reach innerHTML.
_BROWSE_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu forge</title><style>
body { font-family: sans-serif; margin: 2em; background: #fafafa;
       max-width: 75em; }
table { border-collapse: collapse; width: 98em; max-width: 100em;
        background: #fff; }
td, th { border: 1px solid #ccc; padding: 0.4em 0.7em; text-align:
left; }
tr.model { cursor: pointer; } tr.model:hover { background: #eef3fa; }
#details { background: #fff; border: 1px solid #ccc; padding: 1em;
           margin-top: 1.5em; display: none; }
pre { background: #f4f4f4; padding: 0.6em; overflow-x: auto; }
a.dl { margin-right: 1em; }
.muted { color: #666; font-size: 0.9em; }
</style></head><body>
<h1>forge model repository</h1>
<p class="muted">versioned trained-model packages; click a row for
version history and the manifest. Uploads go through
<code>veles_tpu.forge.client</code> (token-authenticated).</p>
<table id="models"><thead><tr><th>model</th><th>version</th>
<th>author</th><th>description</th><th>updated</th></tr></thead>
<tbody></tbody></table>
<div id="details"></div>
<script>
const service = "__SERVICE__";
function cell(tr, text) {
  const td = document.createElement("td");
  td.textContent = text == null ? "" : String(text);
  tr.appendChild(td);
  return td;
}
async function showDetails(name) {
  const resp = await fetch(service + "?query=details&name=" +
                           encodeURIComponent(name));
  const d = await resp.json();
  const box = document.getElementById("details");
  box.textContent = "";
  const h = document.createElement("h2");
  h.textContent = d.name;
  box.appendChild(h);
  const vt = document.createElement("table");
  const head = document.createElement("tr");
  for (const t of ["version", "author", "uploaded", "download"])
    { const th = document.createElement("th"); th.textContent = t;
      head.appendChild(th); }
  vt.appendChild(head);
  for (const v of (d.versions || []).slice().reverse()) {
    const tr = document.createElement("tr");
    cell(tr, v.version); cell(tr, v.author); cell(tr, v.uploaded);
    const td = document.createElement("td");
    const a = document.createElement("a");
    a.className = "dl";
    a.href = "/fetch?name=" + encodeURIComponent(d.name) +
             "&version=" + encodeURIComponent(v.version);
    a.textContent = "package.tar";
    td.appendChild(a); tr.appendChild(td);
    vt.appendChild(tr);
  }
  box.appendChild(vt);
  const mh = document.createElement("h3");
  mh.textContent = "manifest (latest)";
  box.appendChild(mh);
  const pre = document.createElement("pre");
  pre.textContent = JSON.stringify(d.manifest, null, 2);
  box.appendChild(pre);
  box.style.display = "block";
}
async function load() {
  const resp = await fetch(service + "?query=list");
  const models = await resp.json();
  const tbody = document.querySelector("#models tbody");
  tbody.textContent = "";
  if (!models.length) {
    const tr = document.createElement("tr");
    cell(tr, "(no models uploaded yet)");
    tbody.appendChild(tr);
    return;
  }
  for (const m of models) {
    const tr = document.createElement("tr");
    tr.className = "model";
    cell(tr, m.name); cell(tr, m.version); cell(tr, m.author);
    cell(tr, m.description); cell(tr, m.updated);
    tr.addEventListener("click", () => showDetails(m.name));
    tbody.appendChild(tr);
  }
}
load();
</script></body></html>"""


class ForgeServer(Logger):
    """Stores versioned packages under ``storage_dir``.

    Layout: ``<storage>/<model>/<version>/*`` + per-model
    ``meta.json`` (version journal, latest pointer).
    """

    def __init__(self, storage_dir, host="127.0.0.1", port=0, token=None,
                 allow_insecure=False):
        super(ForgeServer, self).__init__()
        self.storage_dir = os.path.abspath(storage_dir)
        os.makedirs(self.storage_dir, exist_ok=True)
        self.token = token
        if token is None:
            if host not in ("127.0.0.1", "localhost", "::1") \
                    and not allow_insecure:
                # tokenless means anyone who reaches the port can upload
                # or delete models — never expose that beyond loopback
                # without an explicit opt-in
                raise ValueError(
                    "refusing to bind %s without --token; pass "
                    "--allow-insecure (allow_insecure=True) to "
                    "override" % host)
            self.warning("no --token configured: uploads and deletes "
                         "are unauthenticated")
        self._lock = threading.RLock()
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.owner = self
        self._server.daemon_threads = True
        self.address = self._server.server_address
        self._thread = None

    @property
    def port(self):
        return self.address[1]

    # -- storage -----------------------------------------------------------

    def _meta_path(self, name):
        return os.path.join(self.storage_dir, name, "meta.json")

    def _read_meta(self, name):
        try:
            with open(self._meta_path(name)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def _write_meta(self, name, meta):
        with open(self._meta_path(name), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)

    def list_models(self):
        with self._lock:
            models = []
            for name in sorted(os.listdir(self.storage_dir)):
                meta = self._read_meta(name)
                if meta is None:
                    continue
                latest = meta["versions"][-1]
                models.append({
                    "name": name,
                    "author": latest.get("author", ""),
                    "description": latest.get("short_description", ""),
                    "version": latest["version"],
                    "updated": latest["uploaded"],
                })
            return models

    def details(self, name):
        validate_name(name)
        with self._lock:
            meta = self._read_meta(name)
            if meta is None:
                raise KeyError("no such model: %s" % name)
            latest = meta["versions"][-1]
            manifest_path = os.path.join(
                self.storage_dir, name, latest["version"], "manifest.json")
            with open(manifest_path) as f:
                manifest = json.load(f)
            return {"name": name, "manifest": manifest,
                    "versions": meta["versions"]}

    def upload(self, blob, token=None):
        self._check_token(token)
        try:
            tar = tarfile.open(fileobj=io.BytesIO(blob))
        except tarfile.TarError as e:
            raise ValueError("not a tar package: %s" % e)
        with tar:
            names = tar.getnames()
            if "manifest.json" not in names:
                raise ValueError("package has no manifest.json")
            manifest = json.loads(
                tar.extractfile("manifest.json").read())
            name = validate_name(manifest.get("name"))
            version = validate_name(str(manifest.get("version", "1.0")))
            for member in tar.getmembers():
                # refuse path traversal / links before extraction
                if member.name.startswith(("/", "..")) or \
                        ".." in member.name.split("/") or \
                        not (member.isreg() or member.isdir()):
                    raise ValueError("unsafe member: %s" % member.name)
            with self._lock:
                meta = self._read_meta(name) or {"versions": []}
                if any(v["version"] == version
                       for v in meta["versions"]):
                    raise ValueError(
                        "%s version %s already exists" % (name, version))
                target = os.path.join(self.storage_dir, name, version)
                os.makedirs(target, exist_ok=True)
                tar.extractall(target, filter="data")
                meta["versions"].append({
                    "version": version,
                    "author": manifest.get("author", ""),
                    "short_description":
                        manifest.get("short_description", ""),
                    "uploaded": time.time(),
                })
                self._write_meta(name, meta)
        self.info("uploaded %s version %s", name, version)
        return {"name": name, "version": version}

    def fetch(self, name, version=None):
        validate_name(name)
        with self._lock:
            meta = self._read_meta(name)
            if meta is None:
                raise KeyError("no such model: %s" % name)
            if version is None or version == "latest":
                version = meta["versions"][-1]["version"]
            else:
                validate_name(version)
                if not any(v["version"] == version
                           for v in meta["versions"]):
                    raise KeyError("no version %s of %s" % (version, name))
            source = os.path.join(self.storage_dir, name, version)
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w") as tar:
                for fn in sorted(os.listdir(source)):
                    tar.add(os.path.join(source, fn), arcname=fn)
            return buf.getvalue(), version

    def delete(self, name, token=None, version=None):
        self._check_token(token)
        validate_name(name)
        with self._lock:
            meta = self._read_meta(name)
            if meta is None:
                raise KeyError("no such model: %s" % name)
            if version is None:
                shutil.rmtree(os.path.join(self.storage_dir, name))
                self.info("deleted %s (all versions)", name)
                return {"deleted": name}
            validate_name(version)
            kept = [v for v in meta["versions"] if v["version"] != version]
            if len(kept) == len(meta["versions"]):
                raise KeyError("no version %s of %s" % (version, name))
            shutil.rmtree(os.path.join(self.storage_dir, name, version))
            if kept:
                meta["versions"] = kept
                self._write_meta(name, meta)
            else:
                shutil.rmtree(os.path.join(self.storage_dir, name))
            return {"deleted": name, "version": version}

    def _check_token(self, token):
        import hmac
        if self.token is not None and (
                not isinstance(token, str) or
                not hmac.compare_digest(token, self.token)):
            raise PermissionError("bad or missing token")

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="forge")
        self._thread.start()
        self.info("forge serving %s on %s:%d", self.storage_dir,
                  *self.address)
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        self.server.owner.debug("http: " + fmt, *args)

    def _reply(self, body, code=200, ctype="application/json"):
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, e):
        code = {KeyError: 404, PermissionError: 403}.get(type(e), 400)
        message = str(e).strip("'") or type(e).__name__
        self._reply({"error": message}, code=code)

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        owner = self.server.owner
        service = "/" + root.common.forge.get("service_name", "forge")
        try:
            if parsed.path in ("/", "/browse.html"):
                self._reply(
                    _BROWSE_PAGE.replace("__SERVICE__",
                                         service).encode(),
                    ctype="text/html; charset=utf-8")
            elif parsed.path == service:
                q = query.get("query")
                if q == "list":
                    self._reply(owner.list_models())
                elif q == "details":
                    self._reply(owner.details(query.get("name", "")))
                else:
                    raise ValueError("unknown query %r" % q)
            elif parsed.path == "/fetch":
                blob, version = owner.fetch(query.get("name", ""),
                                            query.get("version"))
                self.send_response(200)
                self.send_header("Content-Type", "application/x-tar")
                self.send_header("X-Forge-Version", version)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)
            else:
                self._reply({"error": "not found"}, code=404)
        except Exception as e:
            self._error(e)

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        try:
            length = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            length = 0
        blob = self.rfile.read(length)
        # token rides a header, not the URL: query strings end up in
        # access logs, browser history and proxy caches
        token = self.headers.get("X-Forge-Token")
        owner = self.server.owner
        service = "/" + root.common.forge.get("service_name", "forge")
        try:
            if parsed.path == "/upload":
                self._reply(owner.upload(blob, token=token))
            elif parsed.path == service and \
                    query.get("query") == "delete":
                # state-changing: POST only (a GET delete is cacheable
                # and prefetchable)
                self._reply(owner.delete(query.get("name", ""),
                                         token=token,
                                         version=query.get("version")))
            else:
                self._reply({"error": "not found"}, code=404)
        except Exception as e:
            self._error(e)


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(description="veles_tpu forge server")
    parser.add_argument("-r", "--root", required=True,
                        help="storage directory")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("-p", "--port", type=int, default=8080)
    parser.add_argument("--token", default=None,
                        help="shared secret required for upload/delete")
    parser.add_argument("--allow-insecure", action="store_true",
                        help="bind a non-loopback host WITHOUT a token "
                             "(anyone reaching the port can upload or "
                             "delete models)")
    args = parser.parse_args(argv)
    server = ForgeServer(args.root, host=args.host, port=args.port,
                         token=args.token,
                         allow_insecure=args.allow_insecure)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
