"""Forge: the model repository (re-designs ``veles/forge/``).

A Forge server stores versioned model packages — workflow file, config
file, weights/export artifacts, described by a ``manifest.json`` — and
serves the reference's protocol surface: ``/service?query=list|
details|delete``, ``/fetch?name=&version=``, ``POST /upload``
(``forge_server.py:103-427``, ``forge_client.py:91-367``). The
reference versioned through server-side git repositories and confirmed
authors by email; here versions are explicit directory snapshots with
an upload journal and auth is a shared token — same capability, much
less machinery.
"""

from veles_tpu.forge.client import ForgeClient  # noqa: F401
from veles_tpu.forge.server import ForgeServer  # noqa: F401
