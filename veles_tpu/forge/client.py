"""Forge client (``veles/forge/forge_client.py:91-367``).

Programmatic API + CLI: ``python -m veles_tpu.forge.client
list|details|fetch|upload|delete ...``. Packages are directories with a
``manifest.json`` naming the model, version, workflow/config files.
"""

import argparse
import io
import json
import os
import tarfile
import urllib.error
import urllib.parse
import urllib.request

from veles_tpu.config import root
from veles_tpu.logger import Logger


class ForgeClient(Logger):
    def __init__(self, base, token=None):
        super(ForgeClient, self).__init__()
        if "://" not in base:
            base = "http://" + base
        self.base = base.rstrip("/")
        self.token = token

    # -- helpers -----------------------------------------------------------

    @property
    def _service(self):
        return "%s/%s" % (self.base,
                          root.common.forge.get("service_name", "forge"))

    def _get_json(self, url):
        try:
            with urllib.request.urlopen(url, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise RuntimeError(self._http_error(e))

    @staticmethod
    def _http_error(e):
        try:
            return json.loads(e.read()).get("error", str(e))
        except Exception:
            return str(e)

    # -- operations --------------------------------------------------------

    def list(self):
        return self._get_json(self._service + "?query=list")

    def details(self, name):
        return self._get_json(
            "%s?query=details&name=%s" %
            (self._service, urllib.parse.quote(name)))

    def delete(self, name, version=None):
        url = "%s?query=delete&name=%s" % (self._service,
                                           urllib.parse.quote(name))
        if version:
            url += "&version=" + urllib.parse.quote(version)
        # state-changing → POST; token in a header, never the URL
        request = urllib.request.Request(
            url, data=b"", headers=self._auth_headers())
        try:
            with urllib.request.urlopen(request, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise RuntimeError(self._http_error(e))

    def _auth_headers(self):
        return {"X-Forge-Token": self.token} if self.token else {}

    def upload(self, path):
        """Upload a package directory (must contain manifest.json)."""
        manifest_path = os.path.join(path, "manifest.json")
        with open(manifest_path) as f:
            manifest = json.load(f)  # fail fast on bad packages
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            for fn in sorted(os.listdir(path)):
                # recursive: packages may carry plots/, data/ subtrees
                tar.add(os.path.join(path, fn), arcname=fn)
        url = self.base + "/upload"
        headers = {"Content-Type": "application/x-tar"}
        headers.update(self._auth_headers())
        request = urllib.request.Request(
            url, data=buf.getvalue(), headers=headers)
        try:
            with urllib.request.urlopen(request, timeout=60) as resp:
                reply = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise RuntimeError(self._http_error(e))
        self.info("uploaded %s version %s", reply["name"],
                  reply["version"])
        return reply

    def fetch(self, name, dest, version=None):
        """Download + unpack a model into ``dest``; returns version."""
        url = "%s/fetch?name=%s" % (self.base, urllib.parse.quote(name))
        if version:
            url += "&version=" + urllib.parse.quote(version)
        try:
            with urllib.request.urlopen(url, timeout=60) as resp:
                got_version = resp.headers.get("X-Forge-Version")
                blob = resp.read()
        except urllib.error.HTTPError as e:
            raise RuntimeError(self._http_error(e))
        os.makedirs(dest, exist_ok=True)
        with tarfile.open(fileobj=io.BytesIO(blob)) as tar:
            for member in tar.getmembers():
                if member.name.startswith(("/", "..")) or \
                        ".." in member.name.split("/"):
                    raise ValueError("unsafe member: %s" % member.name)
            tar.extractall(dest, filter="data")
        self.info("fetched %s version %s into %s", name, got_version,
                  dest)
        return got_version


def main(argv=None):
    parser = argparse.ArgumentParser(description="veles_tpu forge client")
    parser.add_argument("action",
                        choices=("list", "details", "fetch", "upload",
                                 "delete"))
    parser.add_argument("-s", "--server", required=True,
                        help="forge server, host:port or URL")
    parser.add_argument("-n", "--name", default=None)
    parser.add_argument("-v", "--version", default=None)
    parser.add_argument("-d", "--directory", default=".",
                        help="package dir (upload) / destination (fetch)")
    parser.add_argument("--token", default=None)
    args = parser.parse_args(argv)
    client = ForgeClient(args.server, token=args.token)
    if args.action == "list":
        print(json.dumps(client.list(), indent=2))
    elif args.action == "details":
        print(json.dumps(client.details(args.name), indent=2))
    elif args.action == "fetch":
        client.fetch(args.name, args.directory, version=args.version)
    elif args.action == "upload":
        client.upload(args.directory)
    elif args.action == "delete":
        client.delete(args.name, version=args.version)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
