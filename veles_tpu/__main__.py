"""CLI entry point: ``python -m veles_tpu <workflow.py> [config.py] [k=v ...]``.

The reference's ``veles/__main__.py:136-859``: one command runs a model
standalone, as a master (``-l``), as a slave (``-m``), resumes from a
snapshot (``-w``), runs the genetic optimizer (``--optimize``) or an
ensemble (``--ensemble-train``/``--ensemble-test``). A leading
``serve`` subcommand instead starts the dynamic-batching inference
server over a snapshot or export package
(``python -m veles_tpu serve --model ...``, see docs/SERVING.md). Flags are
aggregated from every registered class via the CLI registry
(``veles/cmdline.py``), seeds come from ``-s`` with the reference's
``source:count`` syntax, and config files are Python executed against
the global ``root`` tree.
"""

import importlib.util
import json
import logging
import os
import runpy
import sys

from veles_tpu import cmdline, prng
from veles_tpu.config import apply_config_file, root
from veles_tpu.launcher import Launcher
from veles_tpu.logger import Logger, setup_logging


class Main(Logger):
    """Parse args, seed, load model+config, dispatch the run."""

    EXIT_SUCCESS = 0
    EXIT_FAILURE = 1

    def init_parser(self):
        # import for the side effect of registering their CLI flags
        import veles_tpu.backends  # noqa: F401
        import veles_tpu.loader.base  # noqa: F401
        import veles_tpu.nn.precision  # noqa: F401
        parser = cmdline.init_parser(
            prog="veles_tpu",
            description="TPU-native deep-learning workflow platform")
        parser.add_argument("workflow", nargs="?",
                            help="path to the workflow Python file")
        parser.add_argument("config", nargs="?", default=None,
                            help="path to the config Python file "
                                 "(defaults to <workflow>_config.py)")
        parser.add_argument("overrides", nargs="*", default=[],
                            help="config overrides: root.path.to.key=value")
        parser.add_argument("-s", "--seed", default="1234",
                            help="RNG seed: INT | file:COUNT | "
                                 "/dev/urandom:16 | comma-separated list "
                                 "applied to prng keys default,loader,...")
        parser.add_argument("-w", "--snapshot", default=None,
                            help="resume from a snapshot file")
        parser.add_argument("-i", "--interactive", action="store_true",
                            help="initialize the workflow, then drop "
                                 "into a console with it in scope; "
                                 "call main() there (or exit) to run")
        parser.add_argument("-v", "--verbosity", default="info",
                            choices=["debug", "info", "warning", "error"],
                            help="logging level")
        parser.add_argument("--version", action="store_true",
                            help="print version and exit")
        parser.add_argument("--dump-config", action="store_true",
                            help="print the effective config tree and run")
        parser.add_argument("--dry-run", choices=["init", "exec"],
                            default=None,
                            help="stop after workflow construction (exec) "
                                 "or initialization (init)")
        parser.add_argument("--workflow-graph", default=None,
                            help="write the workflow DOT graph to this file")
        parser.add_argument("--trace-out", default=None, metavar="FILE",
                            help="enable span tracing and dump the trace "
                                 "buffer (Chrome trace-event JSON, open "
                                 "in Perfetto) to FILE at exit; on a "
                                 "master/slave pair pointed at the same "
                                 "FILE the dumps merge into one "
                                 "correlated timeline")
        parser.add_argument("--result-file", default=None,
                            help="write gathered results JSON here")
        parser.add_argument("--optimize", default=None, metavar="GENS:POP",
                            help="run the genetic hyperparameter optimizer")
        parser.add_argument("--ensemble-train", default=None,
                            metavar="N:RATIO",
                            help="train an ensemble of N models on "
                                 "RATIO-subsampled data")
        parser.add_argument("--ensemble-test", default=None, metavar="N",
                            help="evaluate a trained ensemble")
        parser.add_argument("--visualize", default=None, metavar="SNAPSHOT",
                            help="no-op placeholder for plot-only mode")
        return parser

    # -- seeding (``veles/__main__.py:483-537``) ---------------------------

    def _seed_random(self, spec):
        keys = ("default", "loader", "chaos")
        for key, one in zip(keys, str(spec).split(",")):
            self._seed_one(key, one)
        # unseeded keys derive from the first
        for key in keys[len(str(spec).split(",")):]:
            prng.get(key).seed(prng.get(keys[0]).randint(1 << 31))

    def _seed_one(self, key, spec):
        if ":" in spec:
            source, count = spec.rsplit(":", 1)
            with open(source, "rb") as f:
                data = f.read(int(count))
            seed = int.from_bytes(data[:8] or b"\x01", "little")
        else:
            seed = int(spec)
        prng.get(key).seed(seed)

    # -- model / config loading (``__main__.py:396-481``) ------------------

    def _load_model(self, path):
        """Import the workflow file as a module."""
        path = os.path.abspath(path)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        name = os.path.splitext(os.path.basename(path))[0]
        sys.path.insert(0, os.path.dirname(path))
        try:
            spec = importlib.util.spec_from_file_location(name, path)
            module = importlib.util.module_from_spec(spec)
            sys.modules[name] = module
            spec.loader.exec_module(module)
        finally:
            sys.path.pop(0)
        return module

    def _apply_config(self, path):
        if path and os.path.exists(path):
            apply_config_file(path)
            return True
        return False

    def _override_config(self, overrides):
        """Exec positional ``root.a.b=value`` assignments."""
        for item in overrides:
            if "=" not in item:
                raise ValueError("config override %r is not key=value"
                                 % item)
            exec(item, {"root": root})  # noqa: S102 — reference semantics

    # -- workflow construction ---------------------------------------------

    def _find_workflow_class(self, module):
        from veles_tpu.workflow import Workflow
        candidates = [
            obj for obj in vars(module).values()
            if isinstance(obj, type) and issubclass(obj, Workflow) and
            obj.__module__ == module.__name__]
        if not candidates:
            raise ValueError(
                "%s defines neither run(load, main) nor a Workflow "
                "subclass" % module.__name__)
        # the most derived class defined in the file
        candidates.sort(key=lambda c: len(c.__mro__), reverse=True)
        return candidates[0]

    def _launcher_kwargs(self):
        args = self.args
        kwargs = {
            "backend": getattr(args, "backend", None),
            "testing": getattr(args, "testing", False),
            "slave_death_probability": args.slave_death_probability,
            "job_timeout": args.job_timeout,
            "graphics": getattr(args, "graphics", True),
            "web_status": getattr(args, "web_status", False),
            "nodes": getattr(args, "nodes", None),
            "respawn": getattr(args, "respawn", False),
            "eager": getattr(args, "eager", False),
            "segment_size": getattr(args, "segment_size", 8),
            "pipeline": getattr(args, "pipeline", True),
            "secret_file": getattr(args, "secret_file", None),
            "max_frame_mb": getattr(args, "max_frame_mb", None),
            "interactive": getattr(args, "interactive", False),
            "exchange_dtype": getattr(args, "exchange_dtype", "none"),
            "exchange_eps": getattr(args, "exchange_eps", 0.0),
            "auto_resume": getattr(args, "auto_resume", None),
            "straggler_drop_s": getattr(args, "straggler_drop_s", None),
            "reconnect_s": getattr(args, "reconnect_s", None),
            "gspmd": getattr(args, "gspmd", None),
        }
        if args.listen_address:
            kwargs["listen_address"] = args.listen_address
        if args.master_address:
            kwargs["master_address"] = args.master_address
        return kwargs

    def _load(self, WorkflowClass, **kwargs):
        """Callback handed to the user file's run(load, main)."""
        self.launcher = Launcher(**self._launcher_kwargs())
        if self.args.snapshot:
            from veles_tpu.snapshotter import SnapshotterToFile
            self.workflow = SnapshotterToFile.import_(self.args.snapshot)
            self.workflow.workflow = self.launcher
            snapshot = True
        else:
            self.workflow = WorkflowClass(self.launcher, **kwargs)
            snapshot = False
        return self.workflow, snapshot

    def _main(self, **kwargs):
        """Second callback: initialize and run under the launcher."""
        if self.args.dry_run == "exec":
            return
        self.launcher.initialize(**kwargs)
        if self.args.workflow_graph:
            with open(self.args.workflow_graph, "w") as f:
                f.write(self.workflow.generate_graph())
            self.info("wrote workflow graph to %s",
                      self.args.workflow_graph)
        if self.args.dry_run == "init":
            return
        if getattr(self.args, "interactive", False):
            self._interact()
            if self._run_error is not None:
                # the console swallowed (printed) the training failure;
                # the process exit code must still reflect it
                raise self._run_error
        if not self._ran:
            self._run_and_report()

    def _run_and_report(self):
        if self._ran:
            # -i console: a second main() would retrain from the
            # already-trained weights and silently overwrite the result
            # file — warn and keep the existing results
            self.warning("main() already ran in this session; skipping "
                         "(results were already written)")
            return
        self._ran = True  # even on failure: exiting must NOT retrain
        try:
            self._run_and_report_inner()
        except BaseException as e:
            self._run_error = e
            raise

    def _run_and_report_inner(self):
        self.launcher.run()
        self._write_results()
        # exit reports, as the reference printed at shutdown: slowest
        # units (``veles/workflow.py:788-825``) and peak device memory
        # (``veles/__main__.py:779-797`` + memory.py Watcher)
        self.workflow.print_stats()
        from veles_tpu.memory import watcher
        mem = watcher.report()
        self.info("device memory: %.1f MB in use, %.1f MB peak, "
                  "%d arrays", mem["bytes_in_use"] / 1e6,
                  mem["peak_bytes"] / 1e6, mem["arrays"])

    def _interact(self):
        """-i: console between initialize and run (the TPU-era analog
        of the reference running the whole stack under an IPython
        shell with the reactor in a thread,
        ``veles/launcher.py:119,433-459``; here the scheduler is not
        reactor-driven, so the console simply OWNS the step: call
        ``main()`` inside to train now, or exit and the run resumes).
        """
        ns = {
            "workflow": self.workflow,
            "launcher": self.launcher,
            "units": list(self.workflow.units),
            "root": root,
            "main": self._run_and_report,
        }
        banner = ("\nveles_tpu interactive mode — workflow initialized,"
                  " not yet run.\n"
                  "In scope: workflow, launcher, units, root, main().\n"
                  "main() trains now; exiting the console trains if "
                  "you haven't.")
        use_ipython = sys.stdin.isatty()
        if use_ipython:
            try:
                from IPython.terminal.embed import InteractiveShellEmbed
            except ImportError:
                use_ipython = False
        try:
            if use_ipython:
                InteractiveShellEmbed(banner1=banner)(local_ns=ns)
            else:
                # piped stdin (tests, batch use): the stdlib console
                # reads scripted lines and EOFs out cleanly
                import code
                code.interact(banner=banner, local=ns, exitmsg="")
        except SystemExit:
            pass

    def _write_results(self):
        if not self.args.result_file:
            return
        results = self.workflow.gather_results()
        with open(self.args.result_file, "w") as f:
            json.dump(results, f, indent=2, default=str)
        self.info("wrote results to %s", self.args.result_file)

    # -- dispatch ----------------------------------------------------------

    def _run_regular(self, module):
        run_fn = getattr(module, "run", None)
        if callable(run_fn):
            run_fn(self._load, self._main)
        else:
            WorkflowClass = self._find_workflow_class(module)
            self._load(WorkflowClass)
            self._main()
        return self.EXIT_SUCCESS

    def _run_optimize(self, module):
        from veles_tpu.genetics import GeneticsOptimizer
        gens, _, pop = self.args.optimize.partition(":")
        optimizer = GeneticsOptimizer(
            workflow_file=self.args.workflow,
            config_file=self.args.config,
            generations=int(gens or 10),
            population_size=int(pop or 20),
            result_file=self.args.result_file)
        optimizer.run()
        return self.EXIT_SUCCESS

    def _run_ensemble_train(self, module):
        from veles_tpu.ensemble import EnsembleTrainer
        n, _, ratio = self.args.ensemble_train.partition(":")
        trainer = EnsembleTrainer(
            workflow_file=self.args.workflow,
            config_file=self.args.config,
            size=int(n), train_ratio=float(ratio or 0.8),
            result_file=self.args.result_file or "ensemble.json")
        trainer.run()
        return self.EXIT_SUCCESS

    def _run_ensemble_test(self, module):
        from veles_tpu.ensemble import EnsembleTester
        tester = EnsembleTester(
            workflow_file=self.args.workflow,
            config_file=self.args.config,
            results_file=self.args.ensemble_test,
            result_file=self.args.result_file or "ensemble_test.json")
        tester.run()
        return self.EXIT_SUCCESS

    def run(self, argv=None):
        if argv is None:
            argv = sys.argv[1:]
        if argv and argv[0] == "serve":
            # the serving engine is its own process shape (no Launcher,
            # no workflow run loop) with its own flags — dispatch before
            # the training parser rejects them
            from veles_tpu.serving.frontend import main as serve_main
            return serve_main(argv[1:])
        if argv and argv[0] == "sched":
            # same for the gang scheduler's serve/submit/status surface
            from veles_tpu.sched.cli import sched_main
            return sched_main(argv[1:])
        parser = self.init_parser()
        # intermixed: bare k=v override positionals legally FOLLOW
        # options (the ensemble/genetics evaluators build argv that
        # way), which plain parse_args rejects once the optional
        # arguments have consumed the scan position
        self.args = parser.parse_intermixed_args(argv)
        self._ran = False
        self._run_error = None
        if self.args.version:
            from veles_tpu import __version__
            print(__version__)
            return self.EXIT_SUCCESS
        setup_logging(getattr(logging, self.args.verbosity.upper()))
        if not self.args.workflow:
            parser.print_usage()
            return self.EXIT_FAILURE
        # any bare k=v positionals may have landed in config/overrides
        overrides = list(self.args.overrides)
        if self.args.config and "=" in self.args.config:
            overrides.insert(0, self.args.config)
            self.args.config = None
        if self.args.config is None:
            guess = os.path.splitext(self.args.workflow)[0] + "_config.py"
            self.args.config = guess if os.path.exists(guess) else None

        if getattr(self.args, "precision", None):
            from veles_tpu.nn.precision import set_policy
            set_policy(self.args.precision)
        if getattr(self.args, "jax_coordinator", None) and \
                not getattr(self.args, "jax_processes", None):
            # a coordinator with no process count would leave THIS host
            # standalone while its peers block at the coordinator
            raise SystemExit(
                "--jax-coordinator requires --jax-processes (and "
                "--jax-process-id) on every host")
        if getattr(self.args, "jax_processes", None):
            # multi-host pod: join the JAX runtime BEFORE anything
            # touches a device; every host then sees the global mesh
            # and the parallel trainers shard across DCN+ICI
            from veles_tpu.parallel.mesh import init_multihost
            init_multihost(self.args.jax_coordinator,
                           self.args.jax_processes,
                           self.args.jax_process_id)
        self._seed_random(self.args.seed)
        module = self._load_model(self.args.workflow)
        self._apply_config(self.args.config)
        self._override_config(overrides)
        if self.args.dump_config:
            root.print_()

        if self.args.trace_out:
            from veles_tpu.telemetry import tracing
            tracing.enable()
            # the exit-dump merge is for the processes of ONE run
            # (master + slaves); a file left by a previous run must
            # not leak its stale timeline into this one
            try:
                os.remove(self.args.trace_out)
            except OSError:
                pass
        # periodic HBM/RSS gauges (veles_hbm_*_bytes, host RSS) for
        # the dashboard's memory panel; VELES_MEMORY_SAMPLE_S=0 off
        from veles_tpu.telemetry import profiler
        profiler.start_memory_sampler()
        try:
            if self.args.optimize:
                return self._run_optimize(module)
            if self.args.ensemble_train:
                return self._run_ensemble_train(module)
            if self.args.ensemble_test:
                return self._run_ensemble_test(module)
            return self._run_regular(module)
        except KeyboardInterrupt:
            self.warning("interrupted")
            return self.EXIT_FAILURE
        finally:
            if self.args.trace_out:
                from veles_tpu.telemetry import tracing
                n = tracing.get_buffer().dump(
                    self.args.trace_out,
                    process_name=getattr(getattr(self, "launcher", None),
                                         "mode", None) or "veles_tpu")
                self.info("wrote %d trace events to %s", n,
                          self.args.trace_out)
                # per-buffer HBM attribution rides along (pprof gzip;
                # `pprof -http : FILE` or pprof.me to inspect)
                if profiler.dump_memory_profile(
                        self.args.trace_out + ".memprof"):
                    self.info("wrote device memory profile to "
                              "%s.memprof", self.args.trace_out)


def main(argv=None):
    return Main().run(argv)


if __name__ == "__main__":
    sys.exit(main())
