"""Launcher: run-mode selection and service lifecycle.

The reference's ``Launcher`` (``veles/launcher.py:100``) owns the Twisted
reactor, picks standalone/master/slave mode from ``-l``/``-m`` flags,
spawns the graphics server, posts periodic status to the web dashboard
and manages slave processes. The TPU build has no reactor — a
single-controller JAX driver replaces the event loop — so the Launcher
here is a plain object that:

* selects the mode (``listen_address`` → master, ``master_address`` →
  slave, neither → standalone);
* owns the :class:`~veles_tpu.backends.Device` (masters do no compute,
  ``docs/source/manualrst_veles_distributed_training.rst:14``);
* wires the workflow's IDistributable protocol onto the
  :mod:`~veles_tpu.parallel.coordinator` control plane: payloads are
  pickled, zlib-compressed cross-host, and ride the Protocol's binary
  frames / same-host shm (:mod:`veles_tpu.parallel.wire` — the role of
  the reference's txzmq streaming pickle + codecs,
  ``txzmq/connection.py:140-143,283-339``);
* farms out SEGMENT jobs (N minibatches through the slave's fused
  step compiler per round-trip) whenever the workflow has the standard
  trainable shape, single-minibatch jobs otherwise;
* launches the graphics server and posts periodic status JSON to the
  web dashboard (``launcher.py:852-885``) when those services exist.
"""

import threading
import time
import uuid

from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.parallel import wire
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.registry import get_registry

_encode = wire.encode
_decode = wire.decode


def _blob_nbytes(blob):
    return blob.nbytes if isinstance(blob, wire.Chunks) else len(blob)


def parse_address(spec, default_host="127.0.0.1", default_port=5000):
    """``host:port`` | ``:port`` | ``port`` → (host, port).

    The default bind is loopback — the reference listened on all
    interfaces by default (``veles/launcher.py:820``), which combined
    with pickled payloads is remote code execution for anyone on the
    network. Binding wide now takes an explicit ``-l 0.0.0.0:port``
    (pair it with ``--secret-file``)."""
    if isinstance(spec, (tuple, list)):
        return tuple(spec)
    spec = str(spec)
    if ":" in spec:
        host, port = spec.rsplit(":", 1)
        return (host or default_host, int(port or default_port))
    if spec.isdigit():
        return (default_host, int(spec))
    return (spec, default_port)


class Launcher(Logger, metaclass=CommandLineArgumentsRegistry):
    """Owns mode, device, coordinator and auxiliary services."""

    #: kwargs consumed by the Launcher (the rest go to the workflow ctor).
    KWARGS = frozenset([
        "listen_address", "master_address", "device", "backend", "testing",
        "stealth", "web_status", "graphics", "slave_death_probability",
        "job_timeout", "heartbeat_timeout", "max_idle",
        "nodes", "respawn", "slave_command", "eager", "segment_size",
        "pipeline", "secret", "secret_file", "max_frame_mb",
        "interactive", "exchange_dtype", "exchange_eps",
        "heartbeat_interval", "auto_resume", "straggler_drop_s",
        "reconnect_s", "gspmd",
    ])

    def __init__(self, **kwargs):
        super(Launcher, self).__init__()
        unknown = set(kwargs) - self.KWARGS
        if unknown:
            raise TypeError("unknown Launcher kwargs: %s" % ", ".join(
                sorted(unknown)))
        self.listen_address = kwargs.get("listen_address")
        self.master_address = kwargs.get("master_address")
        if self.listen_address and self.master_address:
            raise ValueError("cannot be both master (-l) and slave (-m)")
        self.device = kwargs.get("device")
        self.backend = kwargs.get("backend")
        self.testing = kwargs.get("testing", False)
        self.stealth = kwargs.get("stealth", False)
        self.web_status = kwargs.get("web_status", False)
        self.graphics = kwargs.get("graphics", True)
        self.slave_death_probability = kwargs.get(
            "slave_death_probability", 0.0)
        self.job_timeout = kwargs.get("job_timeout")
        self.heartbeat_timeout = kwargs.get("heartbeat_timeout", 10.0)
        #: slave: seconds between heartbeats (each reports the previous
        #: beat's RTT, aggregated on the master per slave)
        self.heartbeat_interval = kwargs.get("heartbeat_interval", 2.0)
        self.max_idle = kwargs.get("max_idle")
        from veles_tpu.envknob import env_knob
        #: fault-tolerance knobs (ISSUE 12, docs/FAULT_TOLERANCE.md):
        #: auto_resume = snapshot directory the master checkpoints to
        #: on every epoch close and restores from on restart
        self.auto_resume = kwargs.get("auto_resume") or \
            env_knob("VELES_AUTO_RESUME")
        #: master: drop (and requeue the jobs of) a slave held in the
        #: health scorer's straggler state this long (None = alert
        #: only). None-aware fallbacks throughout: the CLI always
        #: passes these kwargs (argparse defaults are None), so a
        #: plain dict.get default would shadow the env knobs
        drop_s = kwargs.get("straggler_drop_s")
        if drop_s is None:
            drop_s = env_knob("VELES_STRAGGLER_DROP_S", parse=float)
        self.straggler_drop_s = None if drop_s in (None, "") \
            else float(drop_s)
        #: slave: on master loss mid-run, re-handshake with exponential
        #: backoff + jitter for up to this many seconds (the window a
        #: restarted master needs to restore its snapshot and re-bind)
        reconnect_s = kwargs.get("reconnect_s")
        if reconnect_s in (None, ""):
            reconnect_s = env_knob("VELES_RECONNECT_S", 30.0,
                                   parse=float)
        self.reconnect_s = float(reconnect_s)
        self._resumed_from = None
        self._resume_complete = False
        self._last_snap_epochs = 0
        self._snapshot_lock = threading.Lock()
        self.nodes = kwargs.get("nodes")
        self.respawn = kwargs.get("respawn", False)
        self.eager = kwargs.get("eager", False)
        #: -i: the run is driven from a console (reference
        #: ``launcher.py:119`` ran the stack under IPython); Shell
        #: units check this to avoid embedding a console in a console
        self.interactive = kwargs.get("interactive", False)
        #: GSPMD tier (ISSUE 15): a mesh spec string ("auto",
        #: "batch=8,model=1", "8x1") routes the standalone run through
        #: one jitted SPMD step over the named batch×model mesh — the
        #: gradient merge is a compiler-inserted psum instead of the
        #: coordinator's host-mediated exchange. None/"" = off.
        #: VELES_GSPMD env is the fallback (the bench legs use it).
        gspmd = kwargs.get("gspmd")
        if gspmd in (None, ""):
            gspmd = env_knob("VELES_GSPMD")
        self.gspmd = gspmd
        #: minibatches per distributed job (1 = reference-style);
        #: segments amortize the round-trip + weight exchange
        self.segment_size = kwargs.get("segment_size", 8)
        #: slave: prefetch the next job while computing (async SGD,
        #: one job of weight staleness); False = strict lockstep
        self.pipeline = kwargs.get("pipeline", True)
        #: master->slave parameter-delta exchange: None/"none" = full
        #: weights every job (bit-compatible with the strict protocol);
        #: "float32" = per-leaf deltas with a dirty/epsilon skip;
        #: "bfloat16" = deltas cast to bf16, halving exchange bytes
        #: (bounded one-push quantization error; async-SGD class, like
        #: --pipeline's staleness)
        dtype = kwargs.get("exchange_dtype")
        self.exchange_dtype = None if dtype in (None, "none") else dtype
        #: with delta exchange: skip leaves whose max |delta| is <= eps
        #: (0.0 = skip only exactly-unchanged leaves)
        self.exchange_eps = float(kwargs.get("exchange_eps", 0.0))
        #: shared secret for the coordinator's mutual HMAC handshake:
        #: explicit kwarg > --secret-file > VELES_TPU_SECRET env
        self.secret = kwargs.get("secret")
        secret_file = kwargs.get("secret_file")
        if self.secret is None and secret_file:
            with open(secret_file) as fin:
                # empty/whitespace file must NOT become secret="" (that
                # would "authenticate" with a zero-entropy key while
                # suppressing the no-secret warning)
                self.secret = fin.read().strip() or None
        if self.secret is None:
            self.secret = env_knob("VELES_TPU_SECRET")
        #: per-connection binary frame cap (MB); the 256 MB default
        #: covers AlexNet-scale weight pickles, VGG-scale needs more
        mb = kwargs.get("max_frame_mb")
        self.max_frame = int(mb * 1024 * 1024) if mb else None
        #: "fused" | "eager" once the standalone run path is chosen
        self.run_mode_used = None
        self.slave_command = kwargs.get("slave_command")
        self._node_launcher = None
        self.id = str(uuid.uuid4())
        self.log_id = self.id[:8]
        self.workflow = None
        self.stopped = False
        self.start_time = None
        self._server = None
        self._client = None
        self._graphics_server = None
        self._status_thread = None
        self._finished = threading.Event()
        self.plots_endpoints = ()

    @staticmethod
    def init_parser(parser):
        parser.add_argument(
            "-l", "--listen", dest="listen_address", default=None,
            help="run as MASTER, listening for slaves on HOST:PORT")
        parser.add_argument(
            "-m", "--master", dest="master_address", default=None,
            help="run as SLAVE of the master at HOST:PORT")
        parser.add_argument(
            "--test", dest="testing", action="store_true",
            help="run the workflow in testing (forward-only) mode")
        parser.add_argument(
            "--slave-death-probability", type=float, default=0.0,
            help="chaos: probability a slave dies mid-job (fault "
                 "injection parity with the reference)")
        parser.add_argument(
            "--job-timeout", type=float, default=None,
            help="master: drop a slave whose job overruns this many "
                 "seconds (adaptive mean+3sigma otherwise)")
        parser.add_argument(
            "--no-graphics", dest="graphics", action="store_false",
            help="do not launch the plotting service")
        parser.add_argument(
            "-n", "--nodes", default=None,
            help="master: spawn slaves on these hosts over SSH "
                 "(host[,host*N,...])")
        parser.add_argument(
            "--respawn", action="store_true",
            help="master: relaunch dead slaves with backoff")
        parser.add_argument(
            "--web-status", action="store_true",
            help="post periodic status JSON to the web dashboard")
        parser.add_argument(
            "--eager", action="store_true",
            help="run the eager per-unit scheduler instead of the fused "
                 "XLA step compiler (the default for standard-shaped "
                 "workflows)")
        parser.add_argument(
            "--gspmd", dest="gspmd", nargs="?", const="auto",
            default=None, metavar="MESH",
            help="standalone/pod: run the single-launcher GSPMD path — "
                 "the whole train step under one jit with NamedShardings "
                 "over a named batch×model mesh, gradient merge as a "
                 "compiler-inserted psum over ICI (docs/"
                 "distributed_training.md §GSPMD tier). MESH like "
                 "'batch=8,model=1' or '8x1'; bare --gspmd puts every "
                 "device on the batch axis (VELES_GSPMD env fallback)")
        parser.add_argument(
            "--segment-size", type=int, default=8,
            help="minibatches per distributed job (master mode); 1 "
                 "reproduces the reference's one-minibatch-per-job "
                 "protocol")
        parser.add_argument(
            "--secret-file", dest="secret_file", default=None,
            help="file holding the shared secret for the master<->slave "
                 "HMAC handshake (VELES_TPU_SECRET env is the fallback; "
                 "required sense: always set one when listening beyond "
                 "loopback)")
        parser.add_argument(
            "--max-frame-mb", dest="max_frame_mb", type=float,
            default=None,
            help="master/slave: raise the per-connection binary frame "
                 "cap (default 256 MB) for models whose pickled weight "
                 "payload is larger")
        parser.add_argument(
            "--no-pipeline", dest="pipeline", action="store_false",
            help="slave: strict request-reply instead of prefetching "
                 "the next job while computing (exact sequential SGD, "
                 "no overlap)")
        parser.add_argument(
            "--exchange-dtype", dest="exchange_dtype", default="none",
            choices=["none", "float32", "bfloat16"],
            help="master: after the first full weight push, send "
                 "per-leaf parameter DELTAS to each slave (skipping "
                 "unchanged leaves); bfloat16 additionally casts the "
                 "deltas, halving master->slave exchange bytes")
        parser.add_argument(
            "--exchange-eps", dest="exchange_eps", type=float,
            default=0.0,
            help="with --exchange-dtype: also skip leaves whose "
                 "largest delta magnitude is <= EPS (default 0: skip "
                 "only exactly-unchanged leaves)")
        parser.add_argument(
            "--auto-resume", dest="auto_resume", default=None,
            metavar="DIR",
            help="master: snapshot to DIR on every epoch close and, "
                 "on restart, resume from the latest loadable snapshot "
                 "there (VELES_AUTO_RESUME env is the fallback)")
        parser.add_argument(
            "--straggler-drop-s", dest="straggler_drop_s", type=float,
            default=None,
            help="master: requeue the jobs of (and drop) a slave the "
                 "health scorer has flagged straggler for this many "
                 "seconds (default: alert only)")
        parser.add_argument(
            "--reconnect-s", dest="reconnect_s", type=float,
            default=None,
            help="slave: when the master vanishes mid-run, retry the "
                 "handshake with exponential backoff for up to this "
                 "many seconds before giving up (0 disables; default "
                 "30, VELES_RECONNECT_S env overrides)")
        return parser

    # -- mode --------------------------------------------------------------

    @property
    def mode(self):
        if self.listen_address:
            return "master"
        if self.master_address:
            return "slave"
        return "standalone"

    @property
    def is_standalone(self):
        return self.mode == "standalone"

    @property
    def is_master(self):
        return self.mode == "master"

    @property
    def is_slave(self):
        return self.mode == "slave"

    @property
    def is_interactive(self):
        return self.interactive

    # -- workflow ownership (Unit.workflow protocol) -----------------------

    def add_ref(self, workflow):
        self.workflow = workflow

    def del_ref(self, workflow):
        if self.workflow is workflow:
            self.workflow = None

    def on_workflow_finished(self):
        self._finished.set()
        if self._server is not None:
            self._server.no_more_jobs = True

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        """Create the device, initialize the workflow, start services."""
        if self.workflow is None:
            raise RuntimeError("no workflow attached to this launcher")
        self.start_time = time.time()
        if self.device is None and not self.is_master:
            # masters do no compute — no device
            from veles_tpu.backends import Device
            self.device = Device(backend=self.backend)
        if self.graphics and not root.common.disable.get("plotting", True):
            self._launch_graphics()
        if self.auto_resume and not self.is_slave:
            # replaces self.workflow when a loadable snapshot exists;
            # must run before the finished callback / initialize below
            # so the RESTORED graph gets them
            self._try_auto_resume()
        self.workflow.add_finished_callback(self.on_workflow_finished)
        if self.testing:
            set_testing = getattr(self.workflow, "set_testing", None)
            if set_testing is not None:
                set_testing(True)
            else:
                self.warning("--test requested but %s has no set_testing",
                             type(self.workflow).__name__)
        # read BEFORE workflow.initialize: the units consume their
        # restored markers there
        was_restored = bool(getattr(self.workflow,
                                    "_restored_from_snapshot_", False))
        self.workflow.initialize(device=self.device, **kwargs)
        if self.is_master:
            if was_restored and self._resumed_from is None:
                # ANY snapshot-restored master (-w snap, manual
                # import_, not just --auto-resume) rewinds to the last
                # closed epoch boundary: a snapshot dumped while
                # run-ahead results were being merged-then-cancelled
                # has consumed minibatches of epochs that never
                # closed — without the rewind those epochs can never
                # complete on sample counts and the resumed run wedges
                self._prepare_master_resume(self.workflow)
            self._start_master()
        elif self.is_slave:
            self._connect_slave()
        if self.web_status:
            self._start_status_notifier()
            self._attach_dashboard_sinks()
        return self

    def _try_auto_resume(self):
        """Master restart (ISSUE 12 tentpole part 3): restore the
        newest loadable snapshot from the auto-resume directory and
        rewind to the last closed epoch boundary, so a master that
        died mid-run comes back and the epoch replays instead of
        hanging half-merged. A corrupt newest artifact falls back to
        the previous one (snapshotter.restore_latest)."""
        from veles_tpu import snapshotter as snap_mod
        t0 = time.perf_counter()
        try:
            restored, path = snap_mod.restore_latest(self.auto_resume)
        except FileNotFoundError as e:
            self.info("auto-resume: %s — starting fresh", e)
            return
        restored.workflow = self  # re-bind to this launcher
        self.workflow = restored
        self._resumed_from = path
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        get_registry().histogram(
            "veles_recovery_ms",
            "Fault detection to training progress resumed",
            labels=("event",)).labels(event="restore").observe(elapsed_ms)
        history = getattr(getattr(restored, "decision", None),
                          "epoch_history", [])
        self.info("auto-resumed from %s in %.0f ms (%d epoch(s) "
                  "closed)", path, elapsed_ms, len(history))
        if self.is_master:
            self._prepare_master_resume(restored)

    def _prepare_master_resume(self, wf):
        """On a master the transient merge buckets died with the old
        process: rewind to the last closed epoch boundary and replay
        (the snapshot's own shuffle state makes the replay serve the
        identical index order)."""
        decision = getattr(wf, "decision", None)
        loader = getattr(wf, "loader", None)
        if decision is None or loader is None:
            return
        resume_epoch = decision.prepare_resume()
        if resume_epoch is None:
            self.info("restored run is already complete; nothing to "
                      "resume")
            self._resume_complete = True
            return
        loader.reset_to_epoch_start(resume_epoch)
        self._last_snap_epochs = len(decision.epoch_history)
        self.info("master resume: replaying epoch %d from its start",
                  resume_epoch)

    def _maybe_master_snapshot(self):
        """Master-side snapshot cadence: one snapshot per CLOSED epoch
        into the auto-resume directory (called from result_sink after
        each merge — the master's graph never executes, so the
        Snapshotter unit cannot gate here; adding one would also
        change the checksum slaves handshake against)."""
        wf = self.workflow
        decision = getattr(wf, "decision", None)
        if decision is None:
            return
        if len(decision.epoch_history) <= self._last_snap_epochs:
            return
        if not self._snapshot_lock.acquire(blocking=False):
            return  # a sibling result thread is already dumping
        try:
            if len(decision.epoch_history) <= self._last_snap_epochs:
                return
            from contextlib import ExitStack
            from veles_tpu.snapshotter import (dump_workflow,
                                               save_snapshot)
            with ExitStack() as stack:
                # a SIBLING result thread may be mid-merge (result_sink
                # runs outside the coordinator lock by design): hold
                # every unit's data lock for the IN-MEMORY dump so no
                # weight array is pickled half-applied. Deadlock-free:
                # merge threads take ONE unit lock at a time and never
                # wait on the snapshot lock. The compress+disk write
                # happens AFTER release — merges must not stall on I/O.
                for unit in wf._distributed_units():
                    lock = getattr(unit, "_data_lock_", None)
                    if lock is not None:
                        stack.enter_context(lock)
                payload = dump_workflow(wf)
            path, nbytes = save_snapshot(wf, self.auto_resume,
                                         payload=payload)
            self._last_snap_epochs = len(decision.epoch_history)
            self.info("master snapshot -> %s (%.1f MiB, %d epoch(s))",
                      path, nbytes / 1048576.0, self._last_snap_epochs)
        except Exception:
            # checkpointing must never kill training
            self.warning("master snapshot failed", exc_info=True)
        finally:
            self._snapshot_lock.release()

    def _launch_graphics(self):
        try:
            from veles_tpu.graphics_server import GraphicsServer
        except ImportError:
            self.warning("graphics server unavailable; plots disabled")
            return
        self._graphics_server = GraphicsServer()
        self.plots_endpoints = self._graphics_server.endpoints

    def _start_master(self):
        from veles_tpu.parallel.coordinator import (CoordinatorServer,
                                                    NoMoreJobsError)
        from veles_tpu.workflow import NoMoreJobs
        workflow = self.workflow
        # the master never calls workflow.run() (it does no compute), so
        # lift the initial stopped state by hand before serving jobs
        workflow.stopped = False

        from veles_tpu.train.segment import segment_capable
        segments = self.segment_size > 1 and segment_capable(workflow)
        if segments:
            self.info("serving fused segment jobs (%d minibatches each)",
                      self.segment_size)

        # per-slave exchange telemetry, aggregated on the master: these
        # are the series the wire-level optimizations (PR 2) were
        # provable only through one-off bench scripts before
        registry = get_registry()
        m_bytes = registry.counter(
            "veles_exchange_bytes_total",
            "Payload bytes exchanged with each slave",
            labels=("slave", "direction"))
        m_encode_ms = registry.histogram(
            "veles_exchange_encode_ms",
            "Master time encoding one job payload", labels=("slave",))
        m_decode_ms = registry.histogram(
            "veles_exchange_decode_ms",
            "Master time decoding one slave update", labels=("slave",))
        # encode/decode times also feed the straggler scorer — a slave
        # whose exchanges run far over the peer median is the early
        # sign of a saturated link or a swapping host
        from veles_tpu.telemetry import health as health_mod
        scorer = health_mod.get_scorer()

        def job_source(slave):
            try:
                if segments:
                    data = workflow.generate_segment_for_slave(
                        slave, max_minibatches=self.segment_size)
                else:
                    data = workflow.generate_data_for_slave(slave)
            except NoMoreJobs:
                raise NoMoreJobsError()
            if data is None:
                return None
            # encode_ms brackets the WHOLE payload transform — the
            # delta diff/cast is the expensive half at model scale
            t0 = time.perf_counter()
            if self.exchange_dtype is not None:
                # per-slave delta stream: first push full, then deltas
                # (state is connection-scoped on both ends, so a
                # reconnected slave restarts with a full push)
                enc = getattr(slave, "delta_encoder", None)
                if enc is None:
                    enc = wire.DeltaEncoder(
                        dtype=None if self.exchange_dtype == "float32"
                        else self.exchange_dtype, eps=self.exchange_eps)
                    slave.delta_encoder = enc
                data = enc.encode(data)
            if slave.sharedio:
                # same-host: out-of-band array framing as scatter/gather
                # chunks — Protocol.send memcpys each array straight
                # into the shared segment, no pickle byte-string ever
                # materializes (docs/PERF.md r5: that pickle pass alone
                # cost 1.8 s at AlexNet-227 scale)
                blob = wire.encode_chunks(data)
            else:
                # remote slaves get zlib-compressed binary frames
                blob = _encode(data, compress=True)
            encode_ms = (time.perf_counter() - t0) * 1e3
            m_encode_ms.labels(slave=slave.id).observe(encode_ms)
            # create=False: this runs outside the coordinator lock —
            # it must not resurrect a slave the reaper just removed
            scorer.observe(slave.id, encode_ms=encode_ms, create=False)
            m_bytes.labels(slave=slave.id, direction="to_slave").inc(
                _blob_nbytes(blob))
            return {"blob": blob}

        def result_sink(data, slave):
            t0 = time.perf_counter()
            payload = _decode(data["blob"])
            decode_ms = (time.perf_counter() - t0) * 1e3
            m_decode_ms.labels(slave=slave.id).observe(decode_ms)
            scorer.observe(slave.id, decode_ms=decode_ms, create=False)
            m_bytes.labels(slave=slave.id, direction="from_slave").inc(
                _blob_nbytes(data["blob"]))
            workflow.apply_data_from_slave(payload, slave)
            if self.auto_resume:
                # one snapshot per closed epoch: the restart point
                self._maybe_master_snapshot()

        def on_drop(slave):
            workflow.drop_slave(slave)

        def initial_data_source(slave):
            payload = workflow.generate_initial_data_for_slave(slave)
            loader = getattr(workflow, "loader", None)
            decision = getattr(workflow, "decision", None)
            mid_run = bool(
                getattr(loader, "_global_offset", 0) or
                getattr(loader, "epoch_number", 0) or
                getattr(decision, "epoch_history", None))
            if mid_run and hasattr(workflow,
                                   "generate_resync_for_slave"):
                # elastic join (ISSUE 12): a slave entering a run in
                # progress gets the FULL live state in its handshake —
                # weights, decision state, epoch cursors, PRNG streams
                # — so its first job is indistinguishable from a
                # resident slave's
                payload = {
                    "units": payload,
                    "resync": workflow.generate_resync_for_slave(slave)}
            return _encode(payload, compress=not slave.sharedio)

        def on_slave_flight(sid, notice):
            # a slave's flight recorder tripped: dump ONE cluster
            # record on the master — its own ring + the per-slave
            # health table + the run's shared trace id — so a NaN on
            # one slave yields one correlated artifact, not N files
            # (the recorder's per-reason rate limit collapses a
            # same-sweep storm from many slaves into one dump)
            from veles_tpu.telemetry import federation as fed_mod
            from veles_tpu.telemetry import flight as flight_mod
            reason = str(notice.get("reason") or "unknown")
            self.warning("slave %s flight record (%s): %s", sid,
                         reason, notice.get("path"))
            flight_mod.get_recorder().dump(
                "cluster_" + reason, slave=sid,
                slave_record=notice.get("path"),
                slave_context=notice.get("context"),
                trace_id=notice.get("trace_id") or
                fed_mod.get_federation().run_info.get("trace_id"),
                cluster=fed_mod.cluster_report())

        bind = parse_address(self.listen_address)
        if self.secret is None and bind[0] not in (
                "127.0.0.1", "localhost", "::1"):
            self.warning(
                "master listening on %s WITHOUT a shared secret — any "
                "peer that can reach the port can submit results; set "
                "--secret-file or VELES_TPU_SECRET", bind[0])
        self._server = CoordinatorServer(
            address=bind,
            checksum=workflow.checksum,
            job_timeout=self.job_timeout,
            heartbeat_timeout=self.heartbeat_timeout,
            job_source=job_source, result_sink=result_sink,
            on_drop=on_drop, initial_data_source=initial_data_source,
            secret=self.secret, max_frame=self.max_frame,
            on_slave_flight=on_slave_flight,
            straggler_drop_s=self.straggler_drop_s)
        if self._resume_complete:
            # the restored run had already finished: serve "done" to
            # every reconnecting slave instead of retraining
            self._server.no_more_jobs = True
        # every span this master records carries the run's trace id;
        # slaves adopt the same id from the handshake reply
        tracing.set_default_trace_id(self._server.trace_id)
        self.info("master listening on %s:%d", *self._server.address)
        if self.nodes:
            import socket as socket_mod
            import sys
            from veles_tpu.parallel.nodes import (NodeLauncher,
                                                  slave_command_from_argv)
            # remote slaves can't dial a wildcard/loopback listen
            # address — advertise this host's name instead
            # (``veles/launcher.py:820-822``)
            host, port = self._server.address
            if host in ("127.0.0.1", "::1"):
                # loopback bind: advertise loopback VERBATIM — local
                # "localhost" nodes can still dial it, and rewriting to
                # gethostname() would point slaves at an external IP
                # where nothing listens
                self.warning(
                    "--nodes with a loopback listen address: remote "
                    "slaves cannot reach this master — pass an explicit "
                    "-l 0.0.0.0:%d (with --secret-file) for remote "
                    "nodes", port)
            if host in ("", "0.0.0.0", "::"):
                # wildcard bind: the master listens everywhere, but
                # slaves need a concrete name to dial
                host = socket_mod.gethostname()
            advertise = (host, port)
            command = self.slave_command or slave_command_from_argv(
                sys.argv[1:], advertise)
            self._node_launcher = NodeLauncher(
                self.nodes, command, master_address=advertise,
                respawn=self.respawn).start()
        self._start_slave_stats()

    def _start_slave_stats(self, interval=2.0):
        """Master-side driver for the per-slave load chart.

        The master never executes workflow units (jobs run on slaves,
        and plotters are disabled there), so the SlaveStats plotter
        cannot ride the unit graph — it ticks on its own timer off the
        live coordinator registry, the role the reference fed from
        ``apply_data_from_slave`` callbacks
        (``veles/plotting_units.py:822``). Only started when a
        graphics server exists to publish to."""
        if self._graphics_server is None or self._server is None:
            return
        from veles_tpu.plotting_units import SlaveStats
        plotter = SlaveStats(self.workflow, name="slave stats",
                             server=self._server)
        self._slave_stats_plotter = plotter

        def tick():
            warned = False
            while not self._finished.wait(interval):
                try:
                    plotter.run()
                    warned = False  # re-arm: log each NEW failure streak
                except Exception:  # a chart must never kill the master
                    if not warned:
                        warned = True
                        self.warning("SlaveStats plotter failing; chart "
                                     "stale until it recovers",
                                     exc_info=True)

        threading.Thread(target=tick, daemon=True,
                         name="slave-stats").start()

    def _connect_slave(self):
        from veles_tpu.parallel.coordinator import CoordinatorClient
        self._client = CoordinatorClient(
            parse_address(self.master_address, default_host="127.0.0.1"),
            checksum=self.workflow.checksum,
            power=self.workflow.computing_power,
            death_probability=self.slave_death_probability,
            pipeline=self.pipeline, secret=self.secret,
            max_frame=self.max_frame,
            heartbeat_interval=self.heartbeat_interval,
            reconnect_s=self.reconnect_s)

        def on_reconnect(client):
            # the client re-handshook with a (possibly restarted)
            # master: adopt its trace id and re-apply its initial
            # data / full-push resync exactly like a fresh join
            if client.trace_id:
                tracing.set_default_trace_id(client.trace_id)
            if client.initial_data is not None:
                self.workflow.apply_initial_data_from_master(
                    _decode(client.initial_data))

        self._client.on_reconnect = on_reconnect
        self._client.connect()
        if self._client.trace_id:
            # adopt the master's run-wide trace id: this slave's unit/
            # step spans merge with the master's on one timeline
            tracing.set_default_trace_id(self._client.trace_id)
        self.info("connected to master as slave %s", self._client.id)
        # when THIS slave's black box trips (NaN, stall, crash), tell
        # the master on the next (woken) heartbeat so it can dump the
        # correlated cluster record
        from veles_tpu.telemetry import flight as flight_mod
        client = self._client

        def notify(reason, path, context):
            if not reason.startswith("cluster_"):
                client.notify_flight(reason, path, context)

        self._flight_listener = notify
        flight_mod.get_recorder().add_dump_listener(notify)
        if self._client.initial_data is not None:
            # the MASTER's negotiates_on_connect state, from the handshake
            self.workflow.apply_initial_data_from_master(
                _decode(self._client.initial_data))

    def _start_status_notifier(self):
        def notify():
            interval = root.common.web.get("notification_interval", 1.0)
            url = "http://%s:%d/update" % (root.common.web.host,
                                           root.common.web.port)
            import json
            import urllib.request
            while not self._finished.wait(interval):
                try:
                    payload = json.dumps(self.status()).encode()
                    req = urllib.request.Request(
                        url, data=payload,
                        headers={"Content-Type": "application/json"})
                    urllib.request.urlopen(req, timeout=2.0)
                except Exception:
                    pass

        self._status_thread = threading.Thread(
            target=notify, daemon=True, name="status-notifier")
        self._status_thread.start()

    def _attach_dashboard_sinks(self):
        """Feed the dashboard's logs page and event timeline live
        (the reference duplicated both into Mongo; here they POST)."""
        import logging as logging_mod
        from veles_tpu import logger as logger_mod
        from veles_tpu.web_status import (WebStatusEventSink,
                                          WebStatusLogHandler)
        self._web_log_handler = WebStatusLogHandler(
            session=self.log_id, node=self.mode)
        logging_mod.getLogger().addHandler(self._web_log_handler)
        self._web_event_sink = logger_mod.add_event_sink(
            WebStatusEventSink(session_id=self.log_id))

    def _detach_dashboard_sinks(self):
        import logging as logging_mod
        from veles_tpu import logger as logger_mod
        handler = getattr(self, "_web_log_handler", None)
        if handler is not None:
            logging_mod.getLogger().removeHandler(handler)
            handler.close()
            self._web_log_handler = None
        sink = getattr(self, "_web_event_sink", None)
        if sink is not None:
            logger_mod.remove_event_sink(sink)
            sink.close()
            self._web_event_sink = None

    def status(self):
        """Periodic master status JSON (``launcher.py:852-885``)."""
        wf = self.workflow
        slaves = {}
        if self._server is not None:
            slaves = {s.id: {"power": s.power, "state": s.state,
                             "jobs_done": s.jobs_done,
                             "in_flight": len(s.jobs_in_flight),
                             "age": round(time.time() - s.last_seen, 1)}
                      for s in self._server.snapshot_slaves()}
        if wf is not None and getattr(self, "_graph_cache", None) is None:
            try:
                self._graph_cache = wf.graph_description()
            except Exception:
                # transient (e.g. racing a unit mutation): retry on the
                # next status tick instead of blanking the graph view
                # for the whole run
                self._graph_cache = None
        perf = {}
        try:
            from veles_tpu.telemetry import flight
            from veles_tpu.telemetry.registry import get_registry
            mfu = get_registry().get("veles_step_mfu")
            if mfu is not None:
                perf["mfu"] = mfu.value
            record = flight.last_record_path()
            if record:
                perf["flight_record"] = record
        except Exception:
            pass
        cluster = None
        if self._server is not None:
            # the per-slave health table rides the status POST so a
            # dashboard in ANOTHER process can serve /cluster.json too
            try:
                from veles_tpu.telemetry import federation
                cluster = federation.cluster_report()
            except Exception:
                cluster = None
        return {
            "id": self.id, "log_id": self.log_id, "mode": self.mode,
            "name": wf.name if wf else None,
            "master": self.listen_address or "",
            "time": time.time() - (self.start_time or time.time()),
            "slaves": slaves,
            "units": len(wf) if wf else 0,
            "stopped": self.stopped,
            "resumed_from": self._resumed_from,
            "perf": perf,
            "cluster": cluster,
            "graph": getattr(self, "_graph_cache", None),
        }

    def run(self):
        """Run to completion in the current mode."""
        try:
            if self.is_master:
                self._run_master()
            elif self.is_slave:
                self._run_slave()
            else:
                self._run_standalone()
        finally:
            self.stop()
        return self.workflow

    def _run_standalone(self):
        """Fused step-compiled training by default; eager on ``--eager``
        or when the graph does not fit the step compiler's contract."""
        workflow = self.workflow
        if self.eager:
            if self.gspmd:
                raise RuntimeError(
                    "--gspmd and --eager are mutually exclusive: the "
                    "GSPMD tier runs the whole step under one jit")
            self.info("running the eager per-unit scheduler (--eager)")
            self.run_mode_used = "eager"
            return workflow.run()
        custom = workflow.make_fused_runner()
        if custom is not None:
            if self.gspmd:
                raise RuntimeError(
                    "--gspmd requested but the workflow supplies its "
                    "own fused runner (%s), which the GSPMD trainer "
                    "cannot drive" % type(custom).__name__)
            self.info("running the workflow's own fused runner (%s)",
                      type(custom).__name__)
            self.run_mode_used = "fused"
            return custom.run()
        from veles_tpu.train.runner import FusedRunner, fused_compatible
        reason = fused_compatible(workflow)
        if reason is not None:
            if self.gspmd:
                # the GSPMD tier IS the step compiler; a graph it
                # cannot model cannot run launcher-SPMD either
                raise RuntimeError(
                    "--gspmd requested but the fused path is "
                    "unavailable: %s" % reason)
            self.info("fused path unavailable (%s); running eager", reason)
            self.run_mode_used = "eager"
            return workflow.run()
        if self.gspmd:
            from veles_tpu.parallel.gspmd import (GSPMDTrainer,
                                                  parse_mesh_spec)
            mesh = parse_mesh_spec(self.gspmd)
            self.info("running the GSPMD path over mesh %s",
                      dict(mesh.shape))
            self.run_mode_used = "gspmd"
            trainer = GSPMDTrainer(workflow, mesh=mesh)
            return FusedRunner(workflow, trainer=trainer).run()
        self.info("running the fused XLA step compiler")
        self.run_mode_used = "fused"
        return FusedRunner(workflow).run()

    def _run_master(self):
        # master does no compute: wait until the workflow declares
        # NoMoreJobs (job_source side) or somebody calls stop()
        while not self._finished.wait(0.1):
            if self._server.no_more_jobs and not any(
                    s.current_job or s.applying
                    for s in self._server.snapshot_slaves()):
                self._finished.set()
        # drain grace: let idle slaves collect their "done" replies
        # and disconnect cleanly — killing the server under a slave
        # mid-poll reads as a master CRASH on its side, and a slave
        # with a reconnect budget (--reconnect-s) would burn all of
        # it redialing a master that is gone on purpose
        deadline = time.time() + 5.0
        while self._server.snapshot_slaves() and time.time() < deadline:
            time.sleep(0.05)

    def _run_slave(self):
        workflow = self.workflow
        from veles_tpu.train.segment import SegmentExecutor
        executor = SegmentExecutor(workflow, eager=self.eager)
        sharedio = self._client.proto._shm_tx
        # reconstructs --exchange-dtype delta pushes against the last
        # applied payload; plain full pushes pass through untouched
        delta = wire.DeltaDecoder()

        def handler(job):
            payload = delta.decode(_decode(job["blob"]))
            if isinstance(payload, dict) and "batches" in payload:
                update = executor.execute(payload)
            else:
                update = workflow.do_job(payload)
            if sharedio:
                # zero-copy out-of-band framing straight into shm
                return {"blob": wire.encode_chunks(update)}
            return {"blob": _encode(update, compress=True)}

        self._client.serve_forever(handler, max_idle=self.max_idle)

    def stop(self):
        if self.stopped:
            return
        self.stopped = True
        self._finished.set()
        listener = getattr(self, "_flight_listener", None)
        if listener is not None:
            from veles_tpu.telemetry import flight as flight_mod
            flight_mod.get_recorder().remove_dump_listener(listener)
            self._flight_listener = None
        if self._client is not None:
            self._client.close()
        if self._node_launcher is not None:
            self._node_launcher.stop()
        if self._server is not None:
            self._server.stop()
        if self._graphics_server is not None:
            self._graphics_server.stop()
        self._detach_dashboard_sinks()

    def __repr__(self):
        return "<Launcher %s mode=%s>" % (self.log_id, self.mode)
