"""Plumbing units: loop anchors and workflow endpoints.

Re-designs ``veles/plumbing.py:17-112``. ``Repeater`` is the loop anchor:
its incoming fired-flags reset on every pass, so linking the loop tail
back into the Repeater re-triggers the chain until a Decision-style unit
blocks the path and opens the end point.
"""

from veles_tpu.units import TrivialUnit, Unit
from veles_tpu.mutable import Bool


class Repeater(TrivialUnit):
    """Loop anchor: fires dependents every time any input fires.

    Unlike ordinary units (barrier over all inputs), a repeater opens on
    *any* single input — that is what lets ``start_point → repeater`` and
    ``loop_tail → repeater`` coexist without dead-locking the barrier.
    """

    hide_from_registry = False

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("view_group", "PLUMBING")
        super(Repeater, self).__init__(workflow, **kwargs)

    def open_gate(self, src):
        if src is not None and src in self.links_from:
            self.reset_fired()
            return True
        return src is None


class StartPoint(TrivialUnit):
    """The workflow's entry unit; owned by Workflow, never user-linked-from."""

    hide_from_registry = False

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Start")
        kwargs.setdefault("view_group", "PLUMBING")
        super(StartPoint, self).__init__(workflow, **kwargs)


class EndPoint(TrivialUnit):
    """The workflow's exit unit: running it finishes the workflow."""

    hide_from_registry = False

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "End")
        kwargs.setdefault("view_group", "PLUMBING")
        super(EndPoint, self).__init__(workflow, **kwargs)

    def open_gate(self, src):
        # the end point opens on any single input: any path reaching it
        # finishes the run (multiple producers may never all fire)
        if src is not None and src in self.links_from:
            self.reset_fired()
            return True
        return src is None

    def run(self):
        self.workflow.on_workflow_finished()


class FireStarter(Unit):
    """Resets a set of Bool flags when run (``veles/plumbing.py:92``)."""

    def __init__(self, workflow, **kwargs):
        self.fire = kwargs.pop("fire", [])
        super(FireStarter, self).__init__(workflow, **kwargs)

    def initialize(self, **kwargs):
        pass

    def run(self):
        for flag in self.fire:
            if isinstance(flag, Bool):
                flag <<= False
