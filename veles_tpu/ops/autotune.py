"""Shape-aware Pallas kernel autotuner with a persistent per-device cache.

The hand-tiled Pallas kernels in this package carry ONE fixed block
config each, which is why level-0 GEMM stayed on XLA dot: the fixed
tiling beats XLA on bandwidth-bound shapes but loses ~2x on large
compute-bound squares (docs/PERF.md "GEMM disciplines"). This module
replaces the static rules with measurement: keyed by
``(op, M, N, K, dtype, transpose flags, device kind)`` it times a
bounded candidate grid of block/tile/pipeline configs against the
XLA-native implementation and persists the winner to an on-disk JSON
cache (sibling to the persistent XLA compile cache wired up in
:mod:`veles_tpu.backends`) that later runs consult at trace time —
the TPU re-realization of the reference's per-device OpenCL autotune
database (``veles/backends.py:672-731``, BLOCK_SIZE/VECTOR_OPT per
device) and of CUDA-L2-style per-shape config search (PAPERS.md).

Modes (``VELES_AUTOTUNE`` env > ``root.common.engine.autotune`` config
> default ``cache``):

* ``off``    — every consult returns ("default", None): callers use
  their legacy static dispatch, bit-for-bit today's behavior;
* ``cache``  — consult the persistent cache; a miss returns
  ("default", None) without measuring (zero startup cost, never
  blocks — the production serving mode);
* ``search`` — a miss triggers a time-budgeted measurement sweep
  (``VELES_AUTOTUNE_BUDGET_S`` per key, default 20 s) whose winner is
  persisted immediately. Searching runs ONLY where kernels can run:
  on TPU, or anywhere under ``VELES_AUTOTUNE_FORCE=interpret`` (tests
  and CI exercise the full seam in Pallas interpret mode on CPU).

Untunable environments degrade gracefully by construction: on CPU
(tier-1 CI) every plan returns the default path without measuring,
and a corrupt or stale cache file is treated as empty, never fatal.

Telemetry (the PR 4 registry): ``veles_autotune_searches_total``,
``veles_autotune_cache_hits_total``, ``veles_autotune_misses_total``
counters and a ``veles_autotune_best_tflops{op,shape}`` gauge; each
sweep runs under a ``span("autotune:search")`` so tuning shows up in
``--trace-out`` timelines.
"""

import json
import os
import re
import threading
import time

import numpy

from veles_tpu.config import root
from veles_tpu.envknob import env_knob
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.registry import get_registry

_MODES = ("off", "cache", "search")
#: a measured alternative must beat the baseline by this margin to win
#: (re-measure noise must not flap the dispatch between runs)
_WIN_MARGIN = 0.02
#: schema version: bump to invalidate every persisted entry at once
CACHE_VERSION = 1

_DEFAULT = ("default", None)
_search_lock = threading.Lock()
_caches = {}
_caches_lock = threading.Lock()
_warned_corrupt = set()


# -- mode / environment ------------------------------------------------------

def mode():
    """Resolve the tuning mode. Env knob wins over the config tree."""
    m = env_knob("VELES_AUTOTUNE")
    if not m:
        m = root.common.engine.get("autotune", "cache")
    return m if m in _MODES else "cache"


def forced_interpret():
    """True when VELES_AUTOTUNE_FORCE requests interpret-mode kernels
    (the CPU test/CI path through the full search machinery)."""
    return env_knob("VELES_AUTOTUNE_FORCE") in ("1", "interpret")


def _on_tpu():
    import jax
    return jax.default_backend() == "tpu"


def tunable():
    """May this process measure kernels at all?"""
    return _on_tpu() or forced_interpret()


def _trace_state_clean():
    """False when called from inside a jax trace (jit/grad/vmap),
    where wall-clock measurement is impossible."""
    try:
        from jax import core
        return bool(core.trace_state_clean())
    except Exception:
        return True


def kernel_interpret():
    """``interpret=`` flag consumers must pass to tuned Pallas calls:
    real kernels on TPU, interpret mode ONLY under the forced test
    path. On an untunable backend (e.g. a host where TPU init failed
    and JAX fell back to CPU) this returns False, so a shipped
    TPU-tuned cache entry degrades to each kernel's XLA fallback
    instead of silently running interpret-mode Pallas."""
    return forced_interpret() and not _on_tpu()


def device_kind():
    """Cache-file identity: one tuning database per device model."""
    import jax
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    return re.sub(r"[^a-z0-9]+", "-", str(kind).lower()).strip("-") or \
        "unknown"


def cache_path():
    explicit = env_knob("VELES_AUTOTUNE_CACHE")
    if explicit:
        return explicit
    from veles_tpu.backends import veles_cache_dir
    return os.path.join(veles_cache_dir("autotune"),
                        device_kind() + ".json")


# -- telemetry ---------------------------------------------------------------

def _metrics():
    reg = get_registry()
    return (
        reg.counter("veles_autotune_searches_total",
                    "Autotune measurement sweeps run"),
        reg.counter("veles_autotune_cache_hits_total",
                    "Autotune plans answered from the cache"),
        reg.counter("veles_autotune_misses_total",
                    "Autotune plans that fell back to the default path"),
        reg.gauge("veles_autotune_best_tflops",
                  "Best measured rate per tuned op/shape",
                  labels=("op", "shape")),
    )


# -- persistent cache --------------------------------------------------------

class AutotuneCache(object):
    """One JSON file of ``{key: entry}`` winners; load-tolerant,
    atomically rewritten, merged with on-disk state on every put so
    concurrently tuning processes do not clobber each other."""

    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()
        self._entries = None

    def _read_disk(self):
        try:
            with open(self.path) as f:
                blob = json.load(f)
            if (isinstance(blob, dict) and
                    blob.get("version") == CACHE_VERSION and
                    isinstance(blob.get("entries"), dict)):
                return dict(blob["entries"])
            raise ValueError("schema mismatch")
        except FileNotFoundError:
            return {}
        except Exception as e:  # corrupt/stale cache == empty cache
            if self.path not in _warned_corrupt:
                _warned_corrupt.add(self.path)
                import logging
                logging.getLogger("autotune").warning(
                    "ignoring unreadable autotune cache %s (%s: %s)",
                    self.path, type(e).__name__, e)
            return {}

    def _ensure(self):
        """Lazy first load. Caller holds ``self._lock``."""
        if self._entries is None:
            self._entries = self._read_disk()
        return self._entries

    def get(self, key):
        with self._lock:
            return self._ensure().get(key)

    def put(self, key, entry):
        with self._lock:
            # merge-then-write: pick up winners other processes
            # persisted since our load, keep ours for the key we own
            merged = self._read_disk()
            self._ensure().update(
                {k: v for k, v in merged.items()
                 if k not in self._entries})
            self._entries[key] = entry
            self._persist()

    def _persist(self):
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = "%s.%d.tmp" % (self.path, os.getpid())
            with open(tmp, "w") as f:
                json.dump({"version": CACHE_VERSION,
                           "device": device_kind(),
                           "entries": self._entries}, f, indent=1,
                          sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            pass  # the cache is an optimization, never a failure

    def __len__(self):
        with self._lock:
            return len(self._ensure())

    def items(self):
        with self._lock:
            return sorted(self._ensure().items())


def get_cache(path=None):
    path = path or cache_path()
    with _caches_lock:
        cache = _caches.get(path)
        if cache is None:
            cache = _caches[path] = AutotuneCache(path)
        return cache


def reset():
    """Drop in-memory cache singletons (tests; disk files survive)."""
    with _caches_lock:
        _caches.clear()
    _warned_corrupt.clear()
    _warmed.clear()


_warmed = set()


def warm():
    """Pull the persistent cache for this device into memory ahead of
    first trace — the per-device cache consultation
    :class:`veles_tpu.accelerated_units.AcceleratedUnit` performs at
    initialize, mirroring the reference's program-build/binary-cache
    discipline (``veles/backends.py``: load the device's tuned
    BLOCK_SIZE database before building kernels). One JSON read per
    cache file per process; returns the entry count (0 when off)."""
    if mode() == "off":
        return 0
    from veles_tpu.telemetry import profiler
    cache = get_cache()
    with profiler.phase("autotune_load"):
        n = len(cache)  # forces the lazy disk load
    if cache.path not in _warmed:
        _warmed.add(cache.path)
        import logging
        logging.getLogger("autotune").info(
            "autotune cache %s: %d tuned shapes (mode=%s)",
            cache.path, n, mode())
    return n


# -- measurement -------------------------------------------------------------

def _measure(fn, args, iters=None):
    """Steady-state seconds per call of ``fn(*args)``: ``iters``
    applications chained inside ONE jit by a scalar carry perturbing
    the first operand (defeats CSE) with a scalar forcing read — the
    remote-relay discipline from scripts/gemm_bench.py (per-call
    timing would measure the ~5 ms dispatch wire, not the kernel)."""
    import jax
    import jax.numpy as jnp

    if iters is None:
        iters = env_knob("VELES_AUTOTUNE_ITERS", 10, parse=int)

    def body(c, _):
        out = fn(args[0] + c.astype(args[0].dtype), *args[1:])
        return out.ravel()[0].astype(jnp.float32) * 1e-30, None

    chain = jax.jit(lambda: jax.lax.scan(
        body, jnp.float32(0), None, length=iters)[0])
    float(chain())  # compile + settle
    t0 = time.perf_counter()
    float(chain())
    return (time.perf_counter() - t0) / iters


def _rand(shape, dtype, seed=0):
    import jax.numpy as jnp
    arr = (numpy.random.RandomState(seed)
           .rand(*shape).astype(numpy.float32) - 0.5)
    return jnp.asarray(arr).astype(dtype)


# -- the plan/search core ----------------------------------------------------

def _key(op, **fields):
    return op + "|" + "|".join(
        "%s=%s" % (k, fields[k]) for k in sorted(fields))


def _plan(op, fields, candidates_fn, runner_fn, flops=None,
          shape_label=None):
    """Answer ``(impl, config)`` for one op instance.

    ``candidates_fn()`` -> ordered [(impl, config)] with the NATIVE
    baseline first; ``runner_fn(impl, config)`` -> (callable, args)
    measured by :func:`_measure`, or None to skip. Consults the cache
    first; searches only in ``search`` mode on a tunable backend.
    """
    m = mode()
    if m == "off":
        return _DEFAULT
    searches, hits, misses, best_gauge = _metrics()
    cache = get_cache()
    key = _key(op, **fields)
    entry = cache.get(key)
    if entry is not None:
        hits.inc()
        return entry["impl"], entry.get("config")
    if m != "search" or not tunable():
        misses.inc()
        return _DEFAULT
    if not _trace_state_clean():
        # Consulted from inside a jit trace (e.g. a unit's jitted
        # apply()): _measure would hit tracers and every candidate
        # would fail. Defer — an eager consult (gemm_bench --autotune,
        # profile_step --tune, or accelerated_units warm-load) tunes
        # the shape; persisting a failed search here would poison the
        # cache with a permanent "default" winner.
        misses.inc()
        return _DEFAULT
    with _search_lock:
        entry = cache.get(key)  # lost the race: someone else tuned it
        if entry is None:
            entry = _search(op, key, candidates_fn(), runner_fn,
                            flops, shape_label)
            if entry is None:  # nothing measured: don't persist
                misses.inc()
                return _DEFAULT
            cache.put(key, entry)
    return entry["impl"], entry.get("config")


def _search(op, key, candidates, runner_fn, flops, shape_label):
    searches, hits, misses, best_gauge = _metrics()
    searches.inc()
    budget = env_knob("VELES_AUTOTUNE_BUDGET_S", 20.0, parse=float)
    results = []
    with tracing.span("autotune:search", op=op, key=key):
        t0 = time.perf_counter()
        for impl, cfg in candidates:
            # the baseline is always measured; alternatives only
            # within the budget (compile time counts against it)
            if results and time.perf_counter() - t0 > budget:
                break
            made = runner_fn(impl, cfg)
            if made is None:
                continue
            fn, args = made
            try:
                results.append((impl, cfg, _measure(fn, args)))
            except Exception:
                continue  # unbuildable candidate (e.g. VMEM overflow)
    if not results:
        return None  # every candidate failed: not a tunable context
    # the baseline is candidates[0] by contract, but it may itself have
    # failed to build (e.g. a VMEM-hungry default block): only apply
    # the anti-flap win margin against a baseline that actually ran,
    # and never mislabel a surviving alternative as the baseline
    base_id = (candidates[0][0], candidates[0][1])
    base = next((r for r in results if (r[0], r[1]) == base_id), None)
    impl, cfg, best_s = min(results, key=lambda r: r[2])
    if base is not None:
        base_impl, base_cfg, base_s = base
        if (impl, cfg) != (base_impl, base_cfg) and \
                best_s > base_s * (1.0 - _WIN_MARGIN):
            impl, cfg, best_s = base_impl, base_cfg, base_s
    by_impl = {}
    for r_impl, _, r_s in results:
        by_impl[r_impl] = min(by_impl.get(r_impl, r_s), r_s)
    entry = {"impl": impl, "config": cfg,
             "baseline_impl": base[0] if base else None,
             "best_ms": round(best_s * 1e3, 4),
             "impl_ms": {k: round(v * 1e3, 4)
                         for k, v in sorted(by_impl.items())},
             "candidates": len(results)}
    if base is not None:
        entry["baseline_ms"] = round(base[2] * 1e3, 4)
    if flops:
        if base is not None:
            entry["baseline_tflops"] = round(flops / base[2] / 1e12, 3)
        entry["best_tflops"] = round(flops / best_s / 1e12, 3)
        # the winning candidate joins the cost book: tuned kernels get
        # the same roofline row as the compiled segments
        try:
            from veles_tpu.telemetry import profiler
            book = profiler.get_cost_book()
            label = "autotune:%s:%s" % (op, shape_label or "?")
            book.note_cost(label, flops, 0.0)
            book.observe_ms(label, best_s)
        except Exception:
            pass
        best_gauge.labels(op=op, shape=shape_label or "?").set(
            entry["best_tflops"])
    return entry


def summary():
    """Report blob for scripts: path, mode, entries, counters."""
    reg = get_registry()

    def _val(name):
        metric = reg.get(name)
        try:
            return metric.value if metric is not None else 0.0
        except ValueError:
            return 0.0
    cache = get_cache()
    return {"path": cache.path, "mode": mode(),
            "device": device_kind(), "entries": dict(cache.items()),
            "searches": _val("veles_autotune_searches_total"),
            "hits": _val("veles_autotune_cache_hits_total"),
            "misses": _val("veles_autotune_misses_total")}


# -- candidate spaces --------------------------------------------------------

#: scoped-VMEM budget for one grid step's working set (of ~16 MB/core;
#: leave headroom for pipelining's double buffers)
_VMEM_BUDGET = 10 * 1024 * 1024
_DS_OPTIONS = (("parallel", "parallel", "arbitrary"),
               ("arbitrary", "arbitrary", "arbitrary"))


def _block_divisors(dim, options, floor):
    """Candidate block sizes: divisors of ``dim`` from ``options``;
    if none divide, the dimension itself when it is small and aligned
    to ``floor`` (thin shapes run as one block)."""
    out = [b for b in options if b <= dim and dim % b == 0]
    if not out and dim <= max(options) and dim % floor == 0:
        out = [dim]
    return out


def _itemsize(dtype):
    try:
        return numpy.dtype(dtype).itemsize
    except TypeError:
        return 2 if "bfloat16" in str(dtype) else 4


def gemm_candidates(m, n, k, dtype, scratch=1):
    """(impl, config) grid for a tiled MXU GEMM, XLA baseline first.
    ``scratch`` = number of (bm, bn) f32 VMEM accumulators the kernel
    keeps (2 for the Kahan variant)."""
    isz = _itemsize(dtype)
    sub = 16 if isz == 2 else 8  # min sublane tile for the dtype
    cands = [("xla", None)]
    for bm in _block_divisors(m, (128, 256, 512), sub):
        for bn in _block_divisors(n, (128, 256, 512), 128):
            for bk in _block_divisors(k, (128, 256, 512, 1024, 2048),
                                      128):
                vmem = ((bm * bk + bk * bn) * isz +
                        bm * bn * 4 * (scratch + 1))
                if vmem > _VMEM_BUDGET:
                    continue
                for ds in _DS_OPTIONS:
                    cands.append(("pallas", {"bm": bm, "bn": bn,
                                             "bk": bk, "ds": list(ds)}))
    return cands


def ds_tuple(cfg, default=("parallel", "parallel", "arbitrary")):
    """Config-dict -> hashable dimension_semantics tuple."""
    return tuple(cfg.get("ds") or default) if cfg else default


# -- op plans ----------------------------------------------------------------

def _gemm_mod():
    """The :mod:`veles_tpu.ops.gemm` MODULE. ``from veles_tpu.ops
    import gemm`` yields the re-exported function (the package
    ``__init__`` shadows the submodule attribute), so resolve through
    ``sys.modules`` after a plain import."""
    import sys
    import veles_tpu.ops.gemm  # noqa: F401 -- ensures sys.modules entry
    return sys.modules["veles_tpu.ops.gemm"]

def gemm_plan(m, n, k, dtype, ta=False, tb=False, level=0):
    """Plan one GEMM: ('default'|'xla'|'pallas'|'loop'|'pairwise',
    config). Keyed the ISSUE way: (op, M, N, K, dtype, transpose
    flags, device kind) — device kind keys the cache FILE."""
    if mode() == "off":
        return _DEFAULT
    import jax.numpy as jnp
    gemm_mod = _gemm_mod()

    fields = dict(m=m, n=n, k=k, dtype=str(dtype),
                  ta=int(bool(ta)), tb=int(bool(tb)))
    flops = 2.0 * m * n * k
    label = "%dx%dx%d" % (m, n, k)
    interp = kernel_interpret()

    # ta/tb are part of the key AND of the measured workload: runtime
    # callers (e.g. fused_linear's backward) hand the dot a transposed
    # view, so candidates must be timed WITH the in-graph transpose —
    # operands stay stored in the pre-transpose layout and the op
    # itself does the .T, exactly as at the call site.
    def operands(seed_b=1):
        a = _rand((k, m) if ta else (m, k), dtype)
        b = _rand((n, k) if tb else (k, n), dtype, seed=seed_b)
        return a, b

    def opa(a):
        return a.T if ta else a

    def opb(b):
        return b.T if tb else b

    if level <= 0:
        def run(impl, cfg):
            a, b = operands()
            if impl == "xla":
                return (lambda a, b: jnp.dot(
                    opa(a), opb(b),
                    preferred_element_type=jnp.float32)), (a, b)
            return (lambda a, b: gemm_mod.pallas_gemm(
                opa(a), opb(b), bm=cfg["bm"], bn=cfg["bn"],
                bk=cfg["bk"], out_dtype=jnp.float32,
                dimension_semantics=ds_tuple(cfg),
                interpret=interp)), (a, b)
        return _plan("gemm", fields,
                     lambda: gemm_candidates(m, n, k, dtype),
                     run, flops, label)

    if level == 1:
        def kahan_cands():
            cands = [("loop", {"chunk": None})]
            cands += [("loop", {"chunk": c})
                      for c in (256, 1024) if c < k]
            cands += [c for c in gemm_candidates(m, n, k, dtype,
                                                 scratch=2)
                      if c[0] == "pallas"]
            return cands

        def run(impl, cfg):
            a, b = operands()
            if impl == "loop":
                return (lambda a, b: gemm_mod._kahan_matmul_loop(
                    opa(a), opb(b), chunk=cfg.get("chunk"))), (a, b)
            return (lambda a, b: gemm_mod.pallas_kahan_gemm(
                opa(a), opb(b), bm=cfg["bm"], bn=cfg["bn"],
                bk=cfg["bk"], dimension_semantics=ds_tuple(cfg),
                interpret=interp)), (a, b)
        return _plan("gemm_kahan", fields, kahan_cands, run, flops,
                     label)

    # level 2: pairwise split-K — tune the partial count
    def pairwise_cands():
        cands, p = [("pairwise", {"parts": None})], 2
        while p < k and len(cands) < 8:
            if k % p == 0:
                cands.append(("pairwise", {"parts": p}))
            p *= 2
        return cands

    def run(impl, cfg):
        a, b = operands()
        return (lambda a, b: gemm_mod.pairwise_matmul(
            opa(a), opb(b), parts=cfg.get("parts"))), (a, b)
    return _plan("gemm_pairwise", fields, pairwise_cands, run, flops,
                 label)


def linear_plan(m, n, k, dtype, activation, out_dtype):
    """Plan the fused All2All forward: GEMM with a bias+activation
    epilogue absorbed into the kernel's output step vs the XLA
    dot -> add -> activation chain."""
    if mode() == "off":
        return _DEFAULT
    import jax.numpy as jnp
    gemm_mod = _gemm_mod()

    fields = dict(m=m, n=n, k=k, dtype=str(dtype), act=str(activation),
                  out=str(out_dtype))
    interp = kernel_interpret()

    def run(impl, cfg):
        x = _rand((m, k), dtype)
        w = _rand((k, n), dtype, seed=1)
        b = _rand((n,), jnp.float32, seed=2)
        if impl == "xla":
            act = gemm_mod.epilogue_fn(activation)
            return (lambda x, w, b: act(jnp.dot(
                x, w, preferred_element_type=jnp.float32) + b)
                .astype(out_dtype)), (x, w, b)
        return (lambda x, w, b: gemm_mod.pallas_gemm(
            x, w, bias=b, activation=activation,
            bm=cfg["bm"], bn=cfg["bn"], bk=cfg["bk"],
            out_dtype=out_dtype, dimension_semantics=ds_tuple(cfg),
            interpret=interp)), (x, w, b)
    return _plan("linear", fields,
                 lambda: gemm_candidates(m, n, k, dtype),
                 run, 2.0 * m * n * k, "%dx%dx%d" % (m, n, k))


def lrn_plan(rows, channels, dtype, which="fwd"):
    """Tune the fused-LRN kernels' row-block size (the one free
    parameter: the channel window never crosses rows, so any row
    tiling is halo-free)."""
    if mode() == "off":
        return _DEFAULT
    from veles_tpu.ops import lrn as lrn_mod

    fields = dict(rows=rows, c=channels, dtype=str(dtype), which=which)
    isz = _itemsize(dtype)

    def cands():
        out = [("pallas", {"block_rows": lrn_mod._BLOCK_ROWS})]
        for br in (128, 256, 1024, 2048):
            if br == lrn_mod._BLOCK_ROWS or br > rows:
                continue
            # fwd keeps ~4 (br, C) f32 temporaries live, bwd ~6
            live = 4 if which == "fwd" else 6
            if br * channels * (4 * live + isz) > _VMEM_BUDGET:
                continue
            out.append(("pallas", {"block_rows": br}))
        return out

    def run(impl, cfg):
        x = _rand((rows, channels), dtype)
        g = _rand((rows, channels), dtype, seed=1)
        interp = kernel_interpret()
        if which == "fwd":
            return (lambda x: lrn_mod._call_fwd(
                x, 2.0, 1e-4, 0.75, 5, interp,
                block_rows=cfg["block_rows"])), (x,)
        return (lambda x, g: lrn_mod._call_bwd(
            x, g, 2.0, 1e-4, 0.75, 5, interp,
            block_rows=cfg["block_rows"])), (x, g)
    return _plan("lrn_" + which, fields, cands, run,
                 shape_label="%dx%d" % (rows, channels))


def reduce_plan(m, n, dtype):
    """Tune the Pallas column reduction's row-block size vs XLA sum."""
    if mode() == "off":
        return _DEFAULT
    import jax.numpy as jnp
    from veles_tpu.ops import reduce as reduce_mod

    fields = dict(m=m, n=n, dtype=str(dtype))

    def cands():
        out = [("xla", None)]
        out += [("pallas", {"block_rows": br})
                for br in (128, 256, 512, 1024)
                if br <= m and m % br == 0]
        return out

    def run(impl, cfg):
        x = _rand((m, n), dtype)
        if impl == "xla":
            return (lambda x: jnp.sum(
                x.astype(jnp.float32), axis=0)), (x,)
        return (lambda x: reduce_mod.pallas_column_reduce(
            x, block_rows=cfg["block_rows"],
            interpret=kernel_interpret())), (x,)
    return _plan("col_reduce", fields, cands, run,
                 shape_label="%dx%d" % (m, n))
