"""On-device minibatch gather (``ocl/fullbatch_loader.cl``,
``cuda/fullbatch_loader.cu:10-37``).

The reference keeps the whole dataset in device memory and gathers each
minibatch by index with a kernel. Here the dataset is an HBM-resident
``jax.Array`` and the gather is a jitted ``take`` — XLA emits a fused
dynamic-gather; under the step compiler it fuses straight into the
forward matmul's input so the minibatch never materializes in HBM.

Padding contract: indices < 0 mark padded slots (short tail batches);
their rows are zero-filled and their labels set to ``pad_label``, which
matches the reference's minibatch_offset tail handling
(``veles/loader/base.py:880``).
"""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("pad_label",))
def gather_minibatch(data, indices, labels=None, pad_label=-1):
    """Gather rows of ``data`` (and ``labels``) by ``indices``.

    Returns (minibatch_data, minibatch_labels|None). Negative indices
    produce zero rows / ``pad_label`` labels.
    """
    valid = indices >= 0
    safe = jnp.where(valid, indices, 0)
    mb = jnp.take(data, safe, axis=0)
    mask_shape = (indices.shape[0],) + (1,) * (data.ndim - 1)
    mb = mb * valid.reshape(mask_shape).astype(mb.dtype)
    if labels is None:
        return mb, None
    lbl = jnp.take(labels, safe, axis=0)
    lbl = jnp.where(
        valid.reshape((indices.shape[0],) + (1,) * (labels.ndim - 1)),
        lbl, pad_label)
    return mb, lbl
