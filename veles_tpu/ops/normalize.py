"""Mean/dispersion normalization (``ocl/mean_disp_normalizer.cl``,
``cuda/mean_disp_normalizer.cu``): out = (x - mean) * rdisp, broadcast
over the sample axis. One fused VPU pass; XLA fuses it into neighbors.
"""

import jax
import jax.numpy as jnp


@jax.jit
def mean_disp_normalize(x, mean, rdisp):
    """(x - mean) * rdisp with mean/rdisp broadcast over axis 0."""
    x32 = x.astype(jnp.float32)
    return (x32 - mean.astype(jnp.float32)) * rdisp.astype(jnp.float32)


@jax.jit
def compute_mean_disp(data):
    """Host-free analysis pass: per-feature mean and reciprocal spread.

    The reference computes mean and dispersion = (max - min) per feature
    during loader analysis; rdisp = 1/dispersion (guarded).
    """
    data32 = data.astype(jnp.float32)
    mean = jnp.mean(data32, axis=0)
    spread = jnp.max(data32, axis=0) - jnp.min(data32, axis=0)
    rdisp = jnp.where(spread > 0, 1.0 / jnp.maximum(spread, 1e-12), 1.0)
    return mean, rdisp
