"""Device random number generation.

The reference fills buffers with xorshift1024* on the GPU
(``ocl/random.cl:1-125``, ``cuda/random.cu:46-73``) seeded from the host
RandomGenerator. Here:

* :func:`xorshift128plus` — exact host implementation of the xorshift128+
  step the reference exposes (``veles/prng/random_generator.py:273``),
  used for state-evolution parity tests;
* :func:`uniform` — counter-based ``jax.random`` fill (the idiomatic TPU
  path: stateless, splittable, reproducible across meshes);
* :func:`pallas_uniform` — hardware PRNG fill inside a Pallas kernel
  (``pltpu.prng_random_bits``), for fusing randomness into larger
  kernels (dropout masks) without a second HBM pass.
"""

import functools

import jax
import jax.numpy as jnp
import numpy

_U64 = (1 << 64) - 1


def xorshift128plus(state):
    """One xorshift128+ step on a 2-element uint64 state (host-side).

    Returns (new_state, output). Bit-exact with the reference's
    generator so stream parity can be asserted in tests.
    """
    s0, s1 = int(state[0]), int(state[1])
    x = s0
    y = s1
    x ^= (x << 23) & _U64
    x ^= x >> 17
    x ^= y ^ (y >> 26)
    new = numpy.array([y, x], dtype=numpy.uint64)
    return new, (x + y) & _U64


def fill_xorshift(state, count):
    """Fill ``count`` uint64s, evolving the 2-word state (host loop)."""
    out = numpy.empty(count, dtype=numpy.uint64)
    for i in range(count):
        state, value = xorshift128plus(state)
        out[i] = value
    return state, out


@functools.partial(jax.jit, static_argnames=("shape", "dtype"))
def uniform(key, shape, vmin=0.0, vmax=1.0, dtype=jnp.float32):
    """Uniform fill via JAX's counter-based PRNG."""
    return jax.random.uniform(key, shape, dtype=dtype, minval=vmin,
                              maxval=vmax)


@functools.partial(jax.jit, static_argnames=("shape", "dtype"))
def normal(key, shape, mean=0.0, stddev=1.0, dtype=jnp.float32):
    return mean + stddev * jax.random.normal(key, shape, dtype=dtype)


def pallas_uniform(seed, shape, vmin=0.0, vmax=1.0):
    """Uniform fill with the TPU hardware PRNG inside a Pallas kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if len(shape) != 2:
        raise ValueError("pallas_uniform wants a 2-D shape")

    def kernel(seed_ref, o_ref):
        pltpu.prng_seed(seed_ref[0])
        bits = pltpu.bitcast(pltpu.prng_random_bits(o_ref.shape),
                             jnp.uint32)
        # map uint32 bits to [vmin, vmax): keep 24 mantissa-safe bits.
        # Mosaic can't cast uint32->f32; after >>8 the top byte is zero,
        # so a bitcast to int32 is value-preserving and casts cleanly.
        u24 = pltpu.bitcast(bits >> 8, jnp.int32)
        u01 = u24.astype(jnp.float32) * (1.0 / (1 << 24))
        o_ref[...] = vmin + (vmax - vmin) * u01

    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
    )(jnp.asarray([seed], dtype=jnp.int32))
