"""Fused Pallas LRN forward + backward (VERDICT r2 item #1).

AlexNet's cross-channel LRN (``veles/znicz reference: normalization``)
is the one hot op XLA handles worst on TPU: the padded-square window
sums of the forward AND of its vjp are materialized to HBM, and the
activations they touch (55^2x96 / 27^2x256 per sample) make LRN ~31%
of the f32 AlexNet step (docs/PERF.md). This module owns the op the
way the reference owned its OpenCL kernels
(``veles/accelerated_units.py:298-309``):

* **forward**: one Pallas kernel — read x, write y, window sums live
  in VMEM (circular lane rolls + boundary masks, never HBM);
* **backward**: one Pallas kernel via ``jax.custom_vjp`` whose only
  residual is ``x`` itself — the denominator is *recomputed in VMEM*
  (recompute-in-backward), so the traffic is the floor: read x and g,
  write dx, one pass;
* beta = 3/4 (the AlexNet constant) uses an rsqrt chain
  (``d^-3/4 = rsqrt(d)^2 * rsqrt(rsqrt(d))``) instead of exp/log —
  in-kernel this is pure VPU work, unlike the XLA-level rsqrt
  decomposition which spilled passes (docs/PERF.md:48-50).

The math:  y_c = x_c * d_c^-beta,  d_c = k + alpha * W(x^2)_c  with
W the n-wide channel window. The vjp needs ONE more window sum:
dx = g * d^-beta - 2*alpha*beta * x * W(g * x * d^(-beta-1)).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _band(channels, n):
    """(C, C) 0/1 band: entry (i, j) = |i - j| <= n // 2."""
    row = jax.lax.broadcasted_iota(jnp.int32, (channels, channels), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (channels, channels), 1)
    return (jnp.abs(row - col) <= n // 2).astype(jnp.float32)


def _window_sum(v, n):
    """Sliding window sum along the last (lane) axis, width ``n``
    centered — as a BANDED MATMUL on the otherwise-idle MXU.

    Cross-lane rolls are VPU shuffles that dominated the kernel
    (measured: roll+mask lost to XLA at C=96); ``v @ band`` moves the
    same reduction to the systolic array where it is noise-level FLOPs,
    and the band's zero corners give the boundary masking for free.
    HIGHEST precision keeps the f32 window sums exact (the MXU's
    default f32 path rounds through bf16 passes)."""
    return jnp.dot(v, _band(v.shape[-1], n),
                   precision=jax.lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32)


def _neg_pow(d, beta):
    """d^-beta on the VPU: rsqrt chain for the AlexNet beta=3/4."""
    if abs(beta - 0.75) < 1e-12:
        s = jax.lax.rsqrt(d)        # d^-1/2
        return s * s * jax.lax.rsqrt(s)   # d^-1 * d^1/4 = d^-3/4
    return jnp.exp(-beta * jnp.log(d))


def _fwd_kernel(x_ref, y_ref, *, k, alpha, beta, n):
    x = x_ref[...].astype(jnp.float32)
    d = k + alpha * _window_sum(x * x, n)
    y_ref[...] = (x * _neg_pow(d, beta)).astype(y_ref.dtype)


def _bwd_kernel(x_ref, g_ref, dx_ref, *, k, alpha, beta, n):
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    d = k + alpha * _window_sum(x * x, n)   # recompute: VMEM, not HBM
    q = _neg_pow(d, beta)                   # d^-beta
    u = _window_sum(g * x * (q / d), n)     # W(g x d^(-beta-1))
    dx_ref[...] = (g * q - (2.0 * alpha * beta) * x * u).astype(
        dx_ref.dtype)


#: rows per grid step, the untuned default. The window never crosses
#: rows (channels-only), so ANY row tiling is halo-free; 512 rows keep
#: the kernel's f32 working set well under the 16 MB scoped-VMEM budget
#: even at C=256 (a per-sample 55x55x96 block + temporaries blew it).
#: The autotuner (:mod:`veles_tpu.ops.autotune`, op ``lrn_fwd``/
#: ``lrn_bwd``) searches alternatives per (rows, C, dtype) and its
#: cached winner overrides this constant at dispatch.
_BLOCK_ROWS = 512


def _row_view(x):
    """(..., C) -> (R, C): layout-preserving, XLA folds it away."""
    return x.reshape(-1, x.shape[-1])


def _row_spec(channels, block_rows):
    return pl.BlockSpec((block_rows, channels), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _tuned_block_rows(rows, channels, dtype, which, block_rows):
    if block_rows is not None:
        return block_rows
    from veles_tpu.ops import autotune
    impl, cfg = autotune.lrn_plan(rows, channels, str(dtype), which)
    if impl == "pallas" and cfg:
        return int(cfg["block_rows"])
    return _BLOCK_ROWS


def _call_fwd(x, k, alpha, beta, n, interpret, block_rows=None):
    rows = _row_view(x)
    block_rows = _tuned_block_rows(rows.shape[0], rows.shape[-1],
                                   x.dtype, "fwd", block_rows)
    spec = _row_spec(rows.shape[-1], block_rows)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, k=k, alpha=alpha, beta=beta, n=n),
        out_shape=jax.ShapeDtypeStruct(rows.shape, x.dtype),
        grid=(pl.cdiv(rows.shape[0], block_rows),),
        in_specs=[spec], out_specs=spec,
        interpret=interpret,
    )(rows)
    return out.reshape(x.shape)


def _call_bwd(x, g, k, alpha, beta, n, interpret, block_rows=None):
    rows, grows = _row_view(x), _row_view(g)
    block_rows = _tuned_block_rows(rows.shape[0], rows.shape[-1],
                                   x.dtype, "bwd", block_rows)
    spec = _row_spec(rows.shape[-1], block_rows)
    out = pl.pallas_call(
        functools.partial(_bwd_kernel, k=k, alpha=alpha, beta=beta, n=n),
        out_shape=jax.ShapeDtypeStruct(rows.shape, x.dtype),
        grid=(pl.cdiv(rows.shape[0], block_rows),),
        in_specs=[spec, spec], out_specs=spec,
        interpret=interpret,
    )(rows, grows)
    return out.reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_fused(x, k=2.0, alpha=1e-4, beta=0.75, n=5, interpret=False):
    """Fused-LRN entry point: NHWC (or any layout with channels last,
    rank >= 2, batch leading). ``n`` must be odd: the kernel's window
    is symmetric (and the backward's self-adjoint-window identity
    relies on that) — even ``n`` takes the XLA slices path."""
    if n % 2 == 0:
        raise ValueError("lrn_fused requires an odd window (n=%d)" % n)
    return _call_fwd(x, k, alpha, beta, n, interpret)


def _fwd_rule(x, k, alpha, beta, n, interpret):
    # residual is x ALONE — the whole point: the denominator is
    # recomputed in VMEM by the backward kernel instead of being
    # saved to (and re-read from) HBM
    return _call_fwd(x, k, alpha, beta, n, interpret), x


def _bwd_rule(k, alpha, beta, n, interpret, x, g):
    return (_call_bwd(x, g, k, alpha, beta, n, interpret),)


lrn_fused.defvjp(_fwd_rule, _bwd_rule)
