"""Device benchmark (``ocl/benchmark.cl`` + ``veles/accelerated_units.py:
706-824``): repeated square GEMM timing. Produces the ``computing_power``
rating (1000/dt of a 1500² gemm in the reference) that masters use for
slave load balancing; also reports achieved TFLOP/s for bench.py.
"""

import time

import jax
import jax.numpy as jnp


def gemm_benchmark(size=1500, repeats=5, dtype=jnp.bfloat16, device=None):
    """Time ``repeats`` chained size×size matmuls; returns a dict."""
    key = jax.random.PRNGKey(0)
    kwargs = {}
    if device is not None and getattr(device, "is_jax", False):
        kwargs["device"] = device.jax_device
    a = jax.device_put(jax.random.normal(key, (size, size), jnp.float32)
                       .astype(dtype), **kwargs)
    b = jax.device_put(jax.random.normal(key, (size, size), jnp.float32)
                       .astype(dtype), **kwargs)

    @jax.jit
    def chain(a, b):
        def body(i, x):
            return jnp.dot(x, b, preferred_element_type=jnp.float32).astype(
                a.dtype)
        return jax.lax.fori_loop(0, repeats, body, a)

    chain(a, b).block_until_ready()  # compile
    start = time.perf_counter()
    chain(a, b).block_until_ready()
    dt = time.perf_counter() - start
    flops = 2.0 * size ** 3 * repeats
    return {
        "seconds": dt,
        "computing_power": 1000.0 * repeats / dt,
        "tflops": flops / dt / 1e12,
        "size": size,
        "dtype": str(dtype.__name__ if hasattr(dtype, "__name__")
                     else dtype),
    }
