"""TPU-native compute ops — the re-implementation of the reference's
kernel set (``ocl/*.cl`` + ``cuda/*.cu``, SURVEY.md §2.2) on XLA/Pallas.

==========================  ===============================================
reference kernel            this package
==========================  ===============================================
matrix_multiplication*.cl   :mod:`veles_tpu.ops.gemm` (MXU dot + Pallas
/ gemm via CUBLAS           tiled kernel; PRECISION_LEVEL 0/1/2)
matrix_reduce.{cl,cu}       :mod:`veles_tpu.ops.reduce`
random.{cl,cu}              :mod:`veles_tpu.ops.random` (xorshift128+ host
(xorshift1024*)             parity + Pallas hardware PRNG fill)
fullbatch_loader.{cl,cu}    :mod:`veles_tpu.ops.gather`
mean_disp_normalizer.*      :mod:`veles_tpu.ops.normalize`
join.jcl/.jcu               :mod:`veles_tpu.ops.join`
benchmark.cl                :mod:`veles_tpu.ops.benchmark`
==========================  ===============================================
"""

from veles_tpu.ops.gemm import gemm  # noqa: F401
from veles_tpu.ops.reduce import matrix_reduce  # noqa: F401
from veles_tpu.ops.gather import gather_minibatch  # noqa: F401
from veles_tpu.ops.normalize import mean_disp_normalize  # noqa: F401
from veles_tpu.ops.join import join_arrays  # noqa: F401
