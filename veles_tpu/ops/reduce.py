"""Matrix reductions (``ocl/matrix_reduce.cl``, ``cuda/matrix_reduce.cu``).

The reference runs a two-stage tree reduction over matrix columns on the
GPU. On TPU, XLA lowers ``jnp.sum``/``jnp.max`` onto the VPU with its own
tree schedule, so the *public contract* (reduce a matrix along an axis
with a selectable op) is all that must survive; a Pallas grid version is
provided for fusing reductions into larger kernels when needed.
"""

import functools

import jax
import jax.numpy as jnp

_OPS = {
    "sum": jnp.sum,
    "max": jnp.max,
    "min": jnp.min,
    "mean": jnp.mean,
    "argmax": jnp.argmax,
    "l2": lambda x, axis: jnp.sqrt(jnp.sum(jnp.square(x), axis=axis)),
}


@functools.partial(jax.jit, static_argnames=("op", "axis"))
def matrix_reduce(x, op="sum", axis=0):
    """Reduce a matrix along ``axis`` with ``op`` (fp32 accumulation)."""
    fn = _OPS[op]
    if op in ("argmax",):
        return fn(x, axis=axis)
    return fn(x.astype(jnp.float32), axis=axis)


def pallas_column_reduce(x, block_rows=None, interpret=False):
    """Column-sum via a Pallas grid walking row blocks with a VMEM
    accumulator — the shape of the reference's two-stage kernel.

    ``block_rows=None`` consults the autotuner (op ``col_reduce``):
    the cached winner may be a tuned block size or XLA's own sum;
    untuned, the legacy 512-row default applies."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, n = x.shape
    if block_rows is None:
        from veles_tpu.ops import autotune
        impl, cfg = autotune.reduce_plan(m, n, str(x.dtype))
        if impl == "xla":
            return jnp.sum(x.astype(jnp.float32), axis=0)
        if impl == "pallas" and cfg:
            block_rows = int(cfg["block_rows"])
            interpret = interpret or autotune.kernel_interpret()
    if block_rows is None:
        block_rows = 512
    block_rows = min(block_rows, m)
    if m % block_rows or not (jax.default_backend() == "tpu" or
                              interpret):
        return jnp.sum(x.astype(jnp.float32), axis=0)
    steps = m // block_rows

    def kernel(x_ref, o_ref, acc_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc_ref[...] += jnp.sum(x_ref[...].astype(jnp.float32), axis=0,
                                keepdims=True)

        @pl.when(pl.program_id(0) == steps - 1)
        def _():
            o_ref[...] = acc_ref[...]

    out = pl.pallas_call(
        kernel,
        grid=(steps,),
        in_specs=[pl.BlockSpec((block_rows, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, n), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, n), jnp.float32)],
        interpret=interpret,
    )(x)
    return out[0]
