"""Input joining (``ocl/join.jcl``, ``cuda/join.jcu``): concatenate
several arrays along the feature axis, flattening trailing dims. The
reference jinja-templates a copy kernel per input list; XLA's concatenate
does the same packing without a bespoke kernel.
"""

import jax
import jax.numpy as jnp


@jax.jit
def join_arrays(*arrays):
    """Concat along axis 1, flattening each input to (batch, -1)."""
    if not arrays:
        raise ValueError("nothing to join")
    batch = arrays[0].shape[0]
    flat = [a.reshape(batch, -1) for a in arrays]
    return jnp.concatenate(flat, axis=1)
