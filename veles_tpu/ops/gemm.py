"""GEMM with selectable accumulation discipline.

Re-provides the reference's matrix-multiplication kernel family
(``ocl/matrix_multiplication_begin.cl`` / ``_subsum.cl`` / ``_end.cl`` /
``_precise.cl``; CUBLAS on the CUDA backend) the TPU way:

* ``precision_level=0`` — plain MXU matmul with fp32 accumulation
  (``preferred_element_type``): the fast path. On TPU this is already
  stronger than the reference's level 0 (fp32 multiply-add chain)
  because the MXU accumulates in fp32 regardless of bf16 inputs.
* ``precision_level=1`` — Kahan-compensated accumulation over K-chunks
  (the reference's ``PRECISION_LEVEL 1`` summation, ``_subsum.cl``).
* ``precision_level=2`` — multi-partial pairwise summation: K is split
  into partials that are reduced pairwise (``PRECISION_LEVEL 2``).

Levels 1/2 exist for numerical-parity experiments; level 0 is what
training uses. Measured against XLA's native dot on one v5e chip
(scripts/gemm_bench.py, chained steady-state): the hand-tiled Pallas
kernels match or beat XLA on latency/bandwidth-bound shapes (AlexNet
fc6 wgrad 2.5 vs 1.5 TF/s; 1500² parity) but XLA's tiling wins ~2× on
large compute-bound squares (4096³: 40 vs 18 TF/s) — so level 0 stays
on XLA dot, and the Pallas kernels' real value is
``pallas_kahan_gemm``: compensated accumulation at ≈ the plain Pallas
kernel's speed (18.7 vs 18.4 TF/s), where the reference's
``PRECISION_LEVEL 1`` traded GEMM throughput for it.
"""

import functools

import jax
import jax.numpy as jnp


def _on_tpu():
    return jax.default_backend() == "tpu"


def gemm(a, b, transpose_a=False, transpose_b=False, alpha=1.0, beta=0.0,
         c=None, precision_level=0, out_dtype=None):
    """cuBLAS-like gemm: ``alpha * op(a) @ op(b) + beta * c``."""
    if transpose_a:
        a = a.T
    if transpose_b:
        b = b.T
    out_dtype = out_dtype or jnp.result_type(a.dtype, b.dtype)
    if precision_level <= 0:
        out = jnp.dot(a, b, preferred_element_type=jnp.float32)
    elif precision_level == 1:
        # on TPU with tileable shapes the Kahan carrier is the Pallas
        # kernel (compensation lives in VMEM next to the accumulator);
        # the fori_loop fallback covers CPU and ragged shapes
        out = kahan_matmul(a, b)
    else:
        out = pairwise_matmul(a, b)
    out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out.astype(out_dtype)


def pairwise_matmul(a, b, parts=None):
    """PRECISION_LEVEL 2: split-K partial sums reduced pairwise."""
    k = a.shape[-1]
    if parts is None:
        parts = 1
        while parts * parts < k:
            parts *= 2
        parts = min(parts, k)
    while k % parts:
        parts //= 2
    kc = k // parts
    ap = a.reshape(a.shape[:-1] + (parts, kc))
    bp = b.reshape((parts, kc) + b.shape[1:])
    # partials[p] = a[:, p-chunk] @ b[p-chunk, :] with fp32 accumulation
    partials = jnp.einsum("mpk,pkn->pmn", ap, bp,
                          preferred_element_type=jnp.float32)
    # pairwise tree reduction of the partials
    while partials.shape[0] > 1:
        n = partials.shape[0]
        if n % 2:
            partials = jnp.concatenate(
                [partials[:-2], (partials[-2] + partials[-1])[None]], axis=0)
        else:
            partials = partials[0::2] + partials[1::2]
    return partials[0]


def kahan_matmul(a, b, chunk=None):
    """PRECISION_LEVEL 1: Kahan-compensated accumulation over K chunks.

    Dispatches to :func:`pallas_kahan_gemm` on TPU when the shapes
    tile (the compensated accumulator never leaves VMEM); otherwise an
    XLA ``fori_loop`` of chunked dots carries the compensation."""
    if _on_tpu() and chunk is None and _tileable(a, b):
        return pallas_kahan_gemm(a, b)
    return _kahan_matmul_loop(a, b, chunk)


def _kahan_matmul_loop(a, b, chunk=None):
    m, k = a.shape
    n = b.shape[1]
    if chunk is None:
        chunk = max(1, min(512, k))
    if k % chunk:
        # zero-pad K to a multiple: zeros add nothing to the sums and
        # keep the loop count at ceil(k/chunk) even for prime K
        pad = chunk - k % chunk
        a = jnp.pad(a, ((0, 0), (0, pad)))
        b = jnp.pad(b, ((0, pad), (0, 0)))
        k += pad
    steps = k // chunk
    a32 = a.astype(jnp.float32)
    b32 = b.astype(jnp.float32)

    def body(i, carry):
        acc, comp = carry
        ak = jax.lax.dynamic_slice(a32, (0, i * chunk), (m, chunk))
        bk = jax.lax.dynamic_slice(b32, (i * chunk, 0), (chunk, n))
        term = jnp.dot(ak, bk, preferred_element_type=jnp.float32)
        # Kahan: y = term - comp; t = acc + y; comp = (t - acc) - y
        y = term - comp
        t = acc + y
        comp = (t - acc) - y
        return t, comp

    acc = jnp.zeros((m, n), jnp.float32)
    comp = jnp.zeros((m, n), jnp.float32)
    acc, _ = jax.lax.fori_loop(0, steps, body, (acc, comp))
    return acc


# ---------------------------------------------------------------------------
# Pallas tiled GEMM (TPU): MXU-tiled with fp32 VMEM accumulator.
# ---------------------------------------------------------------------------

#: default tile sizes for the Pallas kernels
_BM, _BN, _BK = 256, 256, 512


def _tileable(a, b, bm=_BM, bn=_BN, bk=_BK):
    m, k = a.shape
    n = b.shape[1]
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    return m % bm == 0 and n % bn == 0 and k % bk == 0


def _gemm_kernel(a_ref, b_ref, o_ref, acc_ref, *, k_steps):
    @jax.named_scope("init")
    def init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        init()

    acc_ref[...] += jnp.dot(a_ref[...], b_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _kahan_gemm_kernel(a_ref, b_ref, o_ref, acc_ref, comp_ref, *,
                       k_steps):
    """Tiled GEMM whose K-accumulation is Kahan-compensated IN VMEM —
    the fused realization of the reference's ``PRECISION_LEVEL 1``
    summation (``ocl/matrix_multiplication_subsum.cl``): each K-step's
    partial product joins the accumulator through the compensated
    add, and neither the accumulator nor the compensation ever round-
    trips to HBM."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        comp_ref[...] = jnp.zeros_like(comp_ref)

    term = jnp.dot(a_ref[...], b_ref[...],
                   preferred_element_type=jnp.float32)
    y = term - comp_ref[...]
    t = acc_ref[...] + y
    comp_ref[...] = (t - acc_ref[...]) - y
    acc_ref[...] = t

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk",
                                             "out_dtype"))
def pallas_kahan_gemm(a, b, bm=_BM, bn=_BN, bk=_BK, out_dtype=None):
    """Kahan-compensated tiled MXU matmul (precision_level=1 carrier)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    _, n = b.shape
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    if m % bm or n % bn or k % bk or not _on_tpu():
        return _kahan_matmul_loop(a, b)
    k_steps = k // bk
    out_dtype = out_dtype or jnp.float32
    return pl.pallas_call(
        functools.partial(_kahan_gemm_kernel, k_steps=k_steps),
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32),
                        pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n + m * n) * a.dtype.itemsize,
            transcendentals=0),
    )(a, b)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk", "out_dtype"))
def pallas_gemm(a, b, bm=256, bn=256, bk=512, out_dtype=None):
    """Hand-tiled MXU matmul; shapes must divide by the tile sizes.

    Competitive with XLA dot on thin/bandwidth-bound shapes, ~2×
    behind on large squares (see the module docstring's measurements)
    — kept as the uncompensated twin of :func:`pallas_kahan_gemm`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    _, n = b.shape
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    if m % bm or n % bn or k % bk or not _on_tpu():
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(
            out_dtype or a.dtype)
    k_steps = k // bk
    out_dtype = out_dtype or a.dtype
    return pl.pallas_call(
        functools.partial(_gemm_kernel, k_steps=k_steps),
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n + m * n) * a.dtype.itemsize,
            transcendentals=0),
    )(a, b)
