"""GEMM with selectable accumulation discipline.

Re-provides the reference's matrix-multiplication kernel family
(``ocl/matrix_multiplication_begin.cl`` / ``_subsum.cl`` / ``_end.cl`` /
``_precise.cl``; CUBLAS on the CUDA backend) the TPU way:

* ``precision_level=0`` — plain MXU matmul with fp32 accumulation
  (``preferred_element_type``): the fast path. On TPU this is already
  stronger than the reference's level 0 (fp32 multiply-add chain)
  because the MXU accumulates in fp32 regardless of bf16 inputs.
* ``precision_level=1`` — Kahan-compensated accumulation over K-chunks
  (the reference's ``PRECISION_LEVEL 1`` summation, ``_subsum.cl``).
* ``precision_level=2`` — multi-partial pairwise summation: K is split
  into partials that are reduced pairwise (``PRECISION_LEVEL 2``).

Dispatch between XLA dot and the Pallas kernels is SHAPE-AWARE via
:mod:`veles_tpu.ops.autotune`: the old static rule (level 0 always on
XLA dot, because the one fixed 256x256x512 tiling lost ~2x on large
compute-bound squares while beating XLA on bandwidth-bound shapes —
fc6 wgrad 2.5 vs 1.5 TF/s, 4096^3 18 vs 40 TF/s, docs/PERF.md) is now
the ``VELES_AUTOTUNE=off`` fallback; with the tuner on, each
``(M, N, K, dtype)`` picks whatever the per-device measurement cache
says wins, block config included. The Pallas kernels themselves are
parameterized over block sizes, ``dimension_semantics`` and an
optional fused bias+activation epilogue (:func:`fused_linear`) so the
All2All forward absorbs its elementwise tail into the GEMM's output
step instead of a separate HBM pass.
"""

import functools

import jax
import jax.numpy as jnp


def _on_tpu():
    return jax.default_backend() == "tpu"


# -- fused epilogues ---------------------------------------------------------
# Bit-for-bit twins of veles_tpu.nn.activation's family, duplicated
# here (a) to keep ops/ free of an nn/ dependency and (b) because the
# backward pass needs the FROM-Y derivative forms below. The parity is
# pinned by tests/test_autotune.py.

def _act_linear(x):
    return x


def _act_tanh(x):
    return 1.7159 * jnp.tanh(0.6666 * x)


def _act_sigmoid(x):
    return jax.nn.sigmoid(x)


def _act_relu_soft(x):
    return jnp.where(x > 15.0, x, jnp.log1p(jnp.exp(jnp.minimum(x, 15.0))))


def _act_relu_strict(x):
    return jnp.maximum(x, 0.0)


_EPILOGUES = {
    "linear": _act_linear,
    "tanh": _act_tanh,
    "sigmoid": _act_sigmoid,
    "relu": _act_relu_soft,
    "strict_relu": _act_relu_strict,
}

#: activation derivative AS A FUNCTION OF THE OUTPUT y — the property
#: that lets :func:`fused_linear`'s backward keep only (x, w, y) as
#: residuals (no pre-activation round-trips to HBM)
_EPILOGUE_GRADS = {
    "linear": lambda y: jnp.ones_like(y),
    "tanh": lambda y: 1.7159 * 0.6666 * (1.0 - jnp.square(y / 1.7159)),
    "sigmoid": lambda y: y * (1.0 - y),
    # y = log1p(e^x) => dy/dx = sigmoid(x) = 1 - e^-y (clamped region
    # y = x > 15 gives 1 - e^-y ~ 1, exact to f32)
    "relu": lambda y: 1.0 - jnp.exp(-y),
    "strict_relu": lambda y: (y > 0.0).astype(y.dtype),
}


def epilogue_fn(name):
    """The epilogue activation by name (fusable subset only)."""
    try:
        return _EPILOGUES[name]
    except KeyError:
        raise ValueError("no fused epilogue for activation %r (have %s)"
                         % (name, sorted(_EPILOGUES)))


def fusable_activation(name):
    return name in _EPILOGUES


# -- public gemm -------------------------------------------------------------

def gemm(a, b, transpose_a=False, transpose_b=False, alpha=1.0, beta=0.0,
         c=None, precision_level=0, out_dtype=None):
    """cuBLAS-like gemm: ``alpha * op(a) @ op(b) + beta * c``."""
    ta, tb = transpose_a, transpose_b
    if transpose_a:
        a = a.T
    if transpose_b:
        b = b.T
    out_dtype = out_dtype or jnp.result_type(a.dtype, b.dtype)
    if precision_level <= 0:
        out = _planned_dot(a, b, ta=ta, tb=tb)
    elif precision_level == 1:
        # on TPU with tileable shapes the Kahan carrier is the Pallas
        # kernel (compensation lives in VMEM next to the accumulator);
        # the fori_loop fallback covers CPU and ragged shapes
        out = kahan_matmul(a, b, ta=ta, tb=tb)
    else:
        out = pairwise_matmul(a, b, ta=ta, tb=tb)
    out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out.astype(out_dtype)


def _dtype_key(a, b):
    return str(jnp.result_type(a.dtype, b.dtype))


def _planned_dot(a, b, ta=False, tb=False):
    """Level-0 dispatch seam: the autotuner's winner for this shape,
    XLA dot otherwise (= today's static behavior)."""
    from veles_tpu.ops import autotune
    impl, cfg = autotune.gemm_plan(
        a.shape[0], b.shape[1], a.shape[1], _dtype_key(a, b),
        ta=ta, tb=tb, level=0)
    if impl == "pallas" and cfg:
        return pallas_gemm(
            a, b, bm=cfg["bm"], bn=cfg["bn"], bk=cfg["bk"],
            out_dtype=jnp.float32,
            dimension_semantics=autotune.ds_tuple(cfg),
            interpret=autotune.kernel_interpret())
    return jnp.dot(a, b, preferred_element_type=jnp.float32)


def pairwise_matmul(a, b, parts=None, ta=False, tb=False):
    """PRECISION_LEVEL 2: split-K partial sums reduced pairwise."""
    k = a.shape[-1]
    if parts is None:
        from veles_tpu.ops import autotune
        impl, cfg = autotune.gemm_plan(
            a.shape[0], b.shape[1], k, _dtype_key(a, b),
            ta=ta, tb=tb, level=2)
        if impl == "pairwise" and cfg:
            parts = cfg.get("parts")
    if parts is None:
        parts = 1
        while parts * parts < k:
            parts *= 2
        parts = min(parts, k)
    while k % parts:
        parts //= 2
    kc = k // parts
    ap = a.reshape(a.shape[:-1] + (parts, kc))
    bp = b.reshape((parts, kc) + b.shape[1:])
    # partials[p] = a[:, p-chunk] @ b[p-chunk, :] with fp32 accumulation
    partials = jnp.einsum("mpk,pkn->pmn", ap, bp,
                          preferred_element_type=jnp.float32)
    # pairwise tree reduction of the partials
    while partials.shape[0] > 1:
        n = partials.shape[0]
        if n % 2:
            partials = jnp.concatenate(
                [partials[:-2], (partials[-2] + partials[-1])[None]], axis=0)
        else:
            partials = partials[0::2] + partials[1::2]
    return partials[0]


def kahan_matmul(a, b, chunk=None, ta=False, tb=False):
    """PRECISION_LEVEL 1: Kahan-compensated accumulation over K chunks.

    Dispatch order: the autotuner's per-shape winner (Pallas config or
    loop chunk size); untuned, the legacy static rule — Pallas on TPU
    when the shapes tile, else an XLA ``fori_loop`` of chunked dots
    carrying the compensation."""
    if chunk is None:
        from veles_tpu.ops import autotune
        impl, cfg = autotune.gemm_plan(
            a.shape[0], b.shape[1], a.shape[1], _dtype_key(a, b),
            ta=ta, tb=tb, level=1)
        if impl == "pallas" and cfg:
            return pallas_kahan_gemm(
                a, b, bm=cfg["bm"], bn=cfg["bn"], bk=cfg["bk"],
                dimension_semantics=autotune.ds_tuple(cfg),
                interpret=autotune.kernel_interpret())
        if impl == "loop" and cfg:
            return _kahan_matmul_loop(a, b, cfg.get("chunk"))
        if _on_tpu() and _tileable(a, b):
            return pallas_kahan_gemm(a, b)
    return _kahan_matmul_loop(a, b, chunk)


def _kahan_matmul_loop(a, b, chunk=None):
    m, k = a.shape
    n = b.shape[1]
    if chunk is None:
        chunk = max(1, min(512, k))
    if k % chunk:
        # zero-pad K to a multiple: zeros add nothing to the sums and
        # keep the loop count at ceil(k/chunk) even for prime K
        pad = chunk - k % chunk
        a = jnp.pad(a, ((0, 0), (0, pad)))
        b = jnp.pad(b, ((0, pad), (0, 0)))
        k += pad
    steps = k // chunk
    a32 = a.astype(jnp.float32)
    b32 = b.astype(jnp.float32)

    def body(i, carry):
        acc, comp = carry
        ak = jax.lax.dynamic_slice(a32, (0, i * chunk), (m, chunk))
        bk = jax.lax.dynamic_slice(b32, (i * chunk, 0), (chunk, n))
        term = jnp.dot(ak, bk, preferred_element_type=jnp.float32)
        # Kahan: y = term - comp; t = acc + y; comp = (t - acc) - y
        y = term - comp
        t = acc + y
        comp = (t - acc) - y
        return t, comp

    acc = jnp.zeros((m, n), jnp.float32)
    comp = jnp.zeros((m, n), jnp.float32)
    acc, _ = jax.lax.fori_loop(0, steps, body, (acc, comp))
    return acc


# ---------------------------------------------------------------------------
# Pallas tiled GEMM (TPU): MXU-tiled with fp32 VMEM accumulator.
# ---------------------------------------------------------------------------

#: default tile sizes for the Pallas kernels (the untuned fallback —
#: the autotuner's candidate grid supersedes them per shape)
_BM, _BN, _BK = 256, 256, 512
_DS = ("parallel", "parallel", "arbitrary")


def _compiler_params(pltpu, dimension_semantics):
    """``pltpu.CompilerParams`` across JAX renames (older releases
    ship it as ``TPUCompilerParams``)."""
    cls = getattr(pltpu, "CompilerParams", None) or \
        pltpu.TPUCompilerParams
    return cls(dimension_semantics=tuple(dimension_semantics))


def _tileable(a, b, bm=_BM, bn=_BN, bk=_BK):
    m, k = a.shape
    n = b.shape[1]
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    return m % bm == 0 and n % bn == 0 and k % bk == 0


def _gemm_kernel(a_ref, b_ref, o_ref, acc_ref, *, k_steps,
                 activation="linear"):
    @jax.named_scope("init")
    def init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        init()

    acc_ref[...] += jnp.dot(a_ref[...], b_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        o_ref[...] = _EPILOGUES[activation](acc_ref[...]).astype(
            o_ref.dtype)


def _gemm_bias_kernel(a_ref, b_ref, bias_ref, o_ref, acc_ref, *,
                      k_steps, activation="linear"):
    """Tiled GEMM whose output step applies bias + activation while
    the block is still in VMEM — the All2All forward epilogue the
    profile wanted fused (the separate XLA add/act pass re-reads the
    whole (M, N) product from HBM)."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(a_ref[...], b_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        pre = acc_ref[...] + bias_ref[...].astype(jnp.float32)
        o_ref[...] = _EPILOGUES[activation](pre).astype(o_ref.dtype)


def _kahan_gemm_kernel(a_ref, b_ref, o_ref, acc_ref, comp_ref, *,
                       k_steps):
    """Tiled GEMM whose K-accumulation is Kahan-compensated IN VMEM —
    the fused realization of the reference's ``PRECISION_LEVEL 1``
    summation (``ocl/matrix_multiplication_subsum.cl``): each K-step's
    partial product joins the accumulator through the compensated
    add, and neither the accumulator nor the compensation ever round-
    trips to HBM."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        comp_ref[...] = jnp.zeros_like(comp_ref)

    term = jnp.dot(a_ref[...], b_ref[...],
                   preferred_element_type=jnp.float32)
    y = term - comp_ref[...]
    t = acc_ref[...] + y
    comp_ref[...] = (t - acc_ref[...]) - y
    acc_ref[...] = t

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "bm", "bn", "bk", "out_dtype", "dimension_semantics", "interpret"))
def pallas_kahan_gemm(a, b, bm=_BM, bn=_BN, bk=_BK, out_dtype=None,
                      dimension_semantics=_DS, interpret=False):
    """Kahan-compensated tiled MXU matmul (precision_level=1 carrier)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    _, n = b.shape
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    if m % bm or n % bn or k % bk or not (_on_tpu() or interpret):
        return _kahan_matmul_loop(a, b)
    k_steps = k // bk
    out_dtype = out_dtype or jnp.float32
    return pl.pallas_call(
        functools.partial(_kahan_gemm_kernel, k_steps=k_steps),
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32),
                        pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_compiler_params(pltpu, dimension_semantics),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n + m * n) * a.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(a, b)


@functools.partial(jax.jit, static_argnames=(
    "bm", "bn", "bk", "out_dtype", "activation", "dimension_semantics",
    "interpret"))
def pallas_gemm(a, b, bm=_BM, bn=_BN, bk=_BK, out_dtype=None, *,
                bias=None, activation="linear", dimension_semantics=_DS,
                interpret=False):
    """Hand-tiled MXU matmul with an optional fused bias+activation
    epilogue; shapes must divide by the tile sizes (the non-tiling
    and non-TPU fallback is the equivalent XLA chain).

    Block sizes and ``dimension_semantics`` are the autotuner's
    search axes (:mod:`veles_tpu.ops.autotune`); the module-level
    defaults are only the untuned fallback."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    _, n = b.shape
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    out_dtype = out_dtype or a.dtype
    if m % bm or n % bn or k % bk or not (_on_tpu() or interpret):
        out = jnp.dot(a, b, preferred_element_type=jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
        return _EPILOGUES[activation](out).astype(out_dtype)
    k_steps = k // bk
    common = dict(
        grid=(m // bm, n // bn, k_steps),
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_compiler_params(pltpu, dimension_semantics),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n + m * n) * a.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )
    ab_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
        pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
    ]
    if bias is None:
        return pl.pallas_call(
            functools.partial(_gemm_kernel, k_steps=k_steps,
                              activation=activation),
            in_specs=ab_specs, **common)(a, b)
    return pl.pallas_call(
        functools.partial(_gemm_bias_kernel, k_steps=k_steps,
                          activation=activation),
        in_specs=ab_specs + [
            pl.BlockSpec((1, bn), lambda i, j, s: (0, j))],
        **common)(a, b, bias.reshape(1, n))


# ---------------------------------------------------------------------------
# Fused linear layer: act(x @ w + b) with a VJP whose backward dots go
# back through the autotuned dispatch (the fc wgrad shapes are where
# the Pallas kernels historically won).
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_linear(x, w, b, activation, out_dtype, cfg):
    """``act(x @ w + b)`` through the fused-epilogue Pallas kernel.

    ``cfg`` is the hashable tuple ``(bm, bn, bk, dimension_semantics,
    interpret)`` the autotuner picked (see :func:`fused_linear_cfg`).
    Differentiable: the custom VJP keeps only (x, w, y) as residuals —
    every supported epilogue's derivative is a function of the OUTPUT
    (``_EPILOGUE_GRADS``), so the pre-activation never materializes.
    """
    return _fused_linear_fwd(x, w, b, activation, out_dtype, cfg)[0]


def fused_linear_cfg(config):
    """Autotune config dict -> the hashable cfg tuple."""
    from veles_tpu.ops import autotune
    return (config["bm"], config["bn"], config["bk"],
            autotune.ds_tuple(config), autotune.kernel_interpret())


def _fused_linear_fwd(x, w, b, activation, out_dtype, cfg):
    bm, bn, bk, ds, interpret = cfg
    y = pallas_gemm(x, w, bm=bm, bn=bn, bk=bk, out_dtype=out_dtype,
                    bias=b, activation=activation,
                    dimension_semantics=ds, interpret=interpret)
    return y, (x, w, y)


def _fused_linear_bwd(activation, out_dtype, cfg, res, g):
    x, w, y = res
    dpre = (g.astype(jnp.float32) *
            _EPILOGUE_GRADS[activation](y.astype(jnp.float32)))
    db = jnp.sum(dpre, axis=0).astype(jnp.float32)
    # backward dots in the forward's compute dtype (the policy's MXU
    # path), routed through the same shape-aware dispatch — dgrad is
    # (M, K) x (K=N) and wgrad the thin (K, M) x (M, N) shape
    dpre_c = dpre.astype(w.dtype)
    dx = _planned_dot(dpre_c, w.T, tb=True).astype(x.dtype)
    dw = _planned_dot(x.T, dpre_c, ta=True).astype(w.dtype)
    return dx, dw, db


fused_linear.defvjp(_fused_linear_fwd, _fused_linear_bwd)
