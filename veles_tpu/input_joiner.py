"""InputJoiner unit (re-designs ``veles/input_joiner.py:49``).

Concatenates several input Arrays along the feature axis into one
output, on device. The reference jinja-templated a per-input OpenCL copy
kernel (``ocl/join.jcl``); here XLA's concatenate does the packing and
fuses with neighbors (:func:`veles_tpu.ops.join.join_arrays`).
"""

import numpy

from veles_tpu.accelerated_units import AcceleratedUnit
from veles_tpu.memory import Array
from veles_tpu.ops.join import join_arrays


class InputJoiner(AcceleratedUnit):
    """output = concat(flatten(input_0), flatten(input_1), ...)."""

    def __init__(self, workflow, **kwargs):
        self.num_inputs = kwargs.pop("num_inputs", 2)
        super(InputJoiner, self).__init__(workflow, **kwargs)
        self.output = Array()
        for i in range(self.num_inputs):
            setattr(self, "input_%d" % i, None)
        self.demand(*("input_%d" % i for i in range(self.num_inputs)))

    @property
    def inputs(self):
        return [getattr(self, "input_%d" % i)
                for i in range(self.num_inputs)]

    def _input_mems(self):
        return [inp.mem if isinstance(inp, Array) else numpy.asarray(inp)
                for inp in self.inputs]

    def initialize(self, device=None, **kwargs):
        super(InputJoiner, self).initialize(device=device, **kwargs)
        mems = self._input_mems()
        batch = mems[0].shape[0]
        width = sum(int(numpy.prod(m.shape[1:])) for m in mems)
        self.output.reset(numpy.zeros((batch, width), numpy.float32))
        self.init_vectors(self.output,
                          *(i for i in self.inputs if isinstance(i, Array)))

    def jax_run(self):
        devmems = [inp.devmem if isinstance(inp, Array) else inp
                   for inp in self.inputs]
        for inp in self.inputs:
            if isinstance(inp, Array):
                inp.unmap()
        self.output.assign_devmem(join_arrays(*devmems))

    def numpy_run(self):
        mems = [m.reshape(m.shape[0], -1) for m in self._input_mems()]
        out = self.output.map_invalidate()
        out[...] = numpy.concatenate(mems, axis=1)
