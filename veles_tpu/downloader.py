"""Dataset downloader unit (re-designs ``veles/downloader.py:56``).

At workflow initialize time, if the target directory does not already
contain the expected files, fetch an archive from ``url`` and unpack it.
Supports ``file://`` and ``http(s)://`` URLs and ``.zip``/``.tar*``
archives. Runs before any loader touches the data (link it ahead of the
loader or just construct it first — it does all work in initialize()).
"""

import os
import tarfile
import urllib.parse
import urllib.request
import zipfile

from veles_tpu.config import root
from veles_tpu.units import TrivialUnit


class Downloader(TrivialUnit):
    """Fetch + unpack a dataset archive if not already present."""

    view_group = "SERVICE"

    def __init__(self, workflow, **kwargs):
        self.url = kwargs.pop("url")
        self.directory = kwargs.pop(
            "directory", root.common.dirs.get("datasets", "."))
        #: files whose presence means the dataset is already there
        self.files = tuple(kwargs.pop("files", ()))
        super(Downloader, self).__init__(workflow, **kwargs)

    def initialize(self, **kwargs):
        if self.files and all(
                os.path.exists(os.path.join(self.directory, name))
                for name in self.files):
            self.debug("dataset already present in %s", self.directory)
            return
        os.makedirs(self.directory, exist_ok=True)
        archive = self._fetch()
        try:
            self._unpack(archive)
        finally:
            if archive.startswith(self.directory):
                os.unlink(archive)
        missing = [name for name in self.files if not os.path.exists(
            os.path.join(self.directory, name))]
        if missing:
            raise FileNotFoundError(
                "archive from %s did not provide: %s" %
                (self.url, ", ".join(missing)))

    def _fetch(self):
        parsed = urllib.parse.urlparse(self.url)
        name = os.path.basename(parsed.path)
        if parsed.scheme in ("", "file"):
            return urllib.request.url2pathname(parsed.path)
        target = os.path.join(self.directory, name)
        self.info("downloading %s", self.url)
        with urllib.request.urlopen(self.url) as response, \
                open(target, "wb") as out:
            while True:
                chunk = response.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
        return target

    def _unpack(self, archive):
        self.info("unpacking %s to %s", archive, self.directory)
        if zipfile.is_zipfile(archive):
            with zipfile.ZipFile(archive) as z:
                z.extractall(self.directory)  # noqa: S202 — trusted source
        elif tarfile.is_tarfile(archive):
            with tarfile.open(archive) as t:
                t.extractall(self.directory)  # noqa: S202
        else:
            # plain file: place it under the target directory as-is
            import shutil
            shutil.copy(archive, self.directory)
