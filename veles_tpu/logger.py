"""Logging mixin + structured event tracing.

Re-designs the reference's ``veles/logger.py``: every object gets a named
logger through the :class:`Logger` mixin, console output is colorized,
all logging can be duplicated to a rotating file, and ``event()`` emits
structured, timestamped trace records. Where the reference sank events
into MongoDB (``veles/logger.py:210-331``), we write JSON-lines — the
natural sink for a single-controller TPU driver, and directly loadable
into the web status timeline.
"""

import json
import logging
import logging.handlers
import os
import sys
import threading
import time


class ColorFormatter(logging.Formatter):
    """ANSI-colored console formatter (tty only)."""

    COLORS = {
        logging.DEBUG: "\033[37m",
        logging.INFO: "\033[92m",
        logging.WARNING: "\033[93m",
        logging.ERROR: "\033[91m",
        logging.CRITICAL: "\033[1;91m",
    }
    RESET = "\033[0m"

    def __init__(self, colored=None):
        super(ColorFormatter, self).__init__(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s",
            datefmt="%H:%M:%S")
        if colored is None:
            colored = sys.stderr.isatty()
        self.colored = colored

    def format(self, record):
        text = super(ColorFormatter, self).format(record)
        if self.colored:
            color = self.COLORS.get(record.levelno, "")
            if color:
                return color + text + self.RESET
        return text


_setup_lock = threading.Lock()
_setup_done = False


def setup_logging(level=logging.INFO):
    global _setup_done
    with _setup_lock:
        if _setup_done:
            logging.getLogger().setLevel(level)
            return
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(ColorFormatter())
        logging.getLogger().addHandler(handler)
        logging.getLogger().setLevel(level)
        _setup_done = True


def redirect_all_logging_to_file(path, max_bytes=1 << 24, backups=9):
    """Duplicate root logging into a rotating file (``logger.py:187-207``)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    handler = logging.handlers.RotatingFileHandler(
        path, maxBytes=max_bytes, backupCount=backups)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logging.getLogger().addHandler(handler)
    return handler


class EventWriter(object):
    """Structured event sink: JSON lines with session/thread identity."""

    def __init__(self, path, session_id=None):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._file = open(path, "a", buffering=1)
        self._lock = threading.Lock()
        self.session_id = session_id or "%d.%d" % (os.getpid(),
                                                   int(time.time()))

    def write(self, record):
        with self._lock:
            self._file.write(json.dumps(record, default=str) + "\n")

    def close(self):
        with self._lock:
            self._file.close()


_event_writer = None
_event_sinks = []


def duplicate_events_to_file(path, session_id=None):
    """Activate the structured event stream (replaces Mongo duplication)."""
    global _event_writer
    _event_writer = EventWriter(path, session_id)
    return _event_writer


def add_event_sink(sink):
    """Register an additional event consumer (``sink.write(record)``;
    needs a ``session_id``) — e.g. the dashboard's live timeline
    poster (:class:`veles_tpu.web_status.WebStatusEventSink`)."""
    _event_sinks.append(sink)
    return sink


def remove_event_sink(sink):
    try:
        _event_sinks.remove(sink)
    except ValueError:
        pass


def events_active():
    return _event_writer is not None or bool(_event_sinks)


class Logger(object):
    """Mixin giving any object a named logger + event tracing.

    Mirrors ``veles/logger.py:59`` in capability: ``self.info/debug/...``
    helpers, a per-instance ``logger`` named after the class (optionally a
    custom ``logger_name``), and :meth:`event` for begin/end/single trace
    records keyed by instance id.
    """

    def __init__(self, **kwargs):
        logger_name = kwargs.pop("logger_name", type(self).__name__)
        super(Logger, self).__init__()
        self._logger_ = logging.getLogger(logger_name)

    @property
    def logger(self):
        return self._logger_

    @logger.setter
    def logger(self, value):
        self._logger_ = value

    def change_logger_name(self, name):
        self._logger_ = logging.getLogger(name)

    # pickling: loggers carry locks; store only the name. This helper is
    # THE one place encoding that rule — Pickleable delegates here.
    def pickle_logger_state(self, state):
        state["_logger_"] = self._logger_.name
        return state

    def __getstate__(self):
        state = getattr(super(Logger, self), "__getstate__", dict)()
        if not isinstance(state, dict):  # pragma: no cover
            state = self.__dict__.copy()
        return self.pickle_logger_state(dict(state))

    def __setstate__(self, state):
        name = state.pop("_logger_", type(self).__name__)
        parent_setstate = getattr(super(Logger, self), "__setstate__", None)
        if parent_setstate is not None:
            parent_setstate(state)
        else:
            self.__dict__.update(state)
        self._logger_ = logging.getLogger(
            name if isinstance(name, str) else type(self).__name__)

    def msg_changed(self, *args):  # pragma: no cover - debug aid
        pass

    def debug(self, msg, *args, **kwargs):
        self._logger_.debug(msg, *args, **kwargs)

    def info(self, msg, *args, **kwargs):
        self._logger_.info(msg, *args, **kwargs)

    def warning(self, msg, *args, **kwargs):
        self._logger_.warning(msg, *args, **kwargs)

    def error(self, msg, *args, **kwargs):
        self._logger_.error(msg, *args, **kwargs)

    def exception(self, msg="", *args, **kwargs):
        self._logger_.exception(msg, *args, **kwargs)

    def critical(self, msg, *args, **kwargs):
        self._logger_.critical(msg, *args, **kwargs)

    def event(self, name, etype, **attrs):
        """Emit a structured trace event.

        ``etype`` is "begin" | "end" | "single" — the contract of
        ``veles/logger.py:264-289``; no-op unless a sink is active.
        """
        if _event_writer is None and not _event_sinks:
            return
        if etype not in ("begin", "end", "single"):
            raise ValueError("bad event type %r" % etype)
        record = {
            "instance": "%s@%x" % (type(self).__name__, id(self)),
            "name": name,
            "type": etype,
            "time": time.time(),
            "thread": threading.current_thread().name,
        }
        record.update(attrs)
        # each consumer gets ITS session identity — the dashboard
        # filters its timeline by the launcher's log_id while the file
        # stream keeps the pid.time session
        if _event_writer is not None:
            _event_writer.write(dict(
                record, session=_event_writer.session_id))
        for sink in _event_sinks:
            sink.write(dict(record, session=sink.session_id))
