"""THE reader for ``VELES_*`` environment knobs.

Every env knob in the tree is read through :func:`env_knob` (or the
boolean convenience :func:`env_flag`) because the raw
``float(os.environ.get("VELES_X") or ...)`` pattern has produced the
same crash class repeatedly (PR 12's ``float('')``): an
exported-but-empty variable (``export VELES_X=``, a YAML
``env: {VELES_X: }`` block, a systemd ``Environment=`` override) means
*unset*, not "the empty string is a value". ``env_knob`` folds both
``None`` and ``""`` into the default before any parsing happens.

A present-but-garbage value (``VELES_PREFETCH=banana``) raises a
``ValueError`` *naming the knob* by default — a typo'd operator
override should fail at startup with a pointed message, not deep in a
training loop with a bare conversion traceback. Knobs that must
degrade rather than raise (telemetry peaks, bench throttles — anything
whose failure must never unwind a training sweep) pass
``on_error="default"``.

The static analyzer's knob checker (``python -m veles_tpu.analysis``)
flags any ``VELES_*`` read that bypasses this module, so the contract
is enforced, not aspirational. The knob catalog lives in
docs/CONFIGURATION.md; the same checker fails CI when a knob is read
in code but missing from the catalog.
"""

import os

#: lowercased values that mean "false" for :func:`env_flag`; anything
#: else present-and-non-empty is true ("1", "on", "yes", "pallas", ...)
FALSE_WORDS = frozenset(("0", "off", "no", "false"))


def env_knob(name, default=None, parse=None, on_error="raise"):
    """Read env knob ``name``; unset or empty returns ``default``.

    ``parse`` (e.g. ``int``/``float``) converts a present value; on a
    conversion failure ``on_error="raise"`` (the default) raises a
    ``ValueError`` naming the knob, ``on_error="default"`` returns
    ``default`` instead.
    """
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    if parse is None:
        return raw
    try:
        return parse(raw)
    except (TypeError, ValueError):
        if on_error == "default":
            return default
        raise ValueError("%s=%r is not a valid %s" % (
            name, raw, getattr(parse, "__name__", str(parse))))


def env_flag(name, default=False):
    """Boolean knob: unset/empty -> ``default``; else False only for
    the :data:`FALSE_WORDS` spellings (case/whitespace-insensitive)."""
    raw = env_knob(name)
    if raw is None:
        return default
    return raw.strip().lower() not in FALSE_WORDS
