"""Weighted-fair share accounting — ONE ledger for every arbiter.

Extracted from ``serving/admission.py`` (PR 14) so the token/share
math has a single owner: the serving :class:`AdmissionController`
meters *in-flight samples* against an engine capacity, and the
training scheduler (``veles_tpu/sched``) meters *device slots* against
a pool — both are the same weighted-fair problem:

* every principal (a tenant) has a **weight** and a **QoS class**
  (``interactive`` > ``batch`` > ``best_effort``, multiplying the
  weight 4x/2x/1x by default), so interactive work displaces batch
  backfill, never the reverse;
* a principal's **guaranteed share** is ``capacity * w_i / W`` where
  ``W`` sums the effective weights of *recently active* principals —
  an idle principal's share is lendable, a returning one reclaims it
  within one ``activity_window_s``;
* allocation is **work-conserving with reservations**: under-share
  principals are always served (capacity permitting); an over-share
  principal may borrow only headroom no active peer holds a claim on
  (:func:`reserved_claim` — the sum of other active principals'
  unused shares stays reserved for them).

This module is pure accounting: no locks, no metrics, no clocks of
its own — callers hold their own lock, pass ``now`` explicitly, and
publish whatever telemetry fits their plane. Behavior is pinned by
the admission tests (``tests/test_serving_elastic.py``) running
unchanged against the extraction.
"""

import collections

#: QoS class -> weight multiplier; order is also the shed priority
QOS_MULTIPLIER = {"interactive": 4.0, "batch": 2.0, "best_effort": 1.0}
DEFAULT_QOS = "batch"


class ShareAccount(object):
    """Accounting for one principal: outstanding units, drain rate,
    decision windows. (The serving plane calls these *tenants* and
    re-exports this class as its historical ``_Tenant`` name.)"""

    __slots__ = ("name", "weight", "qos", "outstanding", "last_active",
                 "completions", "decisions", "shed_window",
                 "admitted_total", "shed_total")

    def __init__(self, name, weight=1.0, qos=DEFAULT_QOS):
        self.name = name
        self.weight = float(weight)
        self.qos = qos
        self.outstanding = 0
        self.last_active = 0.0
        self.completions = collections.deque()   # (t,) drain window
        self.decisions = collections.deque(maxlen=256)  # 1 admit/0 shed
        self.shed_window = 0    # running count of 0s in `decisions`
        self.admitted_total = 0
        self.shed_total = 0

    @property
    def effective_weight(self):
        return self.weight * QOS_MULTIPLIER.get(self.qos, 1.0)

    def is_active(self, now, activity_window_s):
        """Holding units, or touched within the activity window —
        the set whose weights divide the capacity."""
        return (self.outstanding > 0 or
                now - self.last_active <= activity_window_s)

    def record_decision(self, admitted):
        """Window append with a running shed count — callers publish
        a shed-ratio gauge on every admit/settle under their global
        lock, so re-counting the window there would be O(window)
        hot-path work."""
        if len(self.decisions) == self.decisions.maxlen:
            self.shed_window -= 1 - self.decisions.popleft()
        self.decisions.append(1 if admitted else 0)
        if not admitted:
            self.shed_window += 1

    def drain_rate(self, now, window_s):
        horizon = now - window_s
        while self.completions and self.completions[0] < horizon:
            self.completions.popleft()
        if not self.completions:
            return 0.0
        return len(self.completions) / window_s


def guaranteed_share(capacity, account, accounts, now,
                     activity_window_s):
    """``account``'s guaranteed share (>=1) vs its active peers."""
    active_w = account.effective_weight
    for other in accounts:
        if other is account:
            continue
        if other.is_active(now, activity_window_s):
            active_w += other.effective_weight
    return max(1.0, capacity * account.effective_weight / active_w)


def reserved_claim(capacity, account, accounts, now,
                   activity_window_s):
    """Unused share active OTHER principals still hold a claim on —
    the headroom ``account`` may NOT borrow."""
    reserved = 0.0
    total_w = sum(
        a.effective_weight for a in accounts
        if a is account or a.is_active(now, activity_window_s))
    for other in accounts:
        if other is account:
            continue
        if other.is_active(now, activity_window_s):
            share = capacity * other.effective_weight / total_w
            reserved += max(0.0, share - other.outstanding)
    return reserved
