"""Plotter unit base.

Re-designs ``veles/plotter.py:48-166``: a plotter is an ordinary unit
in the control-flow graph whose ``run()`` captures plot data host-side
and ships a stripped pickle of itself to the graphics server; the
actual matplotlib rendering happens in the client process
(:mod:`veles_tpu.graphics_client`), never on the training path. On
slaves plotters are skipped entirely — plots describe canonical
(master/standalone) state.

Subclasses implement ``fill()`` (grab data from linked attributes —
this is the only part that touches live arrays, so it forces host sync
exactly once per plot) and ``redraw(figure)`` (pure matplotlib over the
captured data).
"""

from veles_tpu.config import root
from veles_tpu.units import Unit


class Plotter(Unit):
    """Base unit for all plotters. See module docstring."""

    hide_from_registry = True
    view_group = "PLOTTER"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("view_group", "PLOTTER")
        super(Plotter, self).__init__(workflow, **kwargs)
        self.clear_plot = kwargs.get("clear_plot", False)
        self.redraw_plot = kwargs.get("redraw_plot", True)
        #: True once fill() captured live data (the publisher must not
        #: re-fill a plotter that accumulated state during the run)
        self.has_filled = False
        self.last_figure_ = None

    @property
    def enabled(self):
        if self.is_slave:
            return False
        # Headless runs disable plotting by default (config.py), but a
        # live graphics server means someone subscribed file/remote
        # renderers — that overrides the no-DISPLAY heuristic.
        if self._find_server() is not None:
            return True
        return not root.common.disable.get("plotting", False)

    def initialize(self, **kwargs):
        pass

    def run(self):
        if not self.enabled:
            return
        self.fill()
        self.has_filled = True
        server = self._find_server()
        if server is not None:
            server.enqueue(self)

    def _find_server(self):
        from veles_tpu.graphics_server import GraphicsServer
        launcher = self.launcher
        server = getattr(launcher, "_graphics_server", None)
        return server if server is not None else GraphicsServer.current

    def fill(self):
        """Capture plot data from linked attributes into plain fields."""

    def redraw(self, figure):
        """Render the captured data onto ``figure`` (client side)."""
        raise NotImplementedError
