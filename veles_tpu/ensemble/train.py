"""Ensemble training (``veles/ensemble/model_workflow.py:50-152``).

Each member trains with a distinct seed on a ``train_ratio`` subsample
(both delivered as config overrides), snapshots itself, and reports its
metrics; the trainer collects all member results — including each
member's snapshot path, which the tester consumes — into one JSON.
"""

from veles_tpu.ensemble.base import EnsembleManagerBase


class EnsembleTrainManager(EnsembleManagerBase):
    """Train-mode manager: one job = train member #i."""

    def __init__(self, train_ratio=0.8, **kwargs):
        super(EnsembleTrainManager, self).__init__(**kwargs)
        if not 0.0 < float(train_ratio) <= 1.0:
            raise ValueError("train_ratio must be in (0, 1] (got %s)"
                             % train_ratio)
        self.train_ratio = float(train_ratio)

    def model_overrides(self, index):
        overrides = super(EnsembleTrainManager, self).model_overrides(index)
        overrides["root.common.ensemble.train_ratio"] = self.train_ratio
        overrides["root.common.disable.plotting"] = True
        overrides["root.common.disable.publishing"] = True
        return overrides

    def model_argv(self, index, result_path):
        # per-member seed: reproducible but distinct member streams
        # (the reference derives them the same way, model_workflow.py:101)
        argv = self._base_argv(result_path, self.seed_base + index * 1000)
        argv.extend("%s=%r" % (k, v)
                    for k, v in self.model_overrides(index).items())
        return argv

    def gathered(self):
        out = super(EnsembleTrainManager, self).gathered()
        out["train_ratio"] = self.train_ratio
        fitnesses = [r.get("fitness", r.get("EvaluationFitness"))
                     for r in self.results if isinstance(r, dict)]
        out["fitnesses"] = [f for f in fitnesses if f is not None]
        return out


class EnsembleTrainer(EnsembleTrainManager):
    """CLI facade: ``--ensemble-train N:RATIO`` (``__main__.py``)."""

    def __init__(self, workflow_file, config_file=None, size=1,
                 train_ratio=0.8, result_file="ensemble.json", **kwargs):
        super(EnsembleTrainer, self).__init__(
            workflow_file=workflow_file, config_file=config_file,
            size=size, train_ratio=train_ratio, result_file=result_file,
            **kwargs)
