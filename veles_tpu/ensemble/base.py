"""Shared ensemble machinery: subprocess evaluation + task farming.

Re-designs ``veles/ensemble/base_workflow.py:59-166``
(EnsembleModelManagerBase): a slot table of per-model results, jobs
handed to slaves through IDistributable with pending-tracking and
requeue-on-drop, and a ``_exec`` helper that runs one model as a
``python -m veles_tpu`` subprocess reading metrics back from a results
file.
"""

import json
import os
import subprocess
import sys
import tempfile

from veles_tpu.distributable import Distributable, IDistributable


class EnsembleManagerBase(Distributable, IDistributable):
    """N result slots; each job = one model index to process."""

    def __init__(self, workflow_file=None, config_file=None, size=1,
                 result_file=None, seed_base=1234, extra_argv=(),
                 runner=None, warm=True, **kwargs):
        super(EnsembleManagerBase, self).__init__(**kwargs)
        if int(size) < 1:
            raise ValueError("ensemble size must be > 0 (got %s)" % size)
        self.workflow_file = workflow_file
        self.config_file = config_file
        self.size = int(size)
        self.results = [None] * self.size
        self.result_file = result_file
        self.seed_base = int(seed_base)
        self.extra_argv = list(extra_argv)
        self.runner = runner  # callable(index) -> dict, for tests/in-proc
        #: keep ONE evaluator process alive across members (the second
        #: member onward pays no JAX import/compile — VERDICT r2 #6);
        #: False reproduces the reference's cold re-exec per member
        self.warm = warm

    def init_unpickled(self):
        super(EnsembleManagerBase, self).init_unpickled()
        self._pending_ = {}
        self._pool_ = None
        self._atexit_registered_ = False

    def _get_pool(self):
        if self._pool_ is None:
            from veles_tpu.parallel.warm_pool import WarmPool
            self._pool_ = WarmPool(workers=1)
            # slaves evaluate via generate_data_for_master and never
            # enter run()'s finally — make sure the evaluator process
            # is reaped at interpreter exit regardless. Registered
            # ONCE per instance: close_pool nulls _pool_, so repeated
            # run() cycles re-create the pool and would otherwise
            # stack a stale atexit entry per recreation
            if not self._atexit_registered_:
                import atexit
                atexit.register(self.close_pool)
                self._atexit_registered_ = True
        return self._pool_

    def close_pool(self):
        if getattr(self, "_pool_", None) is not None:
            self._pool_.close()
            self._pool_ = None

    # -- progress ----------------------------------------------------------

    @property
    def processed(self):
        return sum(1 for r in self.results if r is not None)

    @property
    def pending_indices(self):
        held = {i for s in self._pending_.values() for i in s}
        return [i for i, r in enumerate(self.results)
                if r is None and i not in held]

    @property
    def complete(self):
        return self.processed == self.size

    # -- one model ---------------------------------------------------------

    def model_overrides(self, index):
        """Config overrides marking which ensemble member this run is."""
        return {"root.common.ensemble.model_index": index,
                "root.common.ensemble.size": self.size}

    def model_argv(self, index, result_path):
        raise NotImplementedError

    def process_model(self, index):
        """Run model #index, return its results dict."""
        if self.runner is not None:
            return self.runner(index)
        fd, result_path = tempfile.mkstemp(
            suffix=".json", prefix="veles_tpu_ensemble_")
        os.close(fd)
        argv = self.model_argv(index, result_path)
        if self.warm:
            # warm evaluator: in-process main() in a long-lived worker
            # (the worker deletes the result file after reading it; the
            # finally covers a worker that died before getting there)
            try:
                reply = self._get_pool().run(argv,
                                             result_file=result_path)
            except (RuntimeError, OSError, ValueError) as e:
                # hard worker death — WarmPool.run's documented raise
                # set (RuntimeError on exit, OSError on a broken pipe,
                # ValueError on a truncated reply): the pool already
                # replaced the worker, so record this member as failed
                # and keep the rest of the ensemble
                self.warning("model #%d evaluator died: %s", index, e)
                return None
            finally:
                try:
                    os.unlink(result_path)
                except OSError:
                    pass
            if not reply.get("ok"):
                self.warning("model #%d failed: %s", index,
                             reply.get("error", reply.get("code")))
                return None
            return reply.get("result")
        try:
            full = [sys.executable, "-m", "veles_tpu"] + argv
            self.debug("exec: %s", " ".join(full))
            proc = subprocess.run(full, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT)
            if proc.returncode != 0:
                self.warning(
                    "model #%d failed (%d): %s", index, proc.returncode,
                    proc.stdout[-2000:].decode(errors="replace"))
                return None
            with open(result_path) as f:
                return json.load(f)
        finally:
            try:
                os.unlink(result_path)
            except OSError:
                pass

    def _base_argv(self, result_path, seed):
        """Module-level args (no interpreter prefix: warm workers pass
        these straight to ``veles_tpu.__main__.main``)."""
        argv = [self.workflow_file]
        if self.config_file:
            argv.append(self.config_file)
        argv.extend(["--result-file", result_path, "-s", str(seed),
                     "-v", "warning"])
        argv.extend(self.extra_argv)
        return argv

    # -- driver ------------------------------------------------------------

    def run(self):
        try:
            for index in range(self.size):
                if self.results[index] is None:
                    self.info("processing model %d / %d", index + 1,
                              self.size)
                    self.results[index] = self.process_model(index)
        finally:
            self.close_pool()
        self.write_results()
        return self.results

    def gathered(self):
        """The dict written to result_file; subclasses extend."""
        return {"models": self.results, "size": self.size}

    def write_results(self):
        if not self.result_file:
            return
        with open(self.result_file, "w") as f:
            json.dump(self.gathered(), f, indent=2, default=str)
        self.info("wrote ensemble results to %s", self.result_file)

    # -- task farming (``base_workflow.py:103-131``) -----------------------

    @property
    def has_data_for_slave(self):
        return bool(self.pending_indices)

    def generate_data_for_slave(self, slave):
        free = self.pending_indices
        if not free:
            return None
        index = free[0]
        self._pending_.setdefault(slave, set()).add(index)
        self.info("enqueued model #%d / %d to %s", index + 1, self.size,
                  slave)
        return index

    def apply_data_from_master(self, data):
        self._job_index_ = int(data)

    def generate_data_for_master(self):
        return (self._job_index_, self.process_model(self._job_index_))

    def apply_data_from_slave(self, data, slave):
        index, result = data
        self._pending_.get(slave, set()).discard(index)
        self.results[index] = result

    def drop_slave(self, slave):
        requeued = self._pending_.pop(slave, set())
        if requeued:
            self.info("slave %s dropped, requeued models %s", slave,
                      sorted(requeued))
