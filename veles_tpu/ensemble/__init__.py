"""Model ensembling (``veles/ensemble/``).

Train N independent models on seed-varied, ``train_ratio``-subsampled
data, gather every model's metrics into one results JSON, then evaluate
the ensemble on a test set — the reference's third parallelism strategy
(SURVEY.md §2.4): each model is a whole training run farmed out as a
subprocess (``veles/ensemble/base_workflow.py:59-166``) or a slave job.
"""

from veles_tpu.ensemble.base import EnsembleManagerBase  # noqa: F401
from veles_tpu.ensemble.train import (EnsembleTrainer,  # noqa: F401
                                      EnsembleTrainManager)
from veles_tpu.ensemble.test import (EnsembleTester,  # noqa: F401
                                     aggregate_metrics)
