"""Ensemble evaluation (``veles/ensemble/test_workflow.py:50-107``).

Reads the training results JSON, re-runs every member from its snapshot
in testing mode, and aggregates the member metrics (mean/std for numeric
metrics, the full per-member table for everything else).
"""

import json

import numpy

from veles_tpu.ensemble.base import EnsembleManagerBase


def aggregate_metrics(member_results):
    """mean/std/min/max for every numeric metric across members."""
    table = {}
    for result in member_results:
        if not isinstance(result, dict):
            continue
        for key, value in result.items():
            if isinstance(value, (int, float)) and not isinstance(value,
                                                                  bool):
                table.setdefault(key, []).append(float(value))
    out = {}
    for key, values in table.items():
        arr = numpy.asarray(values)
        out[key] = {"mean": float(arr.mean()), "std": float(arr.std()),
                    "min": float(arr.min()), "max": float(arr.max()),
                    "n": len(values)}
    return out


class EnsembleTester(EnsembleManagerBase):
    """Test-mode manager: one job = evaluate member #i from its snapshot."""

    def __init__(self, workflow_file=None, config_file=None,
                 results_file=None, result_file="ensemble_test.json",
                 **kwargs):
        self.train_results = self._read(results_file)
        members = self.train_results.get("models") or []
        if not members:
            raise ValueError("no trained members in %s" % results_file)
        super(EnsembleTester, self).__init__(
            workflow_file=workflow_file, config_file=config_file,
            size=len(members), result_file=result_file, **kwargs)
        self.results_file = results_file

    @staticmethod
    def _read(results_file):
        if isinstance(results_file, dict):  # already-parsed (tests)
            return results_file
        with open(results_file) as f:
            return json.load(f)

    def snapshot_of(self, index):
        member = self.train_results["models"][index]
        if not isinstance(member, dict):
            return None
        for key in ("Snapshot", "snapshot", "snapshot_file"):
            if member.get(key):
                return member[key]
        return None

    def model_argv(self, index, result_path):
        snapshot = self.snapshot_of(index)
        if snapshot is None:
            raise ValueError(
                "member #%d has no snapshot in %s — cannot test" %
                (index, self.results_file))
        argv = self._base_argv(result_path, self.seed_base + index * 1000)
        argv.extend(["-w", str(snapshot), "--test"])
        argv.extend("%s=%r" % (k, v)
                    for k, v in self.model_overrides(index).items())
        return argv

    def gathered(self):
        return {"models": self.results, "size": self.size,
                "aggregate": aggregate_metrics(
                    [r for r in self.results if r is not None])}
