"""Workflow-as-a-service feed (re-designs ``veles/zmq_loader.py:74``).

The reference exposed a ZeroMQ ROUTER endpoint external producers push
work items into; the workflow consumes them as minibatches. Here the
wire is a stdlib JSON-lines TCP socket (the same framing as the
coordinator control plane) and the consuming side is the shared
queue-fed loader. Producers connect, send one JSON object per line
(``{"data": [...]}``) and receive ``{"ok": true}`` acks; ``{"cmd":
"finish"}`` ends the stream and thereby the workflow.
"""

import json
import socket
import threading

import numpy

from veles_tpu.loader.interactive import QueueFedLoader


class SocketFedLoader(QueueFedLoader):
    """Queue-fed loader with a TCP JSON-lines producer endpoint."""

    def __init__(self, workflow, **kwargs):
        self.endpoint = kwargs.pop("endpoint", ("127.0.0.1", 0))
        super(SocketFedLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        super(SocketFedLoader, self).load_data()
        self._listener_ = socket.create_server(tuple(self.endpoint))
        self.address = self._listener_.getsockname()
        self._accepting_ = True
        thread = threading.Thread(target=self._accept_loop, daemon=True,
                                  name="%s-accept" % self.name)
        thread.start()
        self.info("feed endpoint on %s:%d", *self.address)

    def _accept_loop(self):
        while self._accepting_:
            try:
                sock, _ = self._listener_.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(sock,),
                             daemon=True).start()

    def _serve(self, sock):
        from veles_tpu.parallel.coordinator import Protocol
        proto = Protocol(sock)
        with sock:
            while True:
                try:
                    msg = proto.recv()
                except ConnectionError:
                    return
                except json.JSONDecodeError:
                    proto.send({"error": "bad json"})
                    continue
                if isinstance(msg, dict) and msg.get("cmd") == "finish":
                    self.finish()
                    proto.send({"ok": True, "finished": True})
                    return
                try:
                    sample = numpy.asarray(msg["data"], numpy.float32)
                    # reject wrong-size samples HERE, while the producer
                    # still gets the error ack — once fed, the reshape in
                    # fill_minibatch would crash the workflow run thread
                    sample = sample.reshape(self.sample_shape)
                except (TypeError, KeyError, IndexError, ValueError) as exc:
                    # a bad item must neither kill this connection's
                    # thread nor leave the producer blocked on its ack
                    proto.send({"error": str(exc) or type(exc).__name__})
                    continue
                self.feed(sample)
                proto.send({"ok": True})

    def stop_serving(self):
        self._accepting_ = False
        try:
            self._listener_.close()
        except OSError:
            pass
