"""REST-fed inference loader (re-designs ``veles/loader/restful.py:52``).

Pairs with :class:`veles_tpu.restful_api.RESTfulAPI`: each HTTP request
pushes its decoded sample here, the workflow's forward pass runs, and
the API unit reads the output back. Mechanism shared with the
interactive loader (queue-fed test minibatches).

The reference pinned ``minibatch_size=1``. Pass a larger
``minibatch_size`` and concurrent HTTP requests coalesce into one
forward (link the API's ``batch_size`` to this loader's
``minibatch_size`` so one pass answers every coalesced request)::

    loader = RestfulLoader(wf, sample_shape=(4,), minibatch_size=8)
    api = RESTfulAPI(wf, ...)
    api.link_attrs(loader, ("batch_size", "minibatch_size"))
"""

import numpy

from veles_tpu.loader.interactive import QueueFedLoader


class RestfulLoader(QueueFedLoader):
    """HTTP requests become (possibly coalesced) test minibatches."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("minibatch_size", 1)
        super(RestfulLoader, self).__init__(workflow, **kwargs)

    def feed(self, sample):
        """Validate the shape HERE, on the caller's (HTTP) thread —
        once enqueued, a wrong-size sample would crash the workflow's
        run loop in ``fill_minibatch`` instead of failing the request."""
        sample = numpy.asarray(sample, numpy.float32)
        sample = sample.reshape(self.sample_shape)
        super(RestfulLoader, self).feed(sample)
