"""REST-fed inference loader (re-designs ``veles/loader/restful.py:52``).

Pairs with :class:`veles_tpu.restful_api.RESTfulAPI`: each HTTP request
pushes its decoded sample here, the workflow's forward pass runs, and
the API unit reads the output back. Mechanism shared with the
interactive loader (one queue-fed test minibatch per request).
"""

from veles_tpu.loader.interactive import QueueFedLoader


class RestfulLoader(QueueFedLoader):
    """One HTTP request = one test minibatch."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("minibatch_size", 1)
        super(RestfulLoader, self).__init__(workflow, **kwargs)
