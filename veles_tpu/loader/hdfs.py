"""HDFS-backed loader (gated re-design of ``veles/loader/hdfs_loader.py``).

The reference streamed minibatches out of Hadoop HDFS via the ``hdfs``
/ Mastodon bridge. Neither Hadoop client libraries nor a cluster exist
in this environment, so this is a *gated* implementation: it speaks
WebHDFS over plain HTTP (stdlib only — no extra dependency) when a
namenode is reachable, and raises a clear error otherwise. The loader
surface matches :class:`~veles_tpu.loader.pickles.PicklesLoader`:
test/validation/train object paths, each a pickled ``(data, labels)``
tuple, fetched over WebHDFS and assembled into a device-resident full
batch.
"""

import json
import pickle
import urllib.error
import urllib.parse
import urllib.request

from veles_tpu.loader.fullbatch import FullBatchLoader


class WebHDFSClient(object):
    """Minimal WebHDFS reader: OPEN + GETFILESTATUS."""

    def __init__(self, namenode, user=None, timeout=30.0):
        if "://" not in namenode:
            namenode = "http://" + namenode
        self.base = namenode.rstrip("/") + "/webhdfs/v1"
        self.user = user
        self.timeout = timeout

    def _url(self, path, op):
        if not path.startswith("/"):
            path = "/" + path
        url = "%s%s?op=%s" % (self.base,
                              urllib.parse.quote(path), op)
        if self.user:
            url += "&user.name=" + urllib.parse.quote(self.user, safe="")
        return url

    def status(self, path):
        with urllib.request.urlopen(self._url(path, "GETFILESTATUS"),
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())["FileStatus"]

    def read(self, path):
        with urllib.request.urlopen(self._url(path, "OPEN"),
                                    timeout=self.timeout) as resp:
            return resp.read()


class HDFSLoader(FullBatchLoader):
    """Pickled class files fetched from HDFS (WebHDFS REST)."""

    MAPPING = "hdfs"

    def __init__(self, workflow, **kwargs):
        self.namenode = kwargs.pop("namenode", None)
        self.user = kwargs.pop("user", None)
        self.test_path = kwargs.pop("test_path", None)
        self.validation_path = kwargs.pop("validation_path", None)
        self.train_path = kwargs.pop("train_path", None)
        super(HDFSLoader, self).__init__(workflow, **kwargs)
        self.client = None

    def load_dataset(self):
        if not self.namenode:
            raise RuntimeError(
                "%s needs a namenode=host:port (WebHDFS); no Hadoop "
                "client libraries are bundled — this loader is gated on "
                "a reachable WebHDFS endpoint" % self.name)
        self.client = WebHDFSClient(self.namenode, user=self.user)

        def reader(path):
            try:
                blob = self.client.read(path)
            except (urllib.error.URLError, OSError) as e:
                raise RuntimeError(
                    "%s: cannot fetch %s from %s: %s" %
                    (self.name, path, self.namenode, e))
            obj = pickle.loads(blob)
            if isinstance(obj, tuple) and len(obj) == 2:
                return obj
            return obj, None

        self.load_class_files(
            (self.test_path, self.validation_path, self.train_path),
            reader, kind="HDFS")
