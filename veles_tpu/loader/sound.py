"""Audio file loaders (re-design ``veles/loader/libsndfile_loader.py``).

The reference wrapped libsndfile through ctypes; that dependency is not
in this image, so decoding goes through :mod:`scipy.io.wavfile` (WAV of
any PCM width) with a gated ``soundfile`` path for FLAC/OGG when that
package exists. The loader surface matches the file-image loaders:
test/validation/train path lists scanned into a device-resident full
batch, labels taken from the immediate parent directory name.

Samples are normalized to float32 in [-1, 1], mixed down to mono, and
either truncated or zero-padded to ``samples`` frames so the batch
stacks (the reference raised on >2 channels; we mix instead — an
explicit TPU-friendly choice: fixed shapes).
"""

import os

import numpy

from veles_tpu.loader.base import Loader  # noqa: F401 (registry import)
from veles_tpu.loader.file_scanner import LabeledFileScanner
from veles_tpu.loader.fullbatch import FullBatchLoader

#: extensions decodable without optional deps
WAV_EXTENSIONS = (".wav", ".wave")
#: extensions needing the optional ``soundfile`` package
SOUNDFILE_EXTENSIONS = (".flac", ".ogg", ".aiff", ".aif")


def decode_sound(path):
    """-> (float32 mono array in [-1, 1], sample_rate)."""
    ext = os.path.splitext(path)[1].lower()
    if ext in WAV_EXTENSIONS:
        from scipy.io import wavfile
        rate, data = wavfile.read(path)
        if data.dtype.kind == "i":
            data = data.astype(numpy.float32) / numpy.iinfo(data.dtype).max
        elif data.dtype.kind == "u":  # u8 wav: offset binary
            info = numpy.iinfo(data.dtype)
            data = (data.astype(numpy.float32) - (info.max + 1) / 2) \
                / ((info.max + 1) / 2)
        else:
            data = data.astype(numpy.float32)
    elif ext in SOUNDFILE_EXTENSIONS:
        try:
            import soundfile
        except ImportError:
            raise ImportError(
                "decoding %s needs the optional 'soundfile' package "
                "(only PCM WAV is supported without it)" % path)
        data, rate = soundfile.read(path, dtype="float32")
    else:
        raise ValueError("unsupported audio format: %s" % path)
    if data.ndim > 1:  # mix down to mono
        data = data.mean(axis=1)
    return numpy.ascontiguousarray(data, numpy.float32), int(rate)


class SndFileLoader(FullBatchLoader):
    """Directory-tree audio loader; labels = parent directory names."""

    MAPPING = "sound_file"

    def __init__(self, workflow, **kwargs):
        self.test_paths = tuple(kwargs.pop("test_paths", ()))
        self.validation_paths = tuple(kwargs.pop("validation_paths", ()))
        self.train_paths = tuple(kwargs.pop("train_paths", ()))
        #: fixed number of frames per sample (pad/truncate target);
        #: None = infer from the first file
        self.samples = kwargs.pop("samples", None)
        self.ignored_dirs = kwargs.pop("ignored_dirs", ())
        self.filename_re = kwargs.pop("filename_re", None)
        super(SndFileLoader, self).__init__(workflow, **kwargs)
        self.labels_mapping = {}
        self.sample_rate = None

    def _scan_class(self, paths):
        scanner = LabeledFileScanner(
            WAV_EXTENSIONS + SOUNDFILE_EXTENSIONS,
            ignored_dirs=self.ignored_dirs, filename_re=self.filename_re)
        found = []
        for base in paths:
            found.extend(scanner.scan(base))
        return found

    def _fit(self, data):
        if len(data) >= self.samples:
            return data[:self.samples]
        out = numpy.zeros(self.samples, numpy.float32)
        out[:len(data)] = data
        return out

    def load_dataset(self):
        per_class = [self._scan_class(p) for p in
                     (self.test_paths, self.validation_paths,
                      self.train_paths)]
        if not any(per_class):
            raise ValueError("%s found no audio files" % self.name)
        names = sorted({label for pairs in per_class for _, label in pairs})
        self.labels_mapping = {name: i for i, name in enumerate(names)}
        data, labels = [], []
        for klass, pairs in enumerate(per_class):
            for path, label in pairs:
                sound, rate = decode_sound(path)
                if self.sample_rate is None:
                    self.sample_rate = rate
                elif rate != self.sample_rate:
                    raise ValueError(
                        "%s: %s has rate %d, expected %d (resampling is "
                        "out of scope — preprocess the dataset)" %
                        (self.name, path, rate, self.sample_rate))
                if self.samples is None:
                    self.samples = len(sound)
                data.append(self._fit(sound))
                labels.append(self.labels_mapping[label])
            self.class_lengths[klass] = len(pairs)
        self.original_data.reset(numpy.stack(data))
        self.original_labels.reset(numpy.asarray(labels, numpy.int32))

    @property
    def n_classes(self):
        return len(self.labels_mapping)
