"""Interactive and externally-fed loaders.

``InteractiveLoader`` re-designs ``veles/loader/interactive.py:57``: a
loader whose samples are pushed in from the outside (a shell, a driving
program, a service endpoint) through :meth:`feed`; serving blocks until
a sample arrives. The workflow runs in testing (forward-only) mode and
each fed sample joins the next test minibatch.

``QueueFedLoader`` is the shared mechanism — it also backs the REST
inference loader (``veles_tpu/loader/restful.py``) and the socket-fed
workflow-as-a-service loader (``veles_tpu/zmq_loader.py``), collapsing
the reference's three bespoke implementations into one.

The reference hard-wired ``minibatch_size=1`` (one request, one full
forward dispatch). Here a fill drains **up to** ``minibatch_size``
queued samples at once: the first ``get`` blocks, the rest are taken
non-blocking, rows past the valid count are explicitly zero-padded and
``minibatch_size`` carries the valid count — so concurrent feeders
amortize one forward over the whole batch while a lone feeder still
gets single-sample latency (nothing ever waits for a batch to fill).
"""

import queue

import numpy

from veles_tpu.loader.base import TEST, Loader


class QueueFedLoader(Loader):
    """Serves whatever the outside pushes into an unbounded queue."""

    hide_from_registry = True

    #: sentinel a producer may push to unblock a waiting run loop
    EOF = object()

    def __init__(self, workflow, **kwargs):
        self.sample_shape = tuple(kwargs.pop("sample_shape", ()))
        self.feed_timeout = kwargs.pop("feed_timeout", None)
        kwargs.setdefault("minibatch_size", 1)
        super(QueueFedLoader, self).__init__(workflow, **kwargs)
        self.has_labels = False

    def init_unpickled(self):
        super(QueueFedLoader, self).init_unpickled()
        self._queue_ = queue.Queue()

    def feed(self, sample):
        """Push one sample (numpy array of sample_shape)."""
        self._queue_.put(numpy.asarray(sample, numpy.float32))

    def finish(self):
        """Unblock the loop with no more data (ends the workflow)."""
        self._queue_.put(self.EOF)

    def load_data(self):
        if not self.sample_shape:
            raise ValueError("%s needs sample_shape" % self.name)
        # geometry: an endless test-class stream, max_minibatch_size
        # samples per fill (the valid count rides in minibatch_size)
        self.class_lengths = [self.max_minibatch_size, 0, 0]

    def create_minibatch_data(self):
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape,
            numpy.float32))

    def fill_minibatch(self):
        item = self._queue_.get(timeout=self.feed_timeout)
        if item is self.EOF:
            # stop() aborts in-flight signals, so nothing downstream
            # runs this iteration; zeroing the size is defense in depth
            # against a consumer inspecting loader state post-run
            self.minibatch_size = 0
            self.workflow.stop()
            return
        mb = self.minibatch_data.map_invalidate()
        mb[0] = item.reshape(self.sample_shape)
        count = 1
        eof_seen = False
        # opportunistic drain: whatever is ALREADY queued joins this
        # batch; never block waiting for more (single-feeder latency)
        while count < self.max_minibatch_size:
            try:
                item = self._queue_.get_nowait()
            except queue.Empty:
                break
            if item is self.EOF:
                eof_seen = True
                break
            mb[count] = item.reshape(self.sample_shape)
            count += 1
        if count < self.max_minibatch_size:
            # explicit padding: stale rows from the previous fill must
            # not leak into consumers that read the full buffer
            mb[count:] = 0
        self.minibatch_class = TEST
        self.minibatch_size = count
        if eof_seen:
            # the EOF terminates the stream AFTER this batch is served:
            # put it back so the next fill sees it first
            self._queue_.put(self.EOF)


class InteractiveLoader(QueueFedLoader):
    """The user-facing interactive feed (``loader/interactive.py:57``)."""
