"""Loader: the minibatch server.

Re-designs ``veles/loader/base.py`` (Loader :120, serve_next_minibatch
:726, _advance_global_offset :880, distribution hooks :631-687).

Semantics kept from the reference:

* three sample classes laid out consecutively in index space —
  TEST [0, t), VALIDATION [t, t+v), TRAIN [t+v, total);
* one epoch = one sequential pass over the whole index space (test
  first, then validation, then train), minibatch by minibatch;
* ``shuffled_indices`` is the global permutation; only the TRAIN
  segment reshuffles between epochs, from the loader's own seeded PRNG
  (validation/test order is stable);
* ``last_minibatch``/``epoch_ended`` are shared Bools the Decision unit
  gates on; the final minibatch of a segment may be short — it is
  padded to ``max_minibatch_size`` with index −1 (on-device gather
  zero-fills those rows) so every step has a static shape for XLA;
* distribution: the master serves *indices only*
  (``generate_data_for_slave``), slaves gather locally
  (``apply_data_from_master``); a dropped slave's pending minibatches
  go to ``failed_minibatches`` and are re-served
  (``drop_slave``, ``loader/base.py:679-687``);
* ``--train-ratio`` subsampling for ensemble training.
"""

import numpy

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.memory import Array
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit
from veles_tpu.unit_registry import UnitRegistry

TEST = 0
VALIDATION = 1
TRAIN = 2
CLASS_NAMES = ("test", "validation", "train")


class UserLoaderRegistry(UnitRegistry):
    """Maps MAPPING names to loader classes (``loader/base.py:83``)."""

    loaders = {}

    def __init__(cls, name, bases, namespace):
        super(UserLoaderRegistry, cls).__init__(name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if mapping:
            UserLoaderRegistry.loaders[mapping] = cls


class Loader(Unit, metaclass=UserLoaderRegistry):
    """Base minibatch server; subclasses implement load_data() and
    fill_minibatch()."""

    hide_from_registry = True
    view_group = "LOADER"

    def __init__(self, workflow, **kwargs):
        self.max_minibatch_size = kwargs.pop("minibatch_size", 100)
        # root.common.ensemble.train_ratio lets meta-runs (ensemble
        # training, ``veles/ensemble/model_workflow.py:101``) subsample
        # the train set without touching the workflow file.
        self.train_ratio = kwargs.pop(
            "train_ratio", root.common.ensemble.get("train_ratio", 1.0))
        self.shuffle_limit = kwargs.pop("shuffle_limit", numpy.inf)
        self.rand_name = kwargs.pop("rand", "loader")
        super(Loader, self).__init__(workflow, **kwargs)
        self.class_lengths = [0, 0, 0]
        self.shuffled_indices = Array()
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        self.minibatch_indices = Array()
        self.minibatch_size = 0
        self.minibatch_class = TRAIN
        self.minibatch_offset = 0
        self.epoch_number = 0
        self.epoch_ended = Bool(False)
        self.last_minibatch = Bool(False)
        self.train_ended = Bool(False)
        self.failed_minibatches = []
        self._pending_ = {}
        self.samples_served = 0
        self._global_offset = 0
        self.has_labels = True

    def init_unpickled(self):
        super(Loader, self).init_unpickled()
        self._pending_ = {}

    # -- geometry ----------------------------------------------------------

    @property
    def total_samples(self):
        return int(sum(self.class_lengths))

    @property
    def class_end_offsets(self):
        ends, acc = [], 0
        for length in self.class_lengths:
            acc += length
            ends.append(acc)
        return ends

    def class_of_offset(self, offset):
        """Class index owning global offset (offset is the END of a mb)."""
        for klass, end in enumerate(self.class_end_offsets):
            if offset <= end and self.class_lengths[klass]:
                if offset > end - self.class_lengths[klass]:
                    return klass
        raise ValueError("offset %d outside dataset" % offset)

    # -- to override -------------------------------------------------------

    def load_data(self):
        """Set class_lengths (and stage actual data)."""
        raise NotImplementedError

    def create_minibatch_data(self):
        """Allocate minibatch_data for max_minibatch_size samples."""
        raise NotImplementedError

    def fill_minibatch(self):
        """Fill minibatch_data/labels from minibatch_indices."""
        raise NotImplementedError

    def on_before_fill(self):
        pass

    # -- prefetchable fill (the async input pipeline's ETL hook) -----------

    def fill_indices(self, indices, kind="labels"):
        """Host ETL for an arbitrary index vector, WITHOUT touching the
        unit's minibatch state: returns ``(data_rows, truth_rows)``
        host ndarrays where index −1 yields a zero data row and truth
        is taken at ``max(idx, 0)`` (masked later by the loss math —
        the on-device gather's exact padding contract).

        Must be thread-safe over read-only backing state: the prefetch
        pipeline (:mod:`veles_tpu.loader.prefetch`) calls it from
        worker threads while the step thread computes."""
        raise NotImplementedError(
            "%s does not support prefetchable fills" % self.name)

    def iter_shards(self, klass, shard_samples):
        """Yield the class's shuffled sample indices in fixed-size
        shards of ``shard_samples`` (last one short) — the shard
        iteration helper for NON-fused out-of-core consumers (e.g.
        serving warm-up feeding ``fill_indices``). The fused streamed
        path shards its compiled index matrix directly
        (``FusedTrainer._shard_bounds``), not through this."""
        ends = self.class_end_offsets
        start = ends[klass] - self.class_lengths[klass]
        seg = numpy.asarray(
            self.shuffled_indices.map_read()[start:ends[klass]],
            numpy.int32)
        for offset in range(0, len(seg), shard_samples):
            yield seg[offset:offset + shard_samples]

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        self.load_data()
        if self.total_samples == 0:
            raise ValueError("%s loaded an empty dataset" % self.name)
        if self.train_ratio < 1.0 and self.class_lengths[TRAIN]:
            # idempotent across re-initialize (snapshot resume): the
            # ratio always applies to the ORIGINAL train length, or a
            # resumed loader would shrink its train set a second time
            if getattr(self, "_full_train_length", None) is None:
                self._full_train_length = self.class_lengths[TRAIN]
            self.class_lengths[TRAIN] = max(1, int(
                self._full_train_length * self.train_ratio))
        self.max_minibatch_size = min(self.max_minibatch_size, max(
            length for length in self.class_lengths if length) if any(
                self.class_lengths) else self.max_minibatch_size)
        if self.shuffled_indices.mem is None:
            self.shuffled_indices.reset(
                numpy.arange(self.total_samples, dtype=numpy.int32))
        self.minibatch_indices.reset(
            numpy.zeros(self.max_minibatch_size, numpy.int32))
        if self.has_labels:
            self.minibatch_labels.reset(
                numpy.zeros(self.max_minibatch_size, numpy.int32))
        self.create_minibatch_data()
        if getattr(self, "_restored_from_snapshot_", False):
            # resuming: keep the epoch position and flags that came out
            # of the snapshot — the epoch continues exactly where the
            # checkpoint was taken (``veles/snapshotter.py`` contract)
            self._restored_from_snapshot_ = False
        else:
            self._global_offset = 0
            self.epoch_ended <<= False
            self.last_minibatch <<= False

    def run(self):
        if self.is_slave:
            # the minibatch was patched in by apply_data_from_master:
            # a slave never advances the global serving order itself
            return
        self.serve_next_minibatch()

    # -- the serving loop --------------------------------------------------

    def _advance_global_offset(self):
        """Move to the next minibatch; handles epoch wrap + reshuffle."""
        if self._global_offset >= self.total_samples:
            self._finish_epoch()
        ends = self.class_end_offsets
        klass = None
        for ci, end in enumerate(ends):
            if self._global_offset < end and self.class_lengths[ci]:
                klass = ci
                break
        count = min(self.max_minibatch_size,
                    ends[klass] - self._global_offset)
        start = self._global_offset
        self._global_offset += count
        self.minibatch_class = klass
        self.minibatch_offset = self._global_offset
        self.minibatch_size = count
        self.last_minibatch <<= (self._global_offset == ends[klass])
        self.train_ended <<= (klass == TRAIN and
                              self._global_offset == ends[TRAIN])
        self.epoch_ended <<= (self._global_offset == self.total_samples)
        return start, count

    def _finish_epoch(self):
        self.epoch_number += 1
        self._global_offset = 0
        if self.epoch_number <= self.shuffle_limit:
            self.shuffle()

    def shuffle(self):
        """Reshuffle the TRAIN segment only."""
        if not self.class_lengths[TRAIN]:
            return
        indices = self.shuffled_indices.map_write()
        train_start = self.class_end_offsets[VALIDATION]
        segment = indices[train_start:self.total_samples]
        prng.get(self.rand_name).shuffle(segment)
        indices[train_start:self.total_samples] = segment

    def serve_next_minibatch(self):
        payload = self._next_payload()
        self._apply_payload(payload)
        self.samples_served += payload["size"]
        self.event("minibatch", "single", klass=self.minibatch_class,
                   size=payload["size"], epoch=self.epoch_number)

    def _next_payload(self):
        """One minibatch as a self-contained description.

        A payload snapshots everything position-dependent — the actual
        sample indices (not offsets: the permutation reshuffles between
        epochs), epoch flags, class — so serving, sending to a slave,
        and re-serving after a slave death are all exact replays.
        """
        if self.failed_minibatches:
            # a dropped slave's minibatch is re-served before new ones
            # (``loader/base.py:679-687`` fault-tolerance contract)
            return self.failed_minibatches.pop()
        start, count = self._advance_global_offset()
        indices = numpy.asarray(
            self.shuffled_indices.map_read()[start:start + count])
        return {"indices": indices, "class": self.minibatch_class,
                "start": start, "size": count,
                "epoch": self.epoch_number,
                "last": bool(self.last_minibatch),
                "train_ended": bool(self.train_ended),
                "epoch_ended": bool(self.epoch_ended)}

    def _apply_payload(self, data):
        count = data["size"]
        self.minibatch_class = data["class"]
        self.minibatch_size = count
        self.minibatch_offset = data["start"] + count
        self.epoch_number = data["epoch"]
        self.last_minibatch <<= data["last"]
        self.train_ended <<= data.get("train_ended", False)
        self.epoch_ended <<= data["epoch_ended"]
        mb = self.minibatch_indices.map_invalidate()
        mb[:count] = data["indices"]
        mb[count:] = -1  # pad short tails: static shapes for XLA
        self.on_before_fill()
        self.fill_minibatch()

    # -- distribution (master serves indices only) -------------------------

    def generate_data_for_slave(self, slave=None):
        payload = self._next_payload()
        sid = getattr(slave, "id", slave)
        self._pending_.setdefault(sid, []).append(payload)
        return payload

    def apply_data_from_master(self, data):
        self._apply_payload(data)

    def generate_data_for_master(self):
        return {"served": self.samples_served}

    def apply_data_from_slave(self, data, slave=None):
        sid = getattr(slave, "id", slave)
        pending = self._pending_.get(sid)
        # a segment update resolves several served minibatches at once
        count = (data or {}).get("count", 1)
        for _ in range(min(count, len(pending or ()))):
            pending.pop(0)

    def drop_slave(self, slave=None):
        """Requeue everything a dead slave held (fault tolerance)."""
        sid = getattr(slave, "id", slave)
        for job in self._pending_.pop(sid, []):
            self.failed_minibatches.append(job)

    def reset_to_epoch_start(self, epoch):
        """Rewind the serving cursor to the START of ``epoch``,
        discarding partial-epoch progress (pending registrations,
        requeues, epoch flags).

        The master-restart auto-resume path (ISSUE 12): a snapshot
        taken at an epoch boundary may still carry the cursor partway
        into the next epoch (run-ahead jobs in flight at dump time),
        but the merge buckets for that partial epoch died with the old
        master — replaying the epoch from its start is the only way
        sample-count epoch closing can complete it. When the cursor
        already wrapped into (or past) ``epoch``, the snapshot's own
        shuffle state makes the replay serve the same index order the
        lost jobs had; when the snapshot landed BEFORE the lazy wrap
        (epoch e closed, no e+1 job generated yet), the wrap is
        replayed here so the resumed epoch trains on ITS shuffle, not
        the previous epoch's, and the shuffle PRNG stream does not
        skip a draw."""
        epoch = int(epoch)
        while self.epoch_number < epoch:
            # the lazy epoch wrap (_finish_epoch) the old master never
            # reached: advance the counter AND draw its reshuffle
            self._finish_epoch()
        self.epoch_number = epoch
        self._global_offset = 0
        self.failed_minibatches = []
        self._pending_ = {}
        self.last_minibatch <<= False
        self.train_ended <<= False
        self.epoch_ended <<= False

    @staticmethod
    def init_parser(parser):
        parser.add_argument(
            "--train-ratio", type=float, default=1.0,
            help="fraction of the train set to use (ensembles)")
        return parser
