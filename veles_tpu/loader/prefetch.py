"""Async double-buffered input pipeline (ISSUE 8, ROADMAP item 4).

The fused step compiler eliminated per-step host traffic for datasets
that fit in device memory — but only for those. This module supplies
the other half of ROADMAP item 4:

* :class:`PrefetchPipeline` — a bounded-depth background pipeline.
  Worker threads run host ETL (``fill_minibatch``-style row gathers)
  and issue the host→device transfer for shard N+k while the step
  thread computes shard N, so the step thread's input wait collapses
  to the pipeline's warm fill plus whatever ETL cannot be hidden
  behind compute (the libhclooc out-of-core overlap pattern,
  PAPERS.md). Depth is ``VELES_PREFETCH`` (default 2 =
  double-buffered; 0 reproduces the synchronous path exactly).

* :class:`StagingRing` — a small ring of device staging slots the
  transfers land in. Residency is bounded to ``depth + 2`` shards
  (the one in compute, the queued ones, one being placed); a slot's
  previous occupant is deleted deterministically when the slot is
  reused, so out-of-core streaming has a flat HBM footprint however
  long the epoch.

* residency planning — :func:`plan_residency` decides
  "device-resident when it fits, streamed when it doesn't" against
  the device budget (``VELES_DEVICE_BUDGET_MB`` override — the
  artificial cap the out-of-core tests and benches use — else a
  fraction of the device's reported ``bytes_limit``), and
  :func:`shard_batches` sizes the fixed shards (``VELES_SHARD_MB``).

Telemetry (docs/OBSERVABILITY.md): every consumer-side wait lands in
the ``veles_step_input_wait_ms`` histogram; per-segment starvation
fraction (wait / wall) is published as the
``veles_input_starvation_fraction`` gauge by the streamed drivers in
:mod:`veles_tpu.train.step`; ETL / transfer times ride
``veles_prefetch_etl_ms`` / ``veles_prefetch_h2d_ms`` and
``prefetch:*`` trace spans; the time to the first ready item is the
``pipeline_fill`` startup phase.

``VELES_ETL_THROTTLE_MS`` injects a per-shard host-ETL sleep — the
deliberately slow loader that ``scripts/input_bench.py`` and the perf
gate's overlap probe use to measure (not assert) the overlap win.
"""

import threading
import time
import weakref

import numpy

from veles_tpu.envknob import env_knob
from veles_tpu.telemetry import tracing

#: live pipelines (weak): the conftest session teardown closes any a
#: crashed test left running before the interpreter starts dying
_live_lock = threading.Lock()
_live = weakref.WeakSet()


def default_depth():
    """``VELES_PREFETCH`` (default 2; 0 = synchronous)."""
    return max(0, env_knob("VELES_PREFETCH", 2, parse=int,
                           on_error="default"))


def default_workers():
    """``VELES_PREFETCH_WORKERS`` ETL threads (default 1)."""
    return max(1, env_knob("VELES_PREFETCH_WORKERS", 1, parse=int,
                           on_error="default"))


def etl_throttle_s():
    """Injected per-shard ETL sleep (``VELES_ETL_THROTTLE_MS``) — the
    slow-loader simulation knob for benches/tests; 0 in production."""
    return max(0.0, env_knob("VELES_ETL_THROTTLE_MS", 0.0, parse=float,
                             on_error="default")) / 1e3


def _registry():
    from veles_tpu.telemetry.registry import get_registry
    return get_registry()


def input_wait_histogram():
    return _registry().histogram(
        "veles_step_input_wait_ms",
        "Step-thread wait for the next prefetched input shard")


def starvation_gauge():
    return _registry().gauge(
        "veles_input_starvation_fraction",
        "Input wait / wall fraction of the last streamed segment",
        labels=("phase",))


# -- the pipeline ------------------------------------------------------------


class PrefetchPipeline(object):
    """Ordered bounded-depth producer pipeline over ``n_items`` items.

    ``produce(i)`` runs on worker threads (host ETL + async H2D
    dispatch); the consumer calls :meth:`get` and receives items
    strictly in index order. At most ``depth`` produced-but-unconsumed
    items exist at any time, so device staging memory is bounded.

    A worker exception is delivered to the consumer: the :meth:`get`
    that reaches the failed index re-raises it (after closing the
    pipeline), so a broken loader fails the step loop loudly instead
    of hanging it. ``depth=0`` runs ``produce`` inline on the consumer
    thread — bit-identical to the pre-pipeline synchronous path, with
    the same telemetry (the wait IS the ETL+transfer time).
    """

    def __init__(self, produce, n_items, depth=None, workers=None,
                 name="input", wait_hist=None, fill_phase="pipeline_fill"):
        self.produce = produce
        self.n_items = int(n_items)
        self.depth = default_depth() if depth is None else max(0, depth)
        self.workers = default_workers() if workers is None \
            else max(1, workers)
        self.name = name
        self.wait_s = 0.0          #: cumulative consumer wait
        self.first_wait_s = None   #: warm fill (wait for item 0)
        self._cond = threading.Condition()
        self._results = {}         # index -> ("ok", item) | ("error", e)
        self._next_claim = 0
        self._next_get = 0
        self._stop = False
        self._threads = []
        # non-input consumers (the model-offload ring, ISSUE 17) keep
        # their waits out of the input-starvation accounting: they pass
        # their own histogram and opt out of the pipeline_fill phase
        self._wait_hist = (input_wait_histogram() if wait_hist is None
                           else wait_hist)
        self._fill_phase = fill_phase

    # -- worker side --------------------------------------------------------

    def start(self):
        if self.depth == 0 or self.n_items == 0:
            return self  # synchronous mode: no threads at all
        for k in range(min(self.workers, self.n_items)):
            t = threading.Thread(
                target=self._work, daemon=True,
                name="veles-prefetch-%s-%d" % (self.name, k))
            t.start()
            self._threads.append(t)
        with _live_lock:
            _live.add(self)
        return self

    def _work(self):
        while True:
            with self._cond:
                while (not self._stop and
                       self._next_claim < self.n_items and
                       self._next_claim - self._next_get >= self.depth):
                    self._cond.wait(0.1)
                if self._stop or self._next_claim >= self.n_items:
                    return
                i = self._next_claim
                self._next_claim += 1
            try:
                with tracing.span("prefetch:produce", index=i,
                                  pipeline=self.name):
                    out = ("ok", self.produce(i))
            except BaseException as e:  # delivered to the consumer
                out = ("error", e)
            with self._cond:
                self._results[i] = out
                self._cond.notify_all()
                if out[0] == "error":
                    # stop claiming new work; indices already claimed
                    # by other workers still complete, so the consumer
                    # reaches this error without gaps
                    self._next_claim = self.n_items

    # -- consumer side ------------------------------------------------------

    def get(self):
        """Next item in order. Returns ``(item, wait_s)``; re-raises a
        worker exception at its index."""
        i = self._next_get
        if i >= self.n_items:
            raise IndexError("pipeline of %d items exhausted"
                             % self.n_items)
        start = time.perf_counter()
        if self.depth == 0:
            try:
                payload = self.produce(i)
            finally:
                self._next_get = i + 1
            kind = "ok"
        else:
            with self._cond:
                while i not in self._results and not self._stop:
                    self._cond.wait(0.1)
                if i not in self._results:
                    raise RuntimeError(
                        "prefetch pipeline %r closed while the step "
                        "thread waited for item %d" % (self.name, i))
                kind, payload = self._results.pop(i)
                self._next_get = i + 1
                self._cond.notify_all()
        wait = time.perf_counter() - start
        self.wait_s += wait
        self._wait_hist.observe(wait * 1e3)
        tracing.add_complete("prefetch:wait", start, wait, index=i,
                             pipeline=self.name)
        if self.first_wait_s is None:
            self.first_wait_s = wait
            if self._fill_phase:
                from veles_tpu.telemetry import profiler
                profiler.record_phase(self._fill_phase, wait)
        if kind == "error":
            self.close()
            raise payload
        return payload, wait

    def __iter__(self):
        while self._next_get < self.n_items:
            yield self.get()[0]

    def close(self, timeout=10.0):
        """Stop the workers and join every pipeline thread."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._threads = [t for t in self._threads if t.is_alive()]
        with _live_lock:
            # a worker stuck past the join timeout keeps the pipeline
            # registered so shutdown_all() can retry before teardown
            if not self._threads:
                _live.discard(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False


def shutdown_all(timeout=10.0):
    """Close every live pipeline (conftest session teardown: worker
    threads must not outlive pytest into interpreter shutdown)."""
    with _live_lock:
        pipes = list(_live)
    for p in pipes:
        p.close(timeout)


# -- device staging ----------------------------------------------------------


class StagingRing(object):
    """Fixed ring of device staging slots for streamed shards.

    ``place()`` transfers a PYTREE of host arrays (a loader shard's
    ``(data, truth)`` tuple, or a model layer group's params/opt-state
    dicts — ISSUE 17) through the next slot and deletes the slot's
    previous occupant first, so at most ``slots`` shards are ever
    device-resident — the flat-HBM guarantee out-of-core streaming
    depends on. ``placer`` maps one host LEAF to its device form
    (plain ``device_put``, a ``NamedSharding`` placement for
    data-parallel meshes, or the measured ``reshard.host_placer``).
    """

    def __init__(self, slots, placer):
        self._lock = threading.Lock()
        self._slots = [None] * max(1, int(slots))
        self._pos = 0
        self._placer = placer
        self._closed = False

    @staticmethod
    def _delete(arrays):
        import jax
        for arr in jax.tree_util.tree_leaves(arrays):
            try:
                # PJRT defers the actual free until in-flight executions
                # using the buffer complete, so deleting here (while the
                # previous shard may still be computing) is safe — the
                # residency BOUND is what this ring guarantees
                arr.delete()
            except Exception:
                pass  # already consumed/deleted: bound still holds

    def place(self, host_arrays):
        with self._lock:
            idx = self._pos % len(self._slots)
            self._pos += 1
            old = self._slots[idx]
            self._slots[idx] = None
        if old is not None:
            self._delete(old)
        import jax
        placed = jax.tree_util.tree_map(self._placer, host_arrays)
        with self._lock:
            if self._closed:
                # clear() raced an in-flight place (a worker past its
                # join timeout): don't re-insert into the emptied ring
                # — drop our own shard so shutdown's residency promise
                # holds; the (dead) consumer never uses it
                drop, placed_slot = placed, None
            else:
                drop, placed_slot = None, placed
                self._slots[idx] = placed_slot
        if drop is not None:
            self._delete(drop)
        return placed

    def reopen(self):
        """Accept placements again after a :meth:`clear` (a trainer
        reused across runs reopens its ring per segment)."""
        with self._lock:
            self._closed = False

    def clear(self):
        with self._lock:
            self._closed = True
            slots, self._slots = self._slots, [None] * len(self._slots)
        for old in slots:
            if old is not None:
                self._delete(old)


def default_placer(device=None):
    """Host ndarray -> committed ``jax.Array`` (async on TPU)."""
    import jax
    if device is not None and getattr(device, "is_jax", False):
        return device.put
    return jax.device_put


def sharded_placer(sharding, n_shards):
    """Host rows -> addressable per-device shards of a data-axis
    ``NamedSharding`` (ISSUE 15): THE pad-and-place implementation the
    GSPMD/data-parallel trainers hand the staging ring — streamed
    shards of the global batch land directly on their owning devices
    with no gather-then-scatter hop, the sample dim padded with zero
    rows to divide the axis (local shard indices never reach the pad
    rows). Placement goes through the measured reshard primitive, so
    per-shard H2D shows up as ``veles_reshard_ms{src="host"}``
    alongside ``veles_prefetch_h2d_ms``."""

    def place(host_array):
        pad = -host_array.shape[0] % n_shards
        if pad:
            host_array = numpy.concatenate([
                host_array,
                numpy.zeros((pad,) + host_array.shape[1:],
                            host_array.dtype)])
        from veles_tpu.parallel import reshard
        return reshard.reshard(host_array, sharding)
    return place


def warmup_ring(slots=2, device=None):
    """A small :class:`StagingRing` for serving-replica warm-up.

    The replica bucket sweep (``serving/replica.py``) stages each
    bucket's zeros through this ring instead of materializing every
    bucket on device at once: two slots bound the sweep's HBM
    footprint to the two largest consecutive buckets, and on real
    accelerators the async ``device_put`` overlaps the previous
    bucket's compile — the same double-buffering the training input
    pipeline uses, reused as the H2D path for serving cold starts
    (ROADMAP item 4, serving half)."""
    return StagingRing(slots, default_placer(device))


# -- residency planning ------------------------------------------------------


def device_budget_bytes(device=None):
    """Bytes of device memory the DATASET may occupy resident.

    ``VELES_DEVICE_BUDGET_MB`` wins (the artificial cap out-of-core
    tests/benches set; ``0``/empty = unknown); else 60% of the
    device's reported ``bytes_limit`` (params, activations and XLA
    scratch need the rest); else None (unknown — stay resident, the
    pre-pipeline behavior)."""
    mb = env_knob("VELES_DEVICE_BUDGET_MB", parse=float,
                  on_error="default")
    if mb is not None:
        return mb * 1e6 if mb > 0 else None
    stats = {}
    try:
        if device is not None and getattr(device, "is_jax", False):
            stats = device.memory_stats or {}
        else:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    limit = stats.get("bytes_limit")
    return 0.6 * limit if limit else None


def plan_residency(dataset_bytes, device=None, force=None):
    """``"resident"`` or ``"streamed"`` for a dataset of
    ``dataset_bytes``.

    ``force`` (or ``VELES_STREAM``: ``1``/``force``/``on`` stream
    always, ``0``/``off``/``no`` never; anything else is ignored and
    the budget decides) overrides the budget comparison."""
    if force is None:
        env = env_knob("VELES_STREAM")
        if env in ("1", "force", "on", "yes", "true"):
            force = True
        elif env in ("0", "off", "no", "false"):
            force = False
    if force is not None:
        return "streamed" if force else "resident"
    budget = device_budget_bytes(device)
    if budget is not None and dataset_bytes > budget:
        return "streamed"
    return "resident"


def shard_batches(batch_bytes, depth=None, budget_bytes=None):
    """Minibatches per fixed-size streamed shard.

    Targets ``VELES_SHARD_MB`` (default 256) per shard, shrunk so the
    ring's ``depth + 2`` resident shards still fit the device budget
    when one is known."""
    target = env_knob("VELES_SHARD_MB", 256.0, parse=float,
                      on_error="default") * 1e6
    depth = default_depth() if depth is None else depth
    if budget_bytes:
        target = min(target, budget_bytes / (depth + 2))
    return max(1, int(target // max(1, batch_bytes)))


# -- host ETL ----------------------------------------------------------------


def gather_rows(data, truth, indices):
    """``fill_minibatch``-style host ETL for one shard: gather rows of
    ``data``/``truth`` by global sample index.

    Matches the on-device gather's padding contract exactly
    (:meth:`FusedTrainer._gather`): index −1 produces a ZERO data row;
    truth is taken at ``max(idx, 0)`` and masked later by the loss
    math. Pure function over host arrays — safe from worker threads.
    """
    throttle = etl_throttle_s()
    if throttle:
        time.sleep(throttle)
    indices = numpy.asarray(indices).reshape(-1)
    safe = numpy.maximum(indices, 0)
    rows = data[safe]  # fancy index: always a fresh writable copy
    invalid = indices < 0
    if invalid.any():
        rows[invalid] = 0
    return rows, truth[safe]


def local_indices(global_idx):
    """Shard-local index matrix for a shard built by
    :func:`gather_rows`: row i of the shard replaces global sample
    ``global_idx.flat[i]``, pads stay −1 so the in-scan valid mask
    (and therefore the loss math) is unchanged."""
    global_idx = numpy.asarray(global_idx)
    flat = global_idx.reshape(-1)
    local = numpy.where(flat < 0, -1,
                        numpy.arange(flat.size)).astype(numpy.int32)
    return local.reshape(global_idx.shape)
