"""Pickled-arrays loader (re-designs ``veles/loader/pickles.py``).

Each class file is a pickle of either ``(data, labels)`` or just
``data`` (numpy arrays). Staged into the device-resident full batch.
"""

import pickle

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader


class PicklesLoader(FullBatchLoader):
    """test_path/validation_path/train_path pickles → full batch."""

    def __init__(self, workflow, **kwargs):
        self.test_path = kwargs.pop("test_path", None)
        self.validation_path = kwargs.pop("validation_path", None)
        self.train_path = kwargs.pop("train_path", None)
        super(PicklesLoader, self).__init__(workflow, **kwargs)

    def _read(self, path):
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if isinstance(blob, tuple) and len(blob) == 2:
            data, labels = blob
            return (numpy.asarray(data, numpy.float32),
                    numpy.asarray(labels, numpy.int32))
        return numpy.asarray(blob, numpy.float32), None

    def load_dataset(self):
        data_parts, label_parts = [], []
        for klass, path in enumerate((self.test_path,
                                      self.validation_path,
                                      self.train_path)):
            if path is None:
                continue
            data, labels = self._read(path)
            self.class_lengths[klass] = len(data)
            data_parts.append(data)
            if labels is not None:
                label_parts.append(labels)
        if not data_parts:
            raise ValueError("%s: no pickle paths given" % self.name)
        self.original_data.reset(numpy.concatenate(data_parts))
        if label_parts and len(label_parts) != len(data_parts):
            # labels gather by global sample index: a partial label set
            # would silently misalign classes against samples
            raise ValueError(
                "%s: %d of %d class files carry labels — need all or "
                "none" % (self.name, len(label_parts), len(data_parts)))
        if label_parts:
            self.original_labels.reset(numpy.concatenate(label_parts))
        else:
            self.has_labels = False
