"""Pickled-arrays loader (re-designs ``veles/loader/pickles.py``).

Each class file is a pickle of either ``(data, labels)`` or just
``data`` (numpy arrays). Staged into the device-resident full batch.
"""

import pickle

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader


class PicklesLoader(FullBatchLoader):
    """test_path/validation_path/train_path pickles → full batch."""

    def __init__(self, workflow, **kwargs):
        self.test_path = kwargs.pop("test_path", None)
        self.validation_path = kwargs.pop("validation_path", None)
        self.train_path = kwargs.pop("train_path", None)
        super(PicklesLoader, self).__init__(workflow, **kwargs)

    def _read(self, path):
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if isinstance(blob, tuple) and len(blob) == 2:
            data, labels = blob
            return (numpy.asarray(data, numpy.float32),
                    numpy.asarray(labels, numpy.int32))
        return numpy.asarray(blob, numpy.float32), None

    def load_dataset(self):
        self.load_class_files(
            (self.test_path, self.validation_path, self.train_path),
            self._read, kind="pickle")
