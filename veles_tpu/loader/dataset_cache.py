"""On-disk cache for generated benchmark datasets.

ROADMAP item 4's first half: BENCH_r05 spends 86-107 s in ``loader
init (generation)`` against a 30 s timed window, so every tuning
iteration pays ~3x its measurement time in synthetic-data generation.
Generation is deterministic from its config (sizes, seed, dtype), so
the arrays are cached to disk keyed by a hash of that config and a
schema version: any config change produces a different hash, which IS
the invalidation. Files live under the veles cache dir
(:func:`veles_tpu.backends.veles_cache_dir`), sibling to the XLA
compile cache and the kernel-autotune database.

Layout: one directory per dataset, ``datasets/<name>-<hash12>/``
holding ``meta.json`` plus one raw little-endian ``.bin`` per array
(``tofile``/``fromfile`` — npz cannot hold bfloat16 and would buffer
the ~5 GB flagship set through zlib). A partially-written cache is
impossible to observe: arrays land in a ``.tmp-<pid>`` directory that
is renamed into place only after ``meta.json`` (written last) is
complete, and any load error falls back to regeneration.

``VELES_DATASET_CACHE=0`` disables (generation always runs);
``VELES_DATASET_CACHE=rw`` (default) reads and writes.
"""

import hashlib
import json
import logging
import os
import shutil

import numpy

from veles_tpu.envknob import env_flag

#: bump to invalidate every cached dataset at once
CACHE_VERSION = 1

_log = logging.getLogger("dataset_cache")


def enabled():
    return env_flag("VELES_DATASET_CACHE", True)


def config_hash(config):
    """Stable short hash of a JSON-able config dict."""
    blob = json.dumps({"version": CACHE_VERSION, "config": config},
                      sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _dataset_dir(name, config):
    from veles_tpu.backends import veles_cache_dir
    return os.path.join(veles_cache_dir("datasets"),
                        "%s-%s" % (name, config_hash(config)))


def _dtype_of(spec):
    """dtype string -> numpy dtype, accepting ml_dtypes names
    (bfloat16) that ``numpy.dtype`` alone rejects."""
    try:
        return numpy.dtype(spec)
    except TypeError:
        import ml_dtypes
        return numpy.dtype(getattr(ml_dtypes, spec))


def _load(path):
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("version") != CACHE_VERSION:
        raise ValueError("stale schema %r" % meta.get("version"))
    arrays = {}
    for name, spec in meta["arrays"].items():
        dtype = _dtype_of(spec["dtype"])
        shape = tuple(spec["shape"])
        arr = numpy.fromfile(os.path.join(path, name + ".bin"),
                             dtype=numpy.uint8)
        arrays[name] = arr.view(dtype).reshape(shape)
    return arrays


def _sweep_stale_tmp(path):
    """Remove ``.tmp-<pid>`` staging dirs abandoned by dead processes
    (a kill/OOM mid-store would otherwise leak the ~5 GB flagship set
    per crashed run). A pid that is still alive keeps its dir."""
    base = os.path.dirname(path)
    for entry in os.listdir(base):
        full = os.path.join(base, entry)
        if ".tmp-" not in entry or not os.path.isdir(full):
            continue
        try:
            pid = int(entry.rsplit(".tmp-", 1)[1])
        except ValueError:
            pid = -1
        try:
            if pid > 0:
                os.kill(pid, 0)  # alive: writer still at work
                continue
        except ProcessLookupError:
            pass  # no such process: orphan
        except OSError:
            continue  # EPERM etc.: alive but not ours — keep it
        _log.info("removing orphaned dataset staging dir %s", full)
        shutil.rmtree(full, ignore_errors=True)


def _store(path, arrays):
    _sweep_stale_tmp(path)
    tmp = "%s.tmp-%d" % (path, os.getpid())
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    meta = {"version": CACHE_VERSION, "arrays": {}}
    for name, arr in arrays.items():
        arr = numpy.ascontiguousarray(arr)
        arr.view(numpy.uint8).tofile(os.path.join(tmp, name + ".bin"))
        meta["arrays"][name] = {"dtype": str(arr.dtype),
                                "shape": list(arr.shape)}
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    shutil.rmtree(path, ignore_errors=True)
    try:
        os.replace(tmp, path)
    except OSError:
        # a concurrent process won the rename; its arrays equal ours
        shutil.rmtree(tmp, ignore_errors=True)


def cached_build(name, config, builder):
    """``builder() -> {name: ndarray}``, memoized on disk.

    Cache hit: the arrays are read back (no generation). Miss or any
    load failure: ``builder`` runs and its output is persisted for the
    next process. With the cache disabled the builder always runs and
    nothing is written.
    """
    from veles_tpu.telemetry import profiler
    if not enabled():
        with profiler.phase("dataset_generate"):
            return builder()
    path = _dataset_dir(name, config)
    if os.path.isdir(path):
        try:
            with profiler.phase("dataset_load"):
                arrays = _load(path)
            _log.info("dataset cache hit: %s", path)
            return arrays
        except Exception as e:  # corrupt cache == miss, regenerate
            _log.warning("ignoring unreadable dataset cache %s (%s: %s)",
                         path, type(e).__name__, e)
    with profiler.phase("dataset_generate"):
        arrays = builder()
    try:
        _store(path, arrays)
        _log.info("dataset cache store: %s", path)
    except OSError as e:
        _log.warning("dataset cache store failed for %s (%s)", path, e)
    return arrays
