"""Data layer: minibatch-serving loader hierarchy (SURVEY.md §2.3).

``Loader`` is the base minibatch server (classes, shuffling, epochs,
fault-tolerant requeue); ``FullBatchLoader`` keeps the whole dataset
device-resident and gathers minibatches on-chip; image/hdf5/pickles/
interactive/restful variants layer on top.
"""

from veles_tpu.loader.base import (CLASS_NAMES, TEST, TRAIN, VALIDATION,  # noqa
                                   Loader, UserLoaderRegistry)
from veles_tpu.loader.fullbatch import (FullBatchLoader,  # noqa: F401
                                        FullBatchLoaderMSE)
from veles_tpu.loader.ensemble import EnsembleLoader  # noqa: F401
from veles_tpu.loader.hdf5 import HDF5Loader  # noqa: F401
from veles_tpu.loader.hdfs import HDFSLoader  # noqa: F401
from veles_tpu.loader.image import (AutoLabelFileImageLoader,  # noqa: F401
                                    FileImageLoader, ImageLoaderMSE)
from veles_tpu.loader.interactive import (InteractiveLoader,  # noqa: F401
                                          QueueFedLoader)
from veles_tpu.loader.pickles import PicklesLoader  # noqa: F401
from veles_tpu.loader.restful import RestfulLoader  # noqa: F401
from veles_tpu.loader.saver import (MinibatchesLoader,  # noqa: F401
                                    MinibatchesSaver)
from veles_tpu.loader.sound import SndFileLoader  # noqa: F401
