"""Ensemble-results loader (``veles/loader/ensemble.py``).

Feeds the stacked per-member predictions from an ensemble results JSON
(``EnsembleTester`` output, each member carrying ``Output`` and
``Labels`` lists) as the dataset of a stacking meta-model: sample #i is
the ``(n_members, n_classes)`` matrix of member outputs for input #i.

Label handling at parity with the reference (``loader/ensemble.py:100+``):
the first member's labels define the mapping; members whose labels
disagree in order but not in content get their output columns remapped,
members with different label *sets* are an error.
"""

import json

import numpy

from veles_tpu.loader.base import TEST, TRAIN, VALIDATION
from veles_tpu.loader.fullbatch import FullBatchLoader


class EnsembleLoader(FullBatchLoader):
    """Member predictions from a results JSON as a device-resident batch."""

    MAPPING = "ensemble"

    def __init__(self, workflow, **kwargs):
        self.file = kwargs.pop("file", None)
        self.data = kwargs.pop("data", None)  # already-parsed (tests)
        super(EnsembleLoader, self).__init__(workflow, **kwargs)

    def _read(self):
        if self.data is not None:
            return self.data
        if not self.file:
            raise ValueError("EnsembleLoader needs file= or data=")
        with open(self.file) as f:
            return json.load(f)

    def load_dataset(self):
        data = self._read()
        members = [m for m in data.get("models", []) if isinstance(m, dict)]
        if not members:
            raise ValueError("no member results in %s" % (self.file,))
        outputs, labels_ref = [], None
        for index, member in enumerate(members):
            if "Output" not in member:
                raise ValueError("member #%d has no recorded Output "
                                 "(train members with publish_output=True)"
                                 % index)
            output = numpy.asarray(member["Output"], dtype=numpy.float32)
            labels = member.get("Labels")
            if output.shape[0] == 0:
                raise ValueError("member #%d recorded an empty Output"
                                 % index)
            if outputs and output.shape != outputs[0].shape:
                raise ValueError(
                    "member #%d output shape %s != member #0 shape %s" %
                    (index, output.shape, outputs[0].shape))
            if labels is not None:
                labels = numpy.asarray(labels)
                if labels_ref is None:
                    labels_ref = labels
                elif not numpy.array_equal(labels, labels_ref):
                    raise ValueError(
                        "member #%d saw samples in a different order — "
                        "re-run member tests with a fixed seed" % index)
            outputs.append(output)
        stacked = numpy.stack(outputs, axis=1)  # (n, members, classes)
        self.original_data.reset(stacked)
        if labels_ref is not None:
            self.original_labels.reset(
                labels_ref.astype(numpy.int32).reshape(len(labels_ref)))
        klass = TEST if self.testing else TRAIN
        self.class_lengths[TEST] = self.class_lengths[VALIDATION] = \
            self.class_lengths[TRAIN] = 0
        self.class_lengths[klass] = stacked.shape[0]

    @property
    def testing(self):
        launcher = self.launcher
        return bool(getattr(launcher, "testing", False))
