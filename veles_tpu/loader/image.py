"""Image loaders: directory scanning, decoding, labels from paths.

Re-designs the reference's PIL-based image loader family
(``veles/loader/image.py``, ``veles/loader/file_image.py:150``,
``veles/loader/fullbatch_image.py``). The reference streamed images per
minibatch through host RAM; on TPU the right shape is the opposite —
decode once at initialize time into the device-resident full batch
(HBM), then the hot loop is pure on-device gather (no PIL, no host
traffic). Augmentation that the reference did per-sample on the host
(mirror/crop) is applied at staging time.

PIL is an optional dependency: importing this module without it raises
only when a loader is actually used.
"""

import os
import re

import numpy

from veles_tpu.loader.base import TEST, TRAIN, VALIDATION
from veles_tpu.loader.file_scanner import LabeledFileScanner
from veles_tpu.loader.fullbatch import FullBatchLoader, FullBatchLoaderMSE

#: file extensions accepted by the directory scanners
IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif",
                    ".tiff", ".webp")


def _pil():
    try:
        from PIL import Image
    except ImportError:
        raise ImportError(
            "image loaders need Pillow (PIL); it is not installed")
    return Image


def decode_image(path, size=None, color="RGB"):
    """Decode one image file → float32 HWC array in [0, 1]."""
    Image = _pil()
    with Image.open(path) as img:
        img = img.convert(color)
        if size is not None:
            img = img.resize((size[1], size[0]), Image.BILINEAR)
        arr = numpy.asarray(img, dtype=numpy.float32) / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


class ImageAugmenter(object):
    """Staging-time augmentation (``veles/loader/image.py:444-567``
    re-designed for the device-resident full batch).

    The reference distorted per minibatch on the host (cv2 warpAffine,
    random crops around a bbox, mirror variants, rotation set); here
    every variant is materialized ONCE at load time into the full
    batch, so the training loop stays a pure on-device gather. TRAIN
    samples multiply by ``len(rotations) × mirror-factor ×
    crop_number``; eval classes get the deterministic center variant
    (rotation 0, no flip, center crop) so shapes match.

    * ``scale``: float ratio (bilinear resize) or ``(h, w)`` target;
    * ``crop``: ``(h, w)`` ints or floats (fraction of the scaled
      shape); train crops are uniform-random, eval crops centered;
    * ``crop_number``: random crops per train variant;
    * ``mirror``: False | True (every variant also flipped) |
      ``"random"`` (each variant flips with p=0.5);
    * ``rotations``: radians, each multiplies the train set.

    Randomness comes from the seeded PRNG registry (snapshot-
    preserved), so staging is reproducible.
    """

    def __init__(self, crop=None, crop_number=1, scale=1.0,
                 rotations=(0.0,), mirror=False, rand="loader"):
        if mirror not in (False, True, "random"):
            raise ValueError("mirror must be False, True or 'random'")
        self.crop = tuple(crop) if crop is not None else None
        self.crop_number = int(crop_number)
        self.scale = scale
        self.rotations = tuple(rotations)
        self.mirror = mirror
        self.rand_name = rand

    @classmethod
    def pop_from_kwargs(cls, kwargs):
        """Build from (and consume) the loader-ctor kwargs — the one
        place the kwarg spelling lives for every image loader."""
        augmenter = kwargs.pop("augmenter", None)
        if augmenter is not None:
            return augmenter
        return cls(crop=kwargs.pop("crop", None),
                   crop_number=kwargs.pop("crop_number", 1),
                   scale=kwargs.pop("scale", 1.0),
                   rotations=kwargs.pop("rotations", (0.0,)),
                   mirror=kwargs.pop("mirror", False))

    def _rng(self):
        from veles_tpu import prng
        return prng.get(self.rand_name)

    def _scaled(self, img):
        from scipy import ndimage
        if self.scale == 1.0:
            return img
        if isinstance(self.scale, tuple):
            zoom = (self.scale[0] / img.shape[0],
                    self.scale[1] / img.shape[1], 1.0)
        else:
            zoom = (self.scale, self.scale, 1.0)
        return ndimage.zoom(img, zoom, order=1).astype(numpy.float32)

    def _crop_shape(self, shape):
        if self.crop is None:
            return None
        cs = tuple(int(c if isinstance(c, int) else round(c * s))
                   for c, s in zip(self.crop, shape[:2]))
        if cs[0] > shape[0] or cs[1] > shape[1] or min(cs) < 1:
            # fail with the configuration error, not a cryptic
            # numpy.stack shape mismatch (or a silent short slice)
            raise ValueError(
                "crop %s does not fit the scaled image shape %s" %
                (cs, tuple(shape[:2])))
        return cs

    def _cut(self, img, oy, ox, ch, cw):
        return img[oy:oy + ch, ox:ox + cw]

    def _rotated(self, img, rot):
        if not rot:
            return img
        from scipy import ndimage
        return ndimage.rotate(img, rot * 180.0 / numpy.pi, order=1,
                              reshape=False, mode="constant",
                              cval=0.0).astype(numpy.float32)

    def _variant_params(self, shape, train):
        """Draw the variant parameter list ``[(rot, flip, oy, ox)]``
        for one image of (scaled) ``shape`` — separated from the pixel
        work so input/target PAIRS can share identical draws."""
        cs = self._crop_shape(shape)
        if not train:
            if cs is None:
                return [(0.0, False, 0, 0)], cs
            return [(0.0, False, (shape[0] - cs[0]) // 2,
                     (shape[1] - cs[1]) // 2)], cs
        rng = self._rng()
        params = []
        for rot in self.rotations:
            if self.mirror is True:
                flips = (False, True)
            elif self.mirror == "random":
                flips = (bool(rng.randint(2)),)
            else:
                flips = (False,)
            for flip in flips:
                if cs is None:
                    params.append((rot, flip, 0, 0))
                    continue
                max_oy = shape[0] - cs[0]
                max_ox = shape[1] - cs[1]
                for _ in range(self.crop_number):
                    oy = rng.randint(max_oy + 1) if max_oy > 0 else 0
                    ox = rng.randint(max_ox + 1) if max_ox > 0 else 0
                    params.append((rot, flip, oy, ox))
        return params, cs

    def _apply_variant(self, img, rot, flip, oy, ox, cs):
        out = self._rotated(img, rot)
        if flip:
            out = out[:, ::-1]
        if cs is not None:
            out = self._cut(out, oy, ox, *cs)
        return numpy.ascontiguousarray(out)

    def expand(self, img, train):
        """One decoded image → list of augmented variants."""
        img = self._scaled(img)
        params, cs = self._variant_params(img.shape, train)
        return [self._apply_variant(img, *p, cs) for p in params]

    def expand_pair(self, img, target, train):
        """Input/target pairs (image→image regression) get IDENTICAL
        variant parameters, so crops and flips stay aligned."""
        img = self._scaled(img)
        target = self._scaled(target)
        params, cs = self._variant_params(img.shape, train)
        return ([self._apply_variant(img, *p, cs) for p in params],
                [self._apply_variant(target, *p, cs) for p in params])


class ImageScanner(LabeledFileScanner):
    """Image-extension scan; labels from parent directory names."""

    def __init__(self, ignored_dirs=(), filename_re=None):
        super(ImageScanner, self).__init__(
            IMAGE_EXTENSIONS, ignored_dirs=ignored_dirs,
            filename_re=filename_re)


class FileImageLoader(FullBatchLoader):
    """Scans test/validation/train directory trees into a device-resident
    full batch; labels from directory names (``file_image.py:150``)."""

    def __init__(self, workflow, **kwargs):
        self.test_paths = tuple(kwargs.pop("test_paths", ()))
        self.validation_paths = tuple(kwargs.pop("validation_paths", ()))
        self.train_paths = tuple(kwargs.pop("train_paths", ()))
        self.size = kwargs.pop("size", None)        # (H, W) resize target
        self.color_space = kwargs.pop("color_space", "RGB")
        self.filename_re = kwargs.pop("filename_re", None)
        self.ignored_dirs = kwargs.pop("ignored_dirs", ())
        self.augmenter = ImageAugmenter.pop_from_kwargs(kwargs)
        super(FileImageLoader, self).__init__(workflow, **kwargs)
        self.labels_mapping = {}

    def _scan_class(self, paths):
        scanner = ImageScanner(self.ignored_dirs, self.filename_re)
        pairs = []
        for base in paths:
            pairs.extend(scanner.scan(base))
        return pairs

    def load_dataset(self):
        per_class = [self._scan_class(p) for p in
                     (self.test_paths, self.validation_paths,
                      self.train_paths)]
        names = sorted({label for pairs in per_class
                        for _, label in pairs})
        self.labels_mapping = {name: i for i, name in enumerate(names)}
        if not any(per_class):
            raise ValueError("%s found no images" % self.name)
        if self.size is None:
            # infer from the first image so all samples stack
            first = next(p for pairs in per_class for p, _ in pairs)
            self.size = decode_image(first, color=self.color_space
                                     ).shape[:2]
        data, labels = [], []
        for klass, pairs in enumerate(per_class):
            count = 0
            for path, label in pairs:
                img = decode_image(path, self.size, self.color_space)
                for variant in self.augmenter.expand(
                        img, train=klass == TRAIN):
                    data.append(variant)
                    labels.append(self.labels_mapping[label])
                    count += 1
            self.class_lengths[klass] = count
        self.original_data.reset(numpy.stack(data).astype(numpy.float32))
        self.original_labels.reset(numpy.asarray(labels, numpy.int32))

    @property
    def n_classes(self):
        return len(self.labels_mapping)


class AutoLabelFileImageLoader(FileImageLoader):
    """Labels extracted from the FILE name by a regex capture group
    (the reference's FullBatchAutoLabelFileImageLoader)."""

    def __init__(self, workflow, **kwargs):
        self.label_regexp = re.compile(kwargs.pop("label_regexp"))
        super(AutoLabelFileImageLoader, self).__init__(workflow, **kwargs)

    def _scan_class(self, paths):
        pairs = super(AutoLabelFileImageLoader, self)._scan_class(paths)
        relabeled = []
        for path, _ in pairs:
            match = self.label_regexp.search(os.path.basename(path))
            if match is None:
                continue
            relabeled.append((path, match.group(1)))
        return relabeled


class ImageLoaderMSE(FullBatchLoaderMSE):
    """Image → image regression (the reference's ``image_mse.py``):
    targets are images too, matched to inputs by index."""

    def __init__(self, workflow, **kwargs):
        self.test_paths = tuple(kwargs.pop("test_paths", ()))
        self.validation_paths = tuple(kwargs.pop("validation_paths", ()))
        self.train_paths = tuple(kwargs.pop("train_paths", ()))
        self.target_paths = tuple(kwargs.pop("target_paths", ()))
        self.size = kwargs.pop("size", None)
        self.color_space = kwargs.pop("color_space", "RGB")
        self.augmenter = ImageAugmenter.pop_from_kwargs(kwargs)
        super(ImageLoaderMSE, self).__init__(workflow, **kwargs)

    def load_dataset(self):
        scanner = ImageScanner()
        target_pool = []
        for base in self.target_paths:
            target_pool.extend(scanner.scan(base))
        per_class = []
        total = 0
        for paths in (self.test_paths, self.validation_paths,
                      self.train_paths):
            pairs = []
            for base in paths:
                pairs.extend(scanner.scan(base))
            per_class.append(pairs)
            total += len(pairs)
        if target_pool and len(target_pool) != total:
            # match-by-index needs equal counts: a silent wraparound
            # would mispair every input after the shorter list ends
            raise ValueError(
                "%s: %d target images for %d inputs — the index "
                "pairing requires equal counts" %
                (self.name, len(target_pool), total))
        data, targets = [], []
        index = 0
        for klass, pairs in enumerate(per_class):
            if pairs and self.size is None:
                self.size = decode_image(
                    pairs[0][0], color=self.color_space).shape[:2]
            count = 0
            for path, _ in pairs:
                img = decode_image(path, self.size, self.color_space)
                # target matched to the input by index (reference
                # image_mse convention); autoencoder convention when no
                # target tree: the input itself
                if target_pool:
                    # equal counts enforced above: each target file
                    # decodes exactly once
                    tgt = decode_image(target_pool[index][0], self.size,
                                       self.color_space)
                else:
                    tgt = img
                index += 1
                imgs, tgts = self.augmenter.expand_pair(
                    img, tgt, train=klass == TRAIN)
                data.extend(imgs)
                targets.extend(tgts)
                count += len(imgs)
            self.class_lengths[klass] = count
        self.original_data.reset(numpy.stack(data).astype(numpy.float32))
        self.has_labels = False
        self.original_targets.reset(
            numpy.stack(targets).astype(numpy.float32))
