"""Directory-tree scanner shared by the file-based loaders.

Collects ``(path, label_name)`` pairs where the label is the immediate
parent directory name — the reference's path-derived labeling
(``veles/loader/file_image.py``). Used by the image and sound loaders
with different extension sets.
"""

import os
import re


class LabeledFileScanner(object):
    """Deterministic recursive scan filtered by extension/regex."""

    def __init__(self, extensions, ignored_dirs=(), filename_re=None):
        self.extensions = tuple(ext.lower() for ext in extensions)
        self.ignored_dirs = set(ignored_dirs)
        self.filename_re = re.compile(filename_re) if filename_re else None

    def scan(self, base):
        if os.path.isfile(base):
            return [(base, os.path.basename(
                os.path.dirname(os.path.abspath(base))))]
        found = []
        # walk lazily: pruning via dirnames[:] only works on the live
        # generator (a sorted(os.walk(...)) would visit ignored dirs)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in self.ignored_dirs)
            for name in sorted(filenames):
                if not name.lower().endswith(self.extensions):
                    continue
                if self.filename_re and not self.filename_re.search(name):
                    continue
                found.append((os.path.join(dirpath, name),
                              os.path.basename(dirpath)))
        found.sort()
        return found
