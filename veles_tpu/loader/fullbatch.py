"""Device-resident full-batch loaders.

Re-designs ``veles/loader/fullbatch.py:79-566``: the entire dataset
lives in device memory (HBM ``jax.Array``); each minibatch is gathered
on-device by index (:func:`veles_tpu.ops.gather.gather_minibatch`), so
the host never touches sample data in the hot loop — the TPU analogue of
the reference's ``fill_minibatch_data_labels`` kernel.

Subclasses (or users) provide ``original_data``/``original_labels``
numpy arrays via :meth:`load_dataset`; ``FullBatchLoaderMSE`` adds
``original_targets`` for regression/autoencoder workflows.
"""

import numpy

from veles_tpu.loader.base import Loader
from veles_tpu.memory import Array
from veles_tpu.normalization import NormalizerRegistry
from veles_tpu.ops.gather import gather_minibatch


class FullBatchLoader(Loader):
    """Whole dataset on device; on-device minibatch gather."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.normalization_type = kwargs.pop("normalization_type", "none")
        self.normalization_parameters = kwargs.pop(
            "normalization_parameters", {})
        super(FullBatchLoader, self).__init__(workflow, **kwargs)
        self.original_data = Array()
        self.original_labels = Array()
        self.normalizer = None

    # -- to provide --------------------------------------------------------

    def load_dataset(self):
        """Fill original_data/original_labels + class_lengths."""
        raise NotImplementedError

    def load_class_files(self, paths, reader, kind="data"):
        """Assemble the dataset from per-class files.

        ``paths`` = (test, validation, train) paths (None = absent
        class); ``reader(path) -> (data, labels-or-None)``. Shared by
        the pickle/HDF5 loaders; enforces the alignment rules: labels
        match their data length, and either every class file carries
        labels or none does (labels gather by global sample index — a
        partial label set would silently misalign classes).
        """
        data_parts, label_parts = [], []
        for klass, path in enumerate(paths):
            if path is None:
                continue
            data, labels = reader(path)
            self.class_lengths[klass] = len(data)
            data_parts.append(data)
            if labels is not None:
                if len(labels) != len(data):
                    raise ValueError(
                        "%s: %d labels for %d samples in %s" %
                        (self.name, len(labels), len(data), path))
                label_parts.append(labels)
        if not data_parts:
            raise ValueError("%s: no %s paths given" % (self.name, kind))
        if label_parts and len(label_parts) != len(data_parts):
            raise ValueError(
                "%s: %d of %d class files carry labels — need all or "
                "none" % (self.name, len(label_parts), len(data_parts)))
        self.original_data.reset(numpy.concatenate(data_parts))
        if label_parts:
            self.original_labels.reset(numpy.concatenate(label_parts))
        else:
            self.has_labels = False

    def load_data(self):
        if self.original_data.mem is not None:
            # restored from snapshot: data (already normalized) came
            # along in the pickle — do not re-load or re-normalize
            self.has_labels = self.original_labels.mem is not None
            return
        self.load_dataset()
        if self.original_data.mem is None:
            raise ValueError("%s.load_dataset left original_data empty" %
                             self.name)
        self.has_labels = self.original_labels.mem is not None
        self._normalize_data()

    def _normalize_data(self):
        self.normalizer = NormalizerRegistry.make(
            self.normalization_type, **self.normalization_parameters)
        if self.normalizer.is_identity:
            return
        data = self.original_data.map_write().astype(numpy.float32)
        train_start = self.class_end_offsets[1]  # after test+validation
        self.normalizer.analyze(data[train_start:])
        self.original_data.reset(self.normalizer.normalize(data))

    def create_minibatch_data(self):
        sample_shape = tuple(self.original_data.shape[1:])
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + sample_shape, numpy.float32))

    def initialize(self, device=None, **kwargs):
        super(FullBatchLoader, self).initialize(**kwargs)
        self.device = device
        for arr in (self.original_data, self.original_labels,
                    self.minibatch_data, self.minibatch_labels,
                    self.minibatch_indices):
            if isinstance(arr, Array) and arr.mem is not None \
                    and device is not None:
                arr.initialize(device)

    def fill_minibatch(self):
        self.minibatch_indices.unmap()
        data, labels = gather_minibatch(
            self.original_data.devmem, self.minibatch_indices.devmem,
            self.original_labels.devmem if self.has_labels else None)
        self.minibatch_data.assign_devmem(data)
        if labels is not None:
            self.minibatch_labels.assign_devmem(labels)

    # -- prefetchable fill (host backing for the async input pipeline) -----

    def host_backing(self, kind="labels"):
        """``(data, truth)`` host ndarray views of the full-batch
        backing store — what streamed (out-of-core) consumers gather
        shards from instead of forcing the dataset device-resident.
        ``kind`` selects ``labels`` or ``targets`` as truth."""
        truth = (self.original_labels if kind == "labels"
                 else getattr(self, "original_targets", None))
        if truth is None or truth.mem is None:
            raise ValueError("%s has no host-resident %s"
                             % (self.name, kind))
        return self.original_data.map_read(), truth.map_read()

    def fill_indices(self, indices, kind="labels"):
        from veles_tpu.loader.prefetch import gather_rows
        data, truth = self.host_backing(kind)
        return gather_rows(data, truth, indices)


class ProviderLoader(FullBatchLoader):
    """Full batch over a provider callable returning
    ``(train_x, train_y, valid_x, valid_y)`` — the one place that owns
    the valid-before-train layout, dtype casts and class lengths
    (MnistLoader and the sample loaders all build on it)."""

    hide_from_registry = True

    def __init__(self, workflow, provider=None, flatten=False,
                 sequence=False, **kwargs):
        super(ProviderLoader, self).__init__(workflow, **kwargs)
        self.provider = provider
        #: flat (n, features) for FC topologies; otherwise 3-D arrays
        #: grow a singleton channel for NHWC conv stacks
        self.flatten = flatten
        #: 3-D samples are (seq, dim) token sequences for attention
        #: stacks — keep them 3-D instead of growing an NHWC channel
        self.sequence = sequence

    def load_dataset(self):
        train_x, train_y, valid_x, valid_y = self.provider()
        data = numpy.concatenate([valid_x, train_x], axis=0).astype(
            numpy.float32)
        labels = numpy.concatenate([valid_y, train_y], axis=0).astype(
            numpy.int32)
        if self.flatten:
            data = data.reshape(len(data), -1)
        elif data.ndim == 3 and not self.sequence:
            data = data[..., None]  # NHWC single channel
        self.original_data.reset(data)
        self.original_labels.reset(labels)
        self.class_lengths = [0, len(valid_x), len(train_x)]


class FullBatchLoaderMSE(FullBatchLoader):
    """Adds per-sample regression targets (``fullbatch.py:563``)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.targets_normalization_type = kwargs.pop(
            "targets_normalization_type", "none")
        super(FullBatchLoaderMSE, self).__init__(workflow, **kwargs)
        self.original_targets = Array()
        self.minibatch_targets = Array()

    def load_data(self):
        super(FullBatchLoaderMSE, self).load_data()
        if self.original_targets.mem is None:
            raise ValueError("MSE loader needs original_targets")

    def create_minibatch_data(self):
        super(FullBatchLoaderMSE, self).create_minibatch_data()
        tshape = tuple(self.original_targets.shape[1:])
        self.minibatch_targets.reset(numpy.zeros(
            (self.max_minibatch_size,) + tshape, numpy.float32))

    def initialize(self, device=None, **kwargs):
        super(FullBatchLoaderMSE, self).initialize(device=device, **kwargs)
        for arr in (self.original_targets, self.minibatch_targets):
            if arr.mem is not None and device is not None:
                arr.initialize(device)

    def fill_minibatch(self):
        super(FullBatchLoaderMSE, self).fill_minibatch()
        targets, _ = gather_minibatch(self.original_targets.devmem,
                                      self.minibatch_indices.devmem)
        self.minibatch_targets.assign_devmem(targets)
