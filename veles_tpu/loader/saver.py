"""Minibatch stream recording and replay.

Re-designs ``veles/loader/saver.py:69,182``: ``MinibatchesSaver`` is a
unit plugged after any loader; every served minibatch (data, labels,
class, epoch flags) is appended to a compressed stream file. The
companion ``MinibatchesLoader`` replays that file later as a loader —
the reference's "preprocessed dataset" workflow: run the expensive
pipeline once, then train many times from the recording.

The reference framed with snappy; snappy is not in this environment, so
frames are gzip-compressed pickles with a length prefix (the format is
self-describing via the header record).
"""

import gzip
import os
import pickle
import struct

import numpy

from veles_tpu.loader.base import Loader
from veles_tpu.memory import Array
from veles_tpu.units import Unit

MAGIC = b"VTPUMB1\x00"


def _write_frame(f, obj):
    blob = gzip.compress(pickle.dumps(obj, protocol=4))
    f.write(struct.pack("<Q", len(blob)))
    f.write(blob)


def _read_frame(f):
    header = f.read(8)
    if len(header) < 8:
        return None
    (length,) = struct.unpack("<Q", header)
    return pickle.loads(gzip.decompress(f.read(length)))


class MinibatchesSaver(Unit):
    """Records every minibatch the linked loader serves."""

    view_group = "SERVICE"

    def __init__(self, workflow, **kwargs):
        self.file_name = kwargs.pop(
            "file_name", os.path.join(".", "minibatches.vtpu"))
        super(MinibatchesSaver, self).__init__(workflow, **kwargs)
        self.demand("minibatch_data", "minibatch_labels", "minibatch_size",
                    "minibatch_class", "last_minibatch", "epoch_ended",
                    "class_lengths", "max_minibatch_size")

    def initialize(self, **kwargs):
        self._file_ = open(self.file_name, "wb")
        self._file_.write(MAGIC)
        _write_frame(self._file_, {
            "class_lengths": list(self.class_lengths),
            "max_minibatch_size": int(self.max_minibatch_size),
        })
        from veles_tpu.workflow import Workflow
        if isinstance(self.workflow, Workflow):
            self.workflow.add_finished_callback(self.close)

    def run(self):
        data = self.minibatch_data
        labels = self.minibatch_labels
        size = int(self.minibatch_size)
        _write_frame(self._file_, {
            "data": numpy.asarray(
                data.map_read() if isinstance(data, Array) else data
            )[:size].copy(),
            "labels": None if labels is None else numpy.asarray(
                labels.map_read() if isinstance(labels, Array) else labels
            )[:size].copy(),
            "class": int(self.minibatch_class),
            "last": bool(self.last_minibatch),
            "epoch_ended": bool(self.epoch_ended),
        })

    def close(self):
        f = getattr(self, "_file_", None)
        if f is not None and not f.closed:
            f.close()


class MinibatchesLoader(Loader):
    """Replays a MinibatchesSaver recording as a loader."""

    def __init__(self, workflow, **kwargs):
        self.file_name = kwargs.pop("file_name", None)
        super(MinibatchesLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        with open(self.file_name, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ValueError("%s is not a minibatch recording" %
                                 self.file_name)
            header = _read_frame(f)
            self.class_lengths = list(header["class_lengths"])
            self.max_minibatch_size = int(header["max_minibatch_size"])
            # one epoch's worth of frames fully describes the dataset:
            # stitch them back into per-sample arrays so the standard
            # shuffling/serving machinery (and the on-device gather
            # path of subclasses) applies unchanged
            frames, seen = [], 0
            while seen < self.total_samples:
                frame = _read_frame(f)
                if frame is None:
                    break
                frames.append(frame)
                seen += len(frame["data"])
        if seen < self.total_samples:
            raise ValueError(
                "recording %s holds %d samples, header promises %d" %
                (self.file_name, seen, self.total_samples))
        # frames arrive in global serving order: test, validation, train
        self._data_cache_ = numpy.concatenate([f["data"] for f in frames])
        labels = [f["labels"] for f in frames]
        if all(lab is not None for lab in labels):
            self._labels_cache_ = numpy.concatenate(labels)
        else:
            self._labels_cache_ = None
            self.has_labels = False

    def create_minibatch_data(self):
        shape = (self.max_minibatch_size,) + self._data_cache_.shape[1:]
        self.minibatch_data.reset(numpy.zeros(shape, numpy.float32))

    def fill_minibatch(self):
        indices = self.minibatch_indices.map_read()
        mb = self.minibatch_data.map_invalidate()
        count = self.minibatch_size
        mb[:count] = self._data_cache_[indices[:count]]
        if self._labels_cache_ is not None:
            labels = self.minibatch_labels.map_invalidate()
            labels[:count] = self._labels_cache_[indices[:count]]
