"""HDF5 dataset loader (re-designs ``veles/loader/loader_hdf5.py``).

Each class (test/validation/train) comes from one ``.h5`` file holding
two datasets: ``data`` (N × sample shape) and ``labels`` (N,). Files are
read once at initialize and staged into the device-resident full batch.
h5py is optional: the import only happens when a file is actually read.
"""

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader


class HDF5Loader(FullBatchLoader):
    """test_path/validation_path/train_path → device-resident batch."""

    DATA_DATASET = "data"
    LABELS_DATASET = "labels"

    def __init__(self, workflow, **kwargs):
        self.test_path = kwargs.pop("test_path", None)
        self.validation_path = kwargs.pop("validation_path", None)
        self.train_path = kwargs.pop("train_path", None)
        super(HDF5Loader, self).__init__(workflow, **kwargs)

    def _read(self, path):
        try:
            import h5py
        except ImportError:
            raise ImportError("HDF5Loader needs h5py; it is not installed")
        with h5py.File(path, "r") as f:
            data = numpy.asarray(f[self.DATA_DATASET], numpy.float32)
            labels = None
            if self.LABELS_DATASET in f:
                labels = numpy.asarray(f[self.LABELS_DATASET],
                                       numpy.int32)
        return data, labels

    def load_dataset(self):
        self.load_class_files(
            (self.test_path, self.validation_path, self.train_path),
            self._read, kind="HDF5")
