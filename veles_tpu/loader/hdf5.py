"""HDF5 dataset loader (re-designs ``veles/loader/loader_hdf5.py``).

Each class (test/validation/train) comes from one ``.h5`` file holding
two datasets: ``data`` (N × sample shape) and ``labels`` (N,). Files are
read once at initialize and staged into the device-resident full batch.
h5py is optional: the import only happens when a file is actually read.
"""

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader


class HDF5Loader(FullBatchLoader):
    """test_path/validation_path/train_path → device-resident batch."""

    DATA_DATASET = "data"
    LABELS_DATASET = "labels"

    def __init__(self, workflow, **kwargs):
        self.test_path = kwargs.pop("test_path", None)
        self.validation_path = kwargs.pop("validation_path", None)
        self.train_path = kwargs.pop("train_path", None)
        super(HDF5Loader, self).__init__(workflow, **kwargs)

    def _read(self, path):
        try:
            import h5py
        except ImportError:
            raise ImportError("HDF5Loader needs h5py; it is not installed")
        with h5py.File(path, "r") as f:
            data = numpy.asarray(f[self.DATA_DATASET], numpy.float32)
            labels = None
            if self.LABELS_DATASET in f:
                labels = numpy.asarray(f[self.LABELS_DATASET],
                                       numpy.int32)
        return data, labels

    def load_dataset(self):
        data_parts, label_parts = [], []
        for klass, path in enumerate((self.test_path,
                                      self.validation_path,
                                      self.train_path)):
            if path is None:
                continue
            data, labels = self._read(path)
            self.class_lengths[klass] = len(data)
            data_parts.append(data)
            if labels is not None:
                if len(labels) != len(data):
                    raise ValueError(
                        "%s: %d labels for %d samples in %s" %
                        (self.name, len(labels), len(data), path))
                label_parts.append(labels)
        if not data_parts:
            raise ValueError("%s: no HDF5 paths given" % self.name)
        self.original_data.reset(numpy.concatenate(data_parts))
        if label_parts and len(label_parts) != len(data_parts):
            # labels gather by global sample index: a partial label set
            # would silently misalign classes against samples
            raise ValueError(
                "%s: %d of %d class files carry labels — need all or "
                "none" % (self.name, len(label_parts), len(data_parts)))
        if label_parts:
            self.original_labels.reset(numpy.concatenate(label_parts))
        else:
            self.has_labels = False
