"""Pickleable base + the master/slave distribution protocol.

Re-designs ``veles/distributable.py``. :class:`Pickleable` defines the
snapshot contract: any attribute whose name ends with ``_`` is transient
(locks, compiled functions, device handles) and is recreated by
``init_unpickled()`` after unpickling — this single convention is what
makes whole-workflow snapshots possible.

:class:`Distributable` adds the five-method data-parallel protocol the
distributed runtime drives (``veles/distributable.py:136-302``). On TPU
the *gradient* path lowers to ``lax.psum`` inside the compiled step; this
protocol survives for what collectives cannot carry: dataset sharding,
task farming (genetics/ensemble), and elasticity bookkeeping.
"""

import threading

from veles_tpu.config import root
from veles_tpu.logger import Logger

#: Seconds to wait on the data lock before warning about a possible deadlock
#: (the reference's DEADLOCK_TIME, ``veles/distributable.py:139-157``).
DEADLOCK_TIME = 4.0


class Pickleable(Logger):
    """Base class with the ``*_``-is-transient pickling convention."""

    def __init__(self, **kwargs):
        super(Pickleable, self).__init__(**kwargs)
        self._method_storage = {}
        self.init_unpickled()

    def init_unpickled(self):
        """(Re)create transient state; called from ctor and unpickling."""
        self.stripped_pickle_ = False

    def __getstate__(self):
        state = {}
        for name, value in self.__dict__.items():
            if name.endswith("_") and not (name.startswith("__") and
                                           name.endswith("__")):
                continue
            if callable(value) and getattr(value, "__self__", None) is self:
                continue  # bound methods re-bind on init_unpickled
            state[name] = value
        return self.pickle_logger_state(state)

    def __setstate__(self, state):
        super(Pickleable, self).__setstate__(state)
        self.init_unpickled()
        from veles_tpu.mutable import ensure_descriptors
        ensure_descriptors(self)  # cross-process snapshot restore

    @property
    def stripped_pickle(self):
        """True while pickling for the wire (drop bulk payloads)."""
        return getattr(self, "stripped_pickle_", False)

    @stripped_pickle.setter
    def stripped_pickle(self, value):
        self.stripped_pickle_ = bool(value)


class IDistributable(object):
    """Marker + documentation of the distribution protocol.

    * ``generate_data_for_master()`` → payload sent slave→master after a job
    * ``generate_data_for_slave(slave)`` → payload sent master→slave as a job
    * ``apply_data_from_master(data)`` — slave applies a job
    * ``apply_data_from_slave(data, slave)`` — master merges an update
    * ``drop_slave(slave)`` — requeue work a dead slave held
    """


class Distributable(Pickleable):
    """Thread-safe wrappers + ``has_data_for_slave`` event."""

    DEADLOCK_TIME = DEADLOCK_TIME

    def __init__(self, **kwargs):
        self._generate_data_for_slave_threadsafe = kwargs.pop(
            "generate_data_for_slave_threadsafe", True)
        self._apply_data_from_slave_threadsafe = kwargs.pop(
            "apply_data_from_slave_threadsafe", True)
        super(Distributable, self).__init__(**kwargs)
        self.negotiates_on_connect = False

    def init_unpickled(self):
        super(Distributable, self).init_unpickled()
        self._data_lock_ = threading.Lock()
        self._data_event_ = threading.Event()
        self._data_event_.set()

    @property
    def has_data_for_slave(self):
        return self._data_event_.is_set()

    @has_data_for_slave.setter
    def has_data_for_slave(self, value):
        if value:
            self._data_event_.set()
        else:
            self._data_event_.clear()

    def wait_for_data_for_slave(self, timeout=DEADLOCK_TIME):
        if not self._data_event_.wait(timeout):
            self.warning("wait_for_data_for_slave timed out after %.1fs",
                         timeout)

    def _locked(self, fn, *args, **kwargs):
        if not self._data_lock_.acquire(timeout=DEADLOCK_TIME):
            self.warning("possible deadlock in %s.%s",
                         type(self).__name__, fn.__name__)
            self._data_lock_.acquire()
        try:
            return fn(*args, **kwargs)
        finally:
            self._data_lock_.release()

    # -- protocol defaults (trivially distributable) ----------------------

    def generate_data_for_master(self):
        return None

    def generate_data_for_slave(self, slave=None):
        return None

    def apply_data_from_master(self, data):
        pass

    def apply_data_from_slave(self, data, slave=None):
        pass

    def drop_slave(self, slave=None):
        pass

    # -- thread-safe entry points used by the runtime ---------------------

    def generate_data_for_slave_locked(self, slave=None):
        if self._generate_data_for_slave_threadsafe:
            return self._locked(self.generate_data_for_slave, slave)
        return self.generate_data_for_slave(slave)

    def apply_data_from_slave_locked(self, data, slave=None):
        if self._apply_data_from_slave_threadsafe:
            return self._locked(self.apply_data_from_slave, data, slave)
        return self.apply_data_from_slave(data, slave)


class TriviallyDistributable(Distributable):
    """Units with no distributed state at all."""
