"""Rendering client for the graphics server.

Re-designs ``veles/graphics_client.py:68-257``: a separate process
subscribes to the PUB endpoint, unpickles plotter snapshots and renders
them with matplotlib. Modes: ``show`` (interactive window), ``png`` /
``pdf`` (one file per plotter name in ``--out``, overwritten on each
snapshot so the directory always holds the latest state).
"""

import argparse
import os
import pickle
import zlib

from veles_tpu.graphics_server import TOPIC, TOPIC_END
from veles_tpu.logger import Logger


class GraphicsClient(Logger):
    """SUB-socket consumer rendering plotter snapshots."""

    def __init__(self, endpoint, mode="png", out=None, backend=None,
                 **kwargs):
        super(GraphicsClient, self).__init__(**kwargs)
        self.endpoint = endpoint
        self.mode = mode
        self.out = out or os.getcwd()
        import matplotlib
        if backend:
            # reference graphics_client.py:124-147 selected the
            # matplotlib backend (Qt/Tk/WebAgg) with fallback; same
            # role, Agg is the headless fallback here. use() only
            # validates the NAME — the pyplot import is what actually
            # loads the backend module (and raises for a valid name
            # whose GUI toolkit is missing), so it must sit INSIDE
            # the try for the fallback to mean anything
            try:
                matplotlib.use(backend, force=True)
                import matplotlib.pyplot  # noqa: F401
            except (ImportError, ValueError) as exc:
                self.warning("backend %r not loadable (%s); "
                             "falling back to Agg", backend, exc)
                matplotlib.use("Agg", force=True)
        elif mode != "show":
            matplotlib.use("Agg")
        import zmq
        self._context_ = zmq.Context.instance()
        self._socket_ = self._context_.socket(zmq.SUB)
        self._socket_.connect(endpoint)
        self._socket_.setsockopt(zmq.SUBSCRIBE, b"")

    def run(self):
        """Receive and render until the ``end`` topic arrives."""
        while True:
            if not self.serve_one():
                break

    def serve_one(self, timeout=None):
        """Render one snapshot; False when the stream ended."""
        import zmq
        if timeout is not None:
            if not self._socket_.poll(int(timeout * 1000), zmq.POLLIN):
                return True
        topic, payload = self._socket_.recv_multipart()
        if topic == TOPIC_END:
            return False
        plotter = pickle.loads(zlib.decompress(payload))
        self.render(plotter)
        return True

    def render(self, plotter):
        import matplotlib.pyplot as pp
        figure = pp.figure(plotter.name)
        figure.clf()
        try:
            plotter.redraw(figure)
        except Exception as exc:  # a bad plot must not kill the client
            self.warning("redraw of %s failed: %s", plotter.name, exc)
            pp.close(figure)
            return
        if self.mode == "show":
            figure.show()
            pp.pause(0.001)
        else:
            name = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in plotter.name)
            path = os.path.join(self.out, "%s.%s" % (name, self.mode))
            figure.savefig(path)
            pp.close(figure)
        return figure

    def close(self):
        self._socket_.close(linger=0)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--endpoint", required=True)
    parser.add_argument("--mode", default="png",
                        choices=("show", "png", "pdf"))
    parser.add_argument("--out", default=None)
    parser.add_argument("--backend", default=None,
                        help="matplotlib backend (e.g. TkAgg, WebAgg); "
                             "falls back to Agg when not loadable")
    args = parser.parse_args(argv)
    GraphicsClient(args.endpoint, mode=args.mode, out=args.out,
                   backend=args.backend).run()


if __name__ == "__main__":
    main()
