"""ZeroMQ PUB fan-out of plot snapshots.

Re-designs ``veles/graphics_server.py:65-143``: plotter units pickle
themselves (stripped) and the server publishes them on a PUB socket;
any number of rendering clients (:mod:`veles_tpu.graphics_client`)
subscribe from the same or another machine. Endpoints: a random-port
TCP bind (always) plus an ipc:// path when the platform supports it —
the reference's epgm multicast leg is dropped (DCN/ICI carry no plot
traffic on TPU pods; TCP covers the cross-host case).

The payload framing is ``[topic, zlib(pickle(plotter))]`` with topic
``b"graphics"`` for snapshots and ``b"end"`` for shutdown — the
reference's snappy codec is replaced by stdlib zlib so the client has
zero non-baked dependencies.
"""

import os
import pickle
import subprocess
import sys
import tempfile
import threading
import zlib

from veles_tpu.logger import Logger

TOPIC = b"graphics"
TOPIC_END = b"end"


class GraphicsServer(Logger):
    """Publishes pickled plotter snapshots over ZeroMQ PUB.

    The most recently constructed server is reachable as
    ``GraphicsServer.current`` — plotter units use it implicitly, the
    way reference plotters reached the process-wide server singleton
    (``veles/graphics_server.py:153-163``).
    """

    current = None

    def __init__(self, **kwargs):
        super(GraphicsServer, self).__init__(**kwargs)
        import zmq
        self._context_ = zmq.Context.instance()
        self._socket_ = self._context_.socket(zmq.PUB)
        self._lock_ = threading.Lock()
        port = self._socket_.bind_to_random_port("tcp://127.0.0.1")
        self.endpoints = {"tcp": "tcp://127.0.0.1:%d" % port}
        if hasattr(os, "fork"):  # ipc transport exists on POSIX only
            path = os.path.join(tempfile.mkdtemp(prefix="veles-graphics-"),
                                "plots.ipc")
            self._socket_.bind("ipc://" + path)
            self.endpoints["ipc"] = "ipc://" + path
        self.stopped = False
        GraphicsServer.current = self
        self.debug("graphics server on %s", self.endpoints["tcp"])

    def enqueue(self, plotter):
        """Pickle (stripped) and publish one plotter snapshot."""
        if self.stopped:
            return
        plotter.stripped_pickle = True
        try:
            payload = zlib.compress(pickle.dumps(plotter, protocol=4), 1)
        finally:
            plotter.stripped_pickle = False
        with self._lock_:
            self._socket_.send_multipart([TOPIC, payload])

    def launch_client(self, mode="png", out=None):
        """Spawn a rendering client subprocess against our endpoint."""
        argv = [sys.executable, "-m", "veles_tpu.graphics_client",
                "--endpoint", self.endpoints["tcp"], "--mode", mode]
        if out:
            argv += ["--out", out]
        env = dict(os.environ)
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [package_root] + env.get("PYTHONPATH", "").split(os.pathsep))
        env.setdefault("JAX_PLATFORMS", "cpu")  # renderer needs no chip
        return subprocess.Popen(argv, env=env)

    def stop(self):
        if self.stopped:
            return
        self.stopped = True
        with self._lock_:
            self._socket_.send_multipart([TOPIC_END, b""])
            self._socket_.close(linger=200)
        if GraphicsServer.current is self:
            GraphicsServer.current = None
