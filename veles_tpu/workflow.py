"""Workflow: the container unit that owns and schedules the graph.

Re-designs ``veles/workflow.py`` (Workflow :87, initialize :303,
run :351, distributed aggregation :476-573, graph export :628, stats
:788, results :827, checksum :851). Execution uses a deterministic
single-threaded signal queue instead of the reference's Twisted thread
pool: units fire control signals into a FIFO; a unit runs when its
barrier of incoming links is complete and its gates allow. Determinism is
deliberate — on TPU the heavy compute is inside jitted step functions
whose dispatch is already asynchronous, so host-side thread fan-out buys
nothing and costs reproducibility.
"""

import collections
import hashlib
import time

from veles_tpu.config import root
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import StartPoint, EndPoint, Repeater
from veles_tpu.telemetry import tracing
from veles_tpu.units import Container, Unit


class NoMoreJobs(Exception):
    """Raised by generate_data_for_slave when the run is complete."""


class Workflow(Container):
    """A graph of units with start/end points and a run loop."""

    hide_from_registry = False

    def __init__(self, workflow=None, **kwargs):
        self._units = []
        super(Workflow, self).__init__(workflow, **kwargs)
        self.stopped = Bool(True)
        self.is_running = False
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self._run_time = 0.0
        self.fitness = None  # set by evaluation units for genetics

    def init_unpickled(self):
        super(Workflow, self).init_unpickled()
        self._signals_ = collections.deque()
        self._aborted_ = False
        self.on_finished_callbacks_ = []

    # Workflow.stopped shadows Unit.stopped (which proxies to the parent).
    @property
    def stopped(self):
        return self._stopped

    @stopped.setter
    def stopped(self, value):
        if isinstance(value, Bool):
            self._stopped = value
        else:
            self._stopped.value = bool(value)

    # -- unit ownership ----------------------------------------------------

    @property
    def units(self):
        return list(self._units)

    @property
    def units_in_dependency_order(self):
        """BFS from start_point, then any unreachable units in add order."""
        order = self.start_point.dependent_units()
        for unit in self._units:
            if unit not in order:
                order.append(unit)
        return [u for u in order if u is not self]

    def add_ref(self, unit):
        if unit is self:
            raise ValueError("workflow cannot own itself")
        if unit not in self._units:
            self._units.append(unit)

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)

    def index_of(self, unit):
        return self._units.index(unit)

    def change_unit(self, old, new_unit, save_gates=True):
        """Swap a unit in an already-linked (possibly snapshot-restored)
        graph, preserving its control links.

        The reference's ``Workflow.change_unit``
        (``veles/workflow.py:977-1051``) is what made its
        snapshot-then-modify loop usable: restore, replace one unit
        (typically the decision), resume. ``old`` is a unit or its
        name; ``new_unit`` takes over every control link into and out
        of ``old`` and (with ``save_gates``) its gate objects. Data
        links (``link_attrs``) and gate EXPRESSIONS other units built
        from the old unit's Bools (e.g. ``repeater.gate_block =
        decision.complete``) reference live objects and must be re-made
        by the caller — same contract as the reference, which left its
        "data links transmission" TODO unresolved. Returns ``new_unit``.
        """
        old_unit = self[old] if isinstance(old, str) else old
        if old_unit is new_unit:
            return new_unit
        sources = list(old_unit.links_from)
        dependents = list(old_unit.links_to)
        gate_block, gate_skip = old_unit.gate_block, old_unit.gate_skip
        old_unit.unlink_all()
        self.del_ref(old_unit)
        self.add_ref(new_unit)
        if sources:
            new_unit.link_from(*sources)
        for dst in dependents:
            dst.link_from(new_unit)
        if save_gates:
            new_unit.gate_block = gate_block
            new_unit.gate_skip = gate_skip
        return new_unit

    def __iter__(self):
        return iter(self._units)

    def __len__(self):
        return len(self._units)

    def __getitem__(self, key):
        if isinstance(key, str):
            for unit in self._units:
                if unit.name == key:
                    return unit
            raise KeyError(key)
        return self._units[key]

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        """Initialize all units in dependency order with partial retry.

        A unit returning True from initialize() is re-queued and retried
        after the others — the reference's partial-initialization contract
        (``veles/workflow.py:303-349``).
        """
        self.event("initialize", "begin")
        pending = [u for u in self.units_in_dependency_order]
        max_rounds = len(pending) + 1
        for _ in range(max_rounds):
            retry = []
            for unit in pending:
                if unit._initialize_wrapped(**kwargs) is True:
                    retry.append(unit)
            if not retry:
                break
            if len(retry) == len(pending):
                raise RuntimeError(
                    "initialization deadlock: %s never became ready" %
                    ", ".join(u.name for u in retry))
            pending = retry
        else:
            raise RuntimeError("initialization did not converge")
        self._is_initialized = True
        self.event("initialize", "end")
        return None

    def signal_fired(self, src):
        """Enqueue control signals from ``src`` to its dependents."""
        for dst in src.links_to:
            self._signals_.append((dst, src))

    def run(self):
        """Run the graph to completion (until the end point fires)."""
        self.event("run", "begin")
        self.stopped <<= False
        self._aborted_ = False
        self.is_running = True
        start = time.perf_counter()
        try:
            self._signals_.clear()
            for unit in self._units:
                unit.reset_fired()
            self.start_point._run_wrapped()
            self.signal_fired(self.start_point)
            self._drain()
        finally:
            self.is_running = False
            elapsed = time.perf_counter() - start
            self._run_time += elapsed
            if tracing.enabled():
                tracing.add_complete("workflow:%s" % self.name, start,
                                     elapsed, units=len(self._units))
            self.event("run", "end")

    def _drain(self):
        # Signals already in flight when the end point fires still run:
        # a loop iteration completes atomically (gates block *new*
        # iterations via Repeater.gate_block). This is what makes a
        # snapshot taken at the stop boundary bit-identical to the same
        # point of an uninterrupted run. An explicit stop() (abort) is
        # different: it discards everything in flight immediately.
        signals = self._signals_
        while signals:
            dst, src = signals.popleft()
            if self._aborted_:
                continue
            if bool(self.stopped) and isinstance(dst, (EndPoint, Repeater)):
                # the end point already ran once; Repeaters anchor loops,
                # so blocking them after the stop guarantees termination
                # even for cycles whose gates are not wired to the stop
                # condition — in-flight units of the current iteration
                # still finish (snapshot-exactness contract)
                continue
            if bool(dst.gate_block):
                continue
            if not dst.open_gate(src):
                continue
            if bool(dst.gate_skip):
                self.signal_fired(dst)
                continue
            dst._run_wrapped()
            if not (isinstance(dst, EndPoint)):
                self.signal_fired(dst)

    def on_workflow_finished(self):
        if bool(self.stopped):
            return  # idempotent: multiple paths may reach the end point
        self.stopped <<= True
        for callback in list(self.on_finished_callbacks_):
            callback()

    def stop(self):
        """Abort: halt the loop now, discarding in-flight signals."""
        self._aborted_ = True
        self.on_workflow_finished()

    def add_finished_callback(self, callback):
        self.on_finished_callbacks_.append(callback)

    # -- distributed protocol aggregation ---------------------------------

    def _distributed_units(self):
        return [u for u in self.units_in_dependency_order]

    def generate_initial_data_for_slave(self, slave=None):
        data = []
        for unit in self._distributed_units():
            if unit.negotiates_on_connect:
                data.append((unit.name, unit.generate_data_for_slave_locked(
                    slave)))
        return data

    def apply_initial_data_from_master(self, data):
        # ISSUE 12: a master mid-run wraps the negotiates_on_connect
        # payload with a full-push RESYNC block ({"units": ...,
        # "resync": ...}) so an elastically-joining slave starts from
        # the fleet's live state; the bare-list form stays the
        # start-of-run handshake payload
        if isinstance(data, dict):
            resync = data.get("resync")
            if resync:
                self.apply_resync_from_master(resync)
            data = data.get("units")
        for name, payload in data or []:
            self[name].apply_data_from_master(payload)

    # -- elastic join: full-push resync (ISSUE 12) -------------------------

    def generate_resync_for_slave(self, slave=None):
        """Everything a slave joining MID-RUN needs to behave exactly
        like a resident slave from its first job: the current weights
        and decision state (every non-loader unit's slave payload),
        the epoch/offset cursors, and the PRNG registry state — so
        its streams continue the fleet's, not restart from seeds.

        Read-only by construction: the loader is EXCLUDED because its
        ``generate_data_for_slave`` advances the serving cursor; its
        cursors ship as plain numbers instead."""
        from veles_tpu import prng
        loader = getattr(self, "loader", None)
        units = [(u.name, u.generate_data_for_slave_locked(slave))
                 for u in self._distributed_units()
                 if u is not loader and not u.negotiates_on_connect]
        resync = {"units": units, "random": prng.dump_states()}
        if loader is not None:
            resync["epoch"] = int(loader.epoch_number)
            resync["served"] = int(loader.samples_served)
        return resync

    def apply_resync_from_master(self, resync):
        """Slave side of :meth:`generate_resync_for_slave`."""
        from veles_tpu import prng
        prng.restore_states(resync.get("random"))
        for name, payload in resync.get("units") or []:
            if payload is None:
                continue
            try:
                self[name].apply_data_from_master(payload)
            except KeyError:
                self.warning("resync names unknown unit %r; skipped",
                             name)
        loader = getattr(self, "loader", None)
        if loader is not None and "epoch" in resync:
            loader.epoch_number = int(resync["epoch"])
            loader.samples_served = int(resync.get("served", 0))

    def generate_data_for_slave(self, slave=None):
        """Collect one job: per-unit payloads (``workflow.py:476-511``).

        Returns None (slave idles briefly) when any unit withholds data
        via ``has_data_for_slave`` — e.g. the decision bounding epoch
        run-ahead. Non-blocking by design: the thread asking for this
        job may be the only one that could otherwise apply the update
        that would unblock it.
        """
        if bool(self.stopped):
            raise NoMoreJobs()
        units = self._distributed_units()
        if not all(u.has_data_for_slave for u in units):
            return None
        return [(u.name, u.generate_data_for_slave_locked(slave))
                for u in units]

    def make_fused_runner(self):
        """Hook for workflows with a custom compiled execution path
        (e.g. the gradient-free SOM loop, :mod:`veles_tpu.train.som`).
        None (default) = let the launcher pick the standard
        FusedRunner/eager dispatch."""
        return None

    def generate_segment_for_slave(self, slave=None, max_minibatches=8):
        """Collect a SEGMENT job: the non-loader unit payloads once
        (weights, decision state) plus up to ``max_minibatches``
        contiguous same-class loader minibatches. The slave runs the
        whole segment through one compiled scan (FusedTrainer) and
        returns one update — amortizing the wire round-trip and weight
        exchange the reference paid per minibatch (VERDICT r1 weak #3).

        Every minibatch payload is individually registered in the
        loader's pending set, so a slave death requeues each one
        exactly as in single-minibatch mode."""
        if bool(self.stopped):
            raise NoMoreJobs()
        units = self._distributed_units()
        if not all(u.has_data_for_slave for u in units):
            return None
        loader = self.loader
        replay = bool(loader.failed_minibatches)
        # _locked: job generation runs OUTSIDE the coordinator's lock
        # (its _handle docstring), so concurrent slave threads would
        # otherwise race _advance_global_offset/_pending_
        batches = [loader.generate_data_for_slave_locked(slave)]
        # a replayed (requeued) minibatch has arbitrary class/epoch —
        # serve it alone; fresh batches extend while the class run
        # continues (``last`` closes a class)
        while (not replay and len(batches) < max_minibatches and
               not batches[-1]["last"] and
               not loader.failed_minibatches):
            batches.append(loader.generate_data_for_slave_locked(slave))
        others = [(u.name, u.generate_data_for_slave_locked(slave))
                  for u in units if u is not loader]
        return {"units": others, "batches": batches}

    def apply_data_from_master(self, job):
        for name, payload in job:
            if payload is not None:
                self[name].apply_data_from_master(payload)

    def generate_data_for_master(self):
        return [(u.name, u.generate_data_for_master())
                for u in self._distributed_units()]

    def apply_data_from_slave(self, update, slave=None):
        for name, payload in update or []:
            if payload is not None:
                self[name].apply_data_from_slave_locked(payload, slave)

    def do_job(self, job, callback=None):
        """Slave-side: apply a job, run the graph, return the update."""
        self.apply_data_from_master(job)
        self.run()
        update = self.generate_data_for_master()
        if callback is not None:
            callback(update)
        return update

    def drop_slave(self, slave=None):
        for unit in self._distributed_units():
            unit.drop_slave(slave)

    # -- results / stats / integrity --------------------------------------

    def gather_results(self):
        """Aggregate metrics from IResultProvider units into one dict."""
        from veles_tpu.result_provider import IResultProvider
        results = {}
        for unit in self._units:
            if isinstance(unit, IResultProvider):
                results.update(unit.get_metric_values() or {})
        return results

    def print_stats(self, top=5):
        """Log the slowest units (``veles/workflow.py:788-825``)."""
        timed = sorted(self._units, key=lambda u: -u.run_time)[:top]
        total = sum(u.run_time for u in self._units) or 1e-12
        self.info("workflow \"%s\": %.3f s total unit time over %d units",
                  self.name, total, len(self._units))
        for unit in timed:
            if unit.run_calls:
                self.info("  %-30s %8.3f s (%5.1f%%) in %d calls",
                          unit.name, unit.run_time,
                          100.0 * unit.run_time / total, unit.run_calls)

    @property
    def checksum(self):
        """Topology checksum guarding master/slave compatibility
        (``veles/workflow.py:851-866``)."""
        digest = hashlib.sha256()
        for unit in self._units:
            digest.update(type(unit).__name__.encode())
            digest.update(unit.name.encode())
            for dst in unit.links_to:
                digest.update(dst.name.encode())
        return digest.hexdigest()

    def graph_description(self):
        """JSON-able control-flow graph for the dashboard's inline SVG
        view (the role of the reference's viz.js ``svg_view.js``)."""
        units = list(dict.fromkeys(
            [self.start_point, self.end_point] + self._units))
        ids = {unit: i for i, unit in enumerate(units)}
        nodes = [{"id": ids[u], "name": u.name,
                  "type": type(u).__name__,
                  "group": u.view_group} for u in units]
        edges = [[ids[src], ids[dst]] for src in units
                 for dst in src.links_to if dst in ids]
        return {"nodes": nodes, "edges": edges}

    def generate_graph(self):
        """DOT source of the control-flow graph (``workflow.py:628-754``)."""
        lines = ["digraph %s {" % self.name.replace(" ", "_"),
                 '  rankdir="TB";']
        ids = {}
        for i, unit in enumerate(dict.fromkeys(
                [self.start_point, self.end_point] + self._units)):
            ids[unit] = "u%d" % i
            lines.append('  %s [label="%s\\n%s" shape=%s];' % (
                ids[unit], type(unit).__name__, unit.name,
                "ellipse" if unit.view_group == "PLUMBING" else "box"))
        for unit in ids:
            for dst in unit.links_to:
                if dst in ids:
                    lines.append("  %s -> %s;" % (ids[unit], ids[dst]))
        lines.append("}")
        return "\n".join(lines)

    def add_plotters(self, klasses=("train", "validation"),
                     confusion=True):
        """Wire the standard plot set the reference samples carry.

        Needs ``self.decision`` / ``self.loader`` (and optionally
        ``self.evaluator`` for the confusion heatmap), which every
        training workflow here exposes. One epoch-metric curve per
        sample class, plotters run after the decision and only at
        epoch boundaries; they never sit on the training path.
        """
        from veles_tpu.plotting_units import (EpochMetricPlotter,
                                              MatrixPlotter)
        self.plotters = []
        prev = self.decision
        for klass in klasses:
            plotter = EpochMetricPlotter(
                self, name="%s %s" % (klass, self.decision.METRIC_NAME),
                klass=klass)
            plotter.link_from(prev)
            plotter.link_attrs(self.decision, ("input", "epoch_history"))
            plotter.gate_skip = ~self.loader.epoch_ended
            self.plotters.append(plotter)
            prev = plotter
        evaluator = getattr(self, "evaluator", None)
        if confusion and evaluator is not None and \
                hasattr(evaluator, "confusion_matrix"):
            plotter = MatrixPlotter(self, name="confusion")
            plotter.link_from(prev)
            plotter.link_attrs(evaluator, ("input", "confusion_matrix"))
            plotter.gate_skip = ~self.loader.epoch_ended
            self.plotters.append(plotter)
        # the SlaveStats chart is NOT wired here: on a master the
        # workflow graph never executes (jobs run on slaves), so the
        # launcher drives it from its own ticker —
        # Launcher._start_slave_stats
        # plotters may be wired onto an already-initialized workflow
        for plotter in self.plotters:
            if not plotter.is_initialized:
                plotter._initialize_wrapped()
        return self.plotters

    def package_export(self, path, precision="float32"):
        """Export an inference package (see :mod:`veles_tpu.export`)."""
        try:
            from veles_tpu.export.package import export_workflow
        except ImportError as exc:
            raise NotImplementedError(
                "the export subsystem is not available: %s" % exc)
        return export_workflow(self, path, precision=precision)

    @property
    def computing_power(self):
        """Slave load metric (``veles/accelerated_units.py:843-858``)."""
        from veles_tpu.accelerated_units import DeviceBenchmark
        device = getattr(self, "device", None)
        if device is None:
            return 0.0
        return DeviceBenchmark.estimate(device)

    def __getstate__(self):
        state = super(Workflow, self).__getstate__()
        state.pop("is_running", None)
        return state

    def __setstate__(self, state):
        super(Workflow, self).__setstate__(state)
        self.is_running = False
