"""Testing/standalone doubles (``veles/dummy.py``).

``DummyLauncher`` quacks like a Launcher without reactors or networking;
``DummyWorkflow`` is a Workflow parented to one. They ship in the
package (not the test tree) because production code uses them too — the
device benchmark constructs units outside any real run, exactly like the
reference's autotuner (``veles/backends.py:680-717``).
"""

from veles_tpu.logger import Logger
from veles_tpu.workflow import Workflow


class DummyLauncher(Logger):
    """Stand-in for Launcher: standalone mode, no services."""

    mode = "standalone"

    def __init__(self, **kwargs):
        super(DummyLauncher, self).__init__(**kwargs)
        self.device = kwargs.get("device")
        self.testing = kwargs.get("testing", False)
        self.stopped = False
        self.id = "dummy"
        self.log_id = "dummy"
        self.plots_endpoints = ()

    @property
    def is_standalone(self):
        return True

    @property
    def is_master(self):
        return False

    @property
    def is_slave(self):
        return False

    @property
    def is_interactive(self):
        return False

    def add_ref(self, workflow):
        self.workflow = workflow

    def del_ref(self, workflow):
        pass

    def on_workflow_finished(self):
        self.stopped = True

    def stop(self):
        self.stopped = True


class DummyWorkflow(Workflow):
    """A workflow owned by a fresh DummyLauncher."""

    hide_from_registry = True

    def __init__(self, **kwargs):
        super(DummyWorkflow, self).__init__(DummyLauncher(), **kwargs)
