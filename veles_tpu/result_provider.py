"""Result-provider contract (``veles/result_provider.py:58``).

Units that publish final metrics (validation error, RMSE, fitness)
implement ``get_metric_values()``; the workflow aggregates them into the
``--result-file`` JSON (``veles/workflow.py:827-849``).
"""


class IResultProvider(object):
    """Mixin marker: implement get_metric_values() -> dict."""

    def get_metric_values(self):
        raise NotImplementedError
