"""Cluster metrics federation: slave registries, one master pane.

Every process has its own :class:`~veles_tpu.telemetry.registry.
MetricsRegistry` (PR 4), so a master + N slaves run exposes N+1
disjoint ``/metrics`` endpoints. This module federates them without a
new socket: each slave piggybacks a compact **delta-encoded registry
snapshot** on the heartbeat messages it already sends
(:class:`~veles_tpu.parallel.coordinator.CoordinatorClient`), and the
master merges the deltas into a :class:`FederatedRegistry` — a
per-slave store of series that renders into the master's own
``/metrics`` / ``/metrics.json`` with a ``{slave="<sid>"}`` label
appended, plus the ``/cluster.json`` health table.

Wire format (one heartbeat's ``"telemetry"`` value)::

    {"v": 1, "seq": 7, "full": true?,            # seq = per-encoder
     "series": [["c"|"g", name, {labels}, value],
                ["h", name, {labels}, {"count": n, "sum": s,
                                       "p50": ..., "p95": ..., "p99": ...}],
                ...],
     "removed": [[name, {labels}], ...]}         # series that vanished

Rows carry ABSOLUTE values, not increments — a lost delta only leaves
series stale, never wrong, and the master heals staleness by asking
for a full push (``{"resync": true}`` in the heartbeat ack) whenever
it sees a sequence gap. Duplicate deliveries (same ``seq``) are
dropped, so the merge is idempotent. Counters stay monotonic across a
slave restart: when a raw counter goes backwards the previous value is
folded into a per-series base offset.

Cardinality is bounded on the master: at most :attr:`FederatedRegistry.
MAX_SLAVES` feeds of :attr:`FederatedRegistry.MAX_SERIES_PER_SLAVE`
series each (overflow counted in ``veles_federation_dropped_series_
total``), and a feed is garbage-collected the moment the coordinator
drops its slave — a churny run cannot grow the registry without bound.
"""

import threading
import time
import uuid

from veles_tpu.telemetry.registry import get_registry

#: bump when the delta wire format changes incompatibly
WIRE_VERSION = 1

_KIND_TAG = {"counters": "c", "gauges": "g", "histograms": "h"}
_TAG_KIND = {"c": "counters", "g": "gauges", "h": "histograms"}


def flatten_snapshot(snap):
    """``registry.snapshot()`` -> ``{(name, labelkey): (tag, name,
    labels, data)}`` where ``data`` is a float for counters/gauges and
    the summary dict for histograms."""
    out = {}
    for kind, tag in _KIND_TAG.items():
        for name, family in snap.get(kind, {}).items():
            for entry in family.get("series", ()):
                labels = entry.get("labels") or {}
                key = (name, tuple(sorted(labels.items())))
                if tag == "h":
                    data = {k: v for k, v in entry.items()
                            if k != "labels"}
                else:
                    data = entry.get("value", 0.0)
                out[key] = (tag, name, labels, data)
    return out


class SnapshotEncoder(object):
    """Slave side: delta-encode the local registry for the heartbeat.

    ``encode()`` snapshots the registry and returns only the series
    that changed since the last call (``None`` when nothing did — the
    heartbeat then carries no telemetry at all). The first call, and
    any call after :meth:`mark_resync`, sends the full snapshot."""

    def __init__(self, registry=None, exclude_prefixes=()):
        self._registry = registry or get_registry()
        self._exclude = tuple(exclude_prefixes)
        self._lock = threading.Lock()
        #: stream generation: lets the master tell a RESTARTED encoder
        #: (new process, seq back at 1) from a replayed old delta
        self._gen = uuid.uuid4().hex[:8]
        self._seq = 0
        self._sent = {}
        self._full = True

    def mark_resync(self):
        """Master saw a gap: send everything on the next beat."""
        with self._lock:
            self._full = True

    def encode(self):
        rows = flatten_snapshot(self._registry.snapshot())
        if self._exclude:
            rows = {key: row for key, row in rows.items()
                    if not key[0].startswith(self._exclude)}
        with self._lock:
            full = self._full
            changed = [[row[0], row[1], row[2], row[3]]
                       for key, row in sorted(rows.items())
                       if full or self._sent.get(key) != row[3]]
            removed = [] if full else \
                [[name, dict(labelkey)]
                 for name, labelkey in self._sent
                 if (name, labelkey) not in rows]
            if not changed and not removed and not full:
                return None
            self._sent = {key: row[3] for key, row in rows.items()}
            self._full = False
            self._seq += 1
            delta = {"v": WIRE_VERSION, "gen": self._gen,
                     "seq": self._seq, "series": changed}
            if full:
                delta["full"] = True
            if removed:
                delta["removed"] = removed
            return delta


class _SlaveFeed(object):
    """Master-side state for one slave's metric stream."""

    __slots__ = ("gen", "seq", "series", "bases", "last_raw",
                 "last_update", "need_full")

    def __init__(self):
        self.gen = None      # encoder stream generation
        self.seq = 0
        self.series = {}     # key -> (tag, name, labels, data)
        self.bases = {}      # key -> counter restart offset
        self.last_raw = {}   # key -> last raw counter value
        self.last_update = 0.0
        self.need_full = False


class FederatedRegistry(object):
    """Master side: merge per-slave snapshot deltas, bounded, GC'd."""

    MAX_SLAVES = 256
    MAX_SERIES_PER_SLAVE = 1024

    def __init__(self, registry=None, max_slaves=None,
                 max_series_per_slave=None):
        self._lock = threading.Lock()
        self._feeds = {}
        self.run_info = {}
        if max_slaves is not None:
            self.MAX_SLAVES = max_slaves
        if max_series_per_slave is not None:
            self.MAX_SERIES_PER_SLAVE = max_series_per_slave
        registry = registry or get_registry()
        self._registry = registry
        self._m_applies = registry.counter(
            "veles_federation_applies_total",
            "Slave snapshot deltas merged")
        self._m_duplicates = registry.counter(
            "veles_federation_duplicates_total",
            "Deltas dropped as duplicate/reordered deliveries")
        self._m_resyncs = registry.counter(
            "veles_federation_resyncs_total",
            "Full-snapshot resyncs requested after a sequence gap")
        self._m_dropped = registry.counter(
            "veles_federation_dropped_series_total",
            "Series dropped by the per-slave cardinality cap")
        self._m_slaves = registry.gauge(
            "veles_federation_slaves", "Slave metric feeds tracked")
        self._m_apply_ms = registry.histogram(
            "veles_federation_apply_ms",
            "Master time merging one slave delta")

    def set_run_info(self, **info):
        """Attach run-level context (trace id, master id) that
        ``cluster_report()`` surfaces."""
        with self._lock:
            self.run_info.update(info)

    # -- merging -----------------------------------------------------------

    def apply(self, sid, delta):
        """Merge one piggybacked delta; returns heartbeat-ack hints
        (``{"resync": True}`` when the slave should send a full
        snapshot). Safe against duplicates, reorders and restarts."""
        if not isinstance(delta, dict) or \
                not isinstance(delta.get("seq"), int):
            return {}
        t0 = time.perf_counter()
        seq = delta["seq"]
        full = bool(delta.get("full"))
        gap = False
        with self._lock:
            feed = self._feeds.get(sid)
            if feed is None:
                if len(self._feeds) >= self.MAX_SLAVES:
                    return {}
                feed = self._feeds[sid] = _SlaveFeed()
            gen = delta.get("gen")
            if feed.gen is None or gen == feed.gen:
                if feed.gen is not None and seq <= feed.seq:
                    # duplicate/reordered delivery from the SAME
                    # encoder stream: dropping it keeps apply()
                    # exactly idempotent (and protects the counter
                    # restart heuristic from replayed old values)
                    self._m_duplicates.inc()
                    return {}
                if feed.seq:
                    gap = seq != feed.seq + 1 and not full
                else:
                    # a BRAND-NEW feed joining mid-stream (re-created
                    # after a drop, or promoted past the slave cap):
                    # everything that stopped churning before now is
                    # missing — only a full push heals that
                    gap = not full
            else:
                # NEW encoder stream behind the same sid: the slave
                # process restarted. Start the series view from
                # scratch but KEEP counter bases/last_raw, so the raw
                # values going backwards fold into the base and the
                # federated counters stay monotonic.
                feed.series.clear()
                feed.seq = 0
                gap = not full
            feed.gen = gen
            if full:
                feed.series.clear()
                feed.need_full = False
            for row in delta.get("series") or ():
                try:
                    tag, name, labels, data = row
                    labels = dict(labels)
                    key = (str(name), tuple(sorted(
                        (str(k), str(v)) for k, v in labels.items())))
                except (TypeError, ValueError):
                    continue  # one malformed row must not kill the beat
                if key not in feed.series and \
                        len(feed.series) >= self.MAX_SERIES_PER_SLAVE:
                    self._m_dropped.inc()
                    continue
                if tag == "c":
                    try:
                        raw = float(data)
                    except (TypeError, ValueError):
                        continue
                    last = feed.last_raw.get(key)
                    if last is not None and raw < last:
                        # slave restart: fold the old total into the
                        # base so the federated counter never decreases
                        feed.bases[key] = feed.bases.get(key, 0.0) + last
                    feed.last_raw[key] = raw
                    data = feed.bases.get(key, 0.0) + raw
                elif tag == "g":
                    try:
                        data = float(data)
                    except (TypeError, ValueError):
                        continue
                elif tag == "h":
                    if not isinstance(data, dict):
                        continue
                    data = dict(data)
                else:
                    continue
                feed.series[key] = (tag, str(name), labels, data)
            for row in delta.get("removed") or ():
                try:
                    name, labels = row
                    key = (str(name), tuple(sorted(
                        (str(k), str(v)) for k, v in dict(labels).items())))
                except (TypeError, ValueError):
                    continue
                feed.series.pop(key, None)
            feed.seq = seq
            feed.last_update = time.time()
            if gap:
                feed.need_full = True
            # need_full persists until a full snapshot actually
            # arrives: every ack keeps asking, so one lost resync
            # request cannot leave the view stale forever
            want_resync = feed.need_full
            self._m_slaves.set(len(self._feeds))
        self._m_applies.inc()
        self._m_apply_ms.observe((time.perf_counter() - t0) * 1e3)
        if want_resync:
            self._m_resyncs.inc()
            return {"resync": True}
        return {}

    def remove_slave(self, sid):
        """GC one slave's feed (coordinator drop path)."""
        with self._lock:
            removed = self._feeds.pop(sid, None)
            self._m_slaves.set(len(self._feeds))
        return removed is not None

    def reset(self):
        """Tests: drop every feed and the run info."""
        with self._lock:
            self._feeds.clear()
            self.run_info = {}
            self._m_slaves.set(0)

    # -- reading -----------------------------------------------------------

    def slaves(self):
        """Per-feed summary: ``{sid: {seq, series, age_s}}``."""
        now = time.time()
        with self._lock:
            return {sid: {"seq": feed.seq,
                          "series": len(feed.series),
                          "age_s": round(now - feed.last_update, 3)}
                    for sid, feed in self._feeds.items()}

    def series_rows(self):
        """``[(sid, tag, name, labels, data)]`` — a consistent copy."""
        with self._lock:
            return [(sid, tag, name, dict(labels), data
                     if not isinstance(data, dict) else dict(data))
                    for sid, feed in self._feeds.items()
                    for tag, name, labels, data in feed.series.values()]

    def merged_snapshot(self, registry=None):
        """The local registry snapshot with every federated series
        folded in under an added ``slave`` label — the cluster-wide
        ``/metrics.json`` body."""
        snap = (registry or self._registry).snapshot()
        for sid, tag, name, labels, data in self.series_rows():
            bucket = snap[_TAG_KIND[tag]]
            family = bucket.get(name)
            if family is None:
                family = bucket[name] = {"help": "", "series": []}
            labels = dict(labels)
            if "slave" in labels:
                # an in-process master+slave (or a master-under-
                # master) pushes series that already carry a slave
                # label; rename it the way Prometheus does on a
                # target-label clash instead of misattributing the
                # inner slave's data to the pushing feed
                labels["exported_slave"] = labels.pop("slave")
            labels["slave"] = sid
            if tag == "h":
                entry = dict(data)
                entry["labels"] = labels
            else:
                entry = {"value": data, "labels": labels}
            family["series"].append(entry)
        return snap


def render_snapshot_prometheus(snap):
    """Prometheus text exposition of a (merged) snapshot dict — THE
    shared renderer from :mod:`~veles_tpu.telemetry.registry`, so the
    local and federated expositions cannot drift apart."""
    from veles_tpu.telemetry.registry import render_snapshot
    return render_snapshot(snap)


#: THE process federation (master side); slaves never touch it.
_federation = None
_federation_lock = threading.Lock()


def get_federation():
    global _federation
    with _federation_lock:
        if _federation is None:
            _federation = FederatedRegistry()
        return _federation


def reset_federation():
    """Tests only."""
    global _federation
    with _federation_lock:
        if _federation is not None:
            _federation.reset()
        _federation = None


def render_cluster_prometheus(registry=None):
    """One cluster-wide exposition: the local registry plus every
    federated slave series (identical to the local rendering when no
    slave feeds exist — the common standalone case)."""
    return render_snapshot_prometheus(
        get_federation().merged_snapshot(registry))


def cluster_snapshot(registry=None):
    """Cluster-wide ``/metrics.json`` body."""
    return get_federation().merged_snapshot(registry)


def cluster_report():
    """The ``/cluster.json`` body: per-slave health + telemetry-feed
    state + active alerts + run identity, all JSON-primitive."""
    from veles_tpu.telemetry import alerts, health
    fed = get_federation()
    feeds = fed.slaves()
    table = health.get_scorer().table()
    slaves = {}
    for sid in set(feeds) | set(table):
        entry = dict(table.get(sid) or {"state": "unknown"})
        entry["telemetry"] = feeds.get(sid)
        slaves[sid] = entry
    return {"generated_t": time.time(),
            "run": dict(fed.run_info),
            "slaves": slaves,
            "alerts_active": alerts.get_engine().active()}
