"""Span tracing with Chrome trace-event export.

``span(name, **attrs)`` wraps any host-side region; events land in a
bounded ring (:class:`TraceBuffer`) as *complete* trace events
(``"ph": "X"``) that :meth:`TraceBuffer.to_chrome` renders as JSON
loadable in Perfetto / ``chrome://tracing``. Timestamps are wall-clock
microseconds derived from a ``perf_counter`` offset captured at import,
so records from different processes (a master and its slave processes)
align on one timeline.

Telemetry must be near-free when idle: when tracing is disabled,
``span()`` returns a shared no-op context manager (one function call,
no allocation); enabled, a span costs a ``perf_counter`` pair and a
deque append — no lock (the deque is the ring, and CPython deque
appends are atomic).

Trace identity: every event carries a ``trace_id`` resolved from (in
order) an explicit argument, the calling thread's context
(:func:`trace_context` — how a client-supplied ``X-Request-Id`` or a
coordinator job's id reaches the spans under it), or the process-wide
default (:func:`set_default_trace_id` — how a distributed run shares
ONE id across master and slave records).

``enable(jax_annotations=True)`` additionally opens a
``jax.profiler.TraceAnnotation`` per span so host spans line up with
device traces captured by the JAX profiler.
"""

import collections
import contextlib
import json
import os
import threading
import time

_WALL_EPOCH = time.time()
_PERF_EPOCH = time.perf_counter()


def _to_us(perf_time):
    """perf_counter() value -> wall-clock microseconds."""
    return (_WALL_EPOCH + (perf_time - _PERF_EPOCH)) * 1e6


class TraceBuffer(object):
    """Bounded ring of Chrome trace events."""

    def __init__(self, maxlen=131072):
        self._events = collections.deque(maxlen=maxlen)
        self._pid = os.getpid()

    def __len__(self):
        return len(self._events)

    def add_complete(self, name, start_perf, duration_s, trace_id=None,
                     **args):
        """Record one finished region ('X' event). ``start_perf`` is the
        ``perf_counter()`` value at region entry."""
        if trace_id is None:
            trace_id = get_trace_id()
        if trace_id is not None:
            args["trace_id"] = trace_id
        self._events.append({
            "name": name,
            "ph": "X",
            "ts": _to_us(start_perf),
            "dur": duration_s * 1e6,
            "pid": self._pid,
            "tid": threading.get_ident(),
            "args": args,
        })

    def add_instant(self, name, trace_id=None, **args):
        if trace_id is None:
            trace_id = get_trace_id()
        if trace_id is not None:
            args["trace_id"] = trace_id
        self._events.append({
            "name": name,
            "ph": "i",
            "ts": _to_us(time.perf_counter()),
            "s": "t",
            "pid": self._pid,
            "tid": threading.get_ident(),
            "args": args,
        })

    def events(self):
        return list(self._events)

    def clear(self):
        self._events.clear()

    def to_chrome(self, process_name=None):
        """The Chrome trace-event JSON object (Perfetto-loadable)."""
        events = self.events()
        if process_name:
            events.insert(0, {
                "name": "process_name", "ph": "M", "pid": self._pid,
                "tid": 0, "args": {"name": process_name}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump(self, path, process_name=None):
        """Write (or merge-append into) a trace file.

        If ``path`` already holds a valid trace (another process of the
        same run exited first — a slave before its master), the events
        merge so the file stays one Perfetto-loadable timeline. The
        read-merge-write cycle runs under an exclusive ``flock`` on a
        sidecar lock file: a master and its slaves routinely exit
        within milliseconds of each other, and an unlocked merge would
        let the second writer clobber the first's events."""
        trace = self.to_chrome(process_name=process_name)
        try:
            import fcntl
            lock = open(path + ".lock", "w")
            fcntl.flock(lock, fcntl.LOCK_EX)
        except (ImportError, OSError):
            lock = None
        try:
            try:
                with open(path) as fin:
                    existing = json.load(fin)
                trace["traceEvents"] = (list(existing["traceEvents"]) +
                                        trace["traceEvents"])
            except (OSError, ValueError, KeyError, TypeError):
                pass
            # write-to-temp + rename: a reader (or a crashing writer)
            # never observes a half-written file
            tmp = "%s.%d.tmp" % (path, os.getpid())
            with open(tmp, "w") as fout:
                json.dump(trace, fout)
            os.replace(tmp, path)
        finally:
            if lock is not None:
                lock.close()
        return len(trace["traceEvents"])


_default_buffer = TraceBuffer()
_buffer = _default_buffer
_enabled = False
_jax_annotation = None  # jax.profiler.TraceAnnotation when passthrough on
_default_trace_id = None
_tls = threading.local()


def get_buffer():
    return _buffer


def enable(buffer=None, jax_annotations=False):
    """Turn span recording on (optionally into a caller-owned buffer)."""
    global _buffer, _enabled, _jax_annotation
    if buffer is not None:
        _buffer = buffer
    _jax_annotation = None
    if jax_annotations:
        try:
            from jax.profiler import TraceAnnotation
            _jax_annotation = TraceAnnotation
        except Exception:  # jax absent or too old: host tracing only
            _jax_annotation = None
    _enabled = True
    return _buffer


def disable():
    """Turn recording off and drop any caller-owned buffer installed by
    ``enable(buffer=...)`` — a later bare ``enable()`` must not keep
    writing into (and dumping) a stale test-owned ring."""
    global _enabled, _jax_annotation, _buffer
    _enabled = False
    _jax_annotation = None
    _buffer = _default_buffer


def enabled():
    return _enabled


# -- trace identity --------------------------------------------------------


def set_default_trace_id(trace_id):
    """Process-wide default (a distributed run's shared id)."""
    global _default_trace_id
    _default_trace_id = trace_id


def get_trace_id():
    """The calling thread's trace id: context override, else default."""
    tid = getattr(_tls, "trace_id", None)
    return tid if tid is not None else _default_trace_id


@contextlib.contextmanager
def trace_context(trace_id):
    """Pin ``trace_id`` onto this thread for the duration (request
    handling, one coordinator job). None = no-op."""
    if trace_id is None:
        yield
        return
    prev = getattr(_tls, "trace_id", None)
    _tls.trace_id = trace_id
    try:
        yield
    finally:
        _tls.trace_id = prev


# -- spans ------------------------------------------------------------------


class _NoopSpan(object):
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span(object):
    __slots__ = ("name", "args", "_start", "_ann")

    def __init__(self, name, args):
        self.name = name
        self.args = args
        self._ann = None

    def __enter__(self):
        if _jax_annotation is not None:
            try:
                self._ann = _jax_annotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        duration = time.perf_counter() - self._start
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        _buffer.add_complete(self.name, self._start, duration,
                             **self.args)
        return False


def span(name, **attrs):
    """Context manager timing a region; no-op singleton when disabled."""
    if not _enabled:
        return _NOOP
    return _Span(name, attrs)


def add_complete(name, start_perf, duration_s, **args):
    """Record an already-timed region (the hot-path form: the caller
    holds the perf_counter pair anyway, so no context manager needs to
    be allocated). No-op when disabled."""
    if _enabled:
        _buffer.add_complete(name, start_perf, duration_s, **args)


def trace_id_from_request(headers, rid=None):
    """THE request-id → trace-id rule, shared by every HTTP surface:
    an ``X-Request-Id`` header wins, else the request body's ``"id"``
    echo value (stringified), else None."""
    trace_id = headers.get("X-Request-Id") if headers is not None else None
    if trace_id is None and rid is not None:
        trace_id = str(rid)
    return trace_id


@contextlib.contextmanager
def request_span(name, trace_id=None, **attrs):
    """One HTTP/RPC request: pins ``trace_id`` (e.g. a client-supplied
    ``X-Request-Id``) onto the thread and opens a span, so every span
    recorded while handling the request shares the id."""
    if not _enabled:
        yield
        return
    with trace_context(trace_id):
        with span(name, **attrs):
            yield
