"""Process-wide metrics registry: labeled Counters, Gauges, Histograms.

One :class:`MetricsRegistry` per process (``get_registry()``) is shared
by training, the distributed coordinator and the serving engine — the
generalization of the reservoir/percentile machinery that grew up
inside :mod:`veles_tpu.serving.metrics` (which now imports it from
here). Two render paths:

* :meth:`MetricsRegistry.snapshot` — a JSON-able dict, served at
  ``/metrics.json`` by the web dashboard and the serving frontend;
* :meth:`MetricsRegistry.render_prometheus` — the Prometheus text
  exposition format (histograms render as summaries with ``quantile``
  labels), served at ``/metrics``.

Percentiles are exact nearest-rank over a bounded reservoir of the most
recent ``reservoir_size`` observations — the window an operator
watching a live run wants, not an all-time estimate.

Thread safety: the registry's single lock is the ONLY lock in the
telemetry layer (tracing appends to a lock-free deque); recording a
sample is an acquire + arithmetic + deque append, far below the cost
of anything worth measuring.
"""

import collections
import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def percentile(sorted_values, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_values:
        return 0.0
    rank = max(0, min(len(sorted_values) - 1,
                      int(round(q / 100.0 * (len(sorted_values) - 1)))))
    return float(sorted_values[rank])


class Reservoir(object):
    """Bounded window of the most recent observations."""

    __slots__ = ("_values",)

    def __init__(self, size=4096):
        self._values = collections.deque(maxlen=size)

    def add(self, value):
        self._values.append(float(value))

    def sorted_values(self):
        return sorted(self._values)

    def percentile(self, q):
        return percentile(self.sorted_values(), q)

    def __len__(self):
        return len(self._values)


def _label_key(label_names, kwargs):
    try:
        return tuple(str(kwargs[name]) for name in label_names)
    except KeyError as e:
        raise ValueError("missing label %s (expected %s)"
                         % (e, ", ".join(label_names)))


class _Metric(object):
    """A metric family: children keyed by label-value tuples."""

    kind = None

    def __init__(self, registry, name, help="", label_names=()):
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._children = {}

    def labels(self, **kwargs):
        key = _label_key(self.label_names, kwargs)
        if len(kwargs) != len(self.label_names):
            raise ValueError("expected labels %s, got %s"
                             % (self.label_names, sorted(kwargs)))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def _make_child(self):
        return self._child_cls(self._lock)

    def _default(self):
        if self.label_names:
            raise ValueError("metric %s has labels %s; use .labels()"
                             % (self.name, self.label_names))
        return self.labels()

    def reset(self):
        """Drop every child (tests / per-run benches)."""
        with self._lock:
            self._children.clear()

    def remove(self, **labels):
        """Drop children matching ``labels``; a SUBSET of the label
        names removes every child whose values match on those names
        (``family.remove(slave=sid)`` clears all of a dead slave's
        series regardless of its other labels). Returns the number of
        children removed — label cardinality stays bounded only if
        somebody actually calls this when the labeled entity dies."""
        unknown = set(labels) - set(self.label_names)
        if unknown:
            raise ValueError("unknown labels %s (family %s has %s)"
                             % (sorted(unknown), self.name,
                                self.label_names))
        match = {name: str(value) for name, value in labels.items()}
        removed = 0
        with self._lock:
            for key in list(self._children):
                values = dict(zip(self.label_names, key))
                if all(values[name] == want
                       for name, want in match.items()):
                    del self._children[key]
                    removed += 1
        return removed

    def series(self):
        """[(labels_dict, child)] — a consistent copy."""
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.label_names, key)), child)
                for key, child in items]


class _CounterChild(object):
    __slots__ = ("value", "_lock")

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, n=1):
        with self._lock:
            self.value += n


class Counter(_Metric):
    """Monotonically increasing count (name it ``*_total``)."""

    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n=1):
        self._default().inc(n)

    @property
    def value(self):
        return self._default().value


class _GaugeChild(object):
    __slots__ = ("value", "_lock")

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock

    def set(self, value):
        with self._lock:
            self.value = float(value)

    def inc(self, n=1):
        with self._lock:
            self.value += n

    def dec(self, n=1):
        with self._lock:
            self.value -= n


class Gauge(_Metric):
    """A value that goes up and down."""

    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value):
        self._default().set(value)

    def inc(self, n=1):
        self._default().inc(n)

    def dec(self, n=1):
        self._default().dec(n)

    @property
    def value(self):
        return self._default().value


class _HistogramChild(object):
    __slots__ = ("count", "sum", "reservoir", "_lock")

    def __init__(self, lock, reservoir_size=4096):
        self.count = 0
        self.sum = 0.0
        self.reservoir = Reservoir(reservoir_size)
        self._lock = lock

    def observe(self, value):
        value = float(value)
        with self._lock:
            self.count += 1
            self.sum += value
            self.reservoir.add(value)

    def percentile(self, q):
        with self._lock:
            return self.reservoir.percentile(q)

    def summary(self, quantiles=(50, 95, 99)):
        with self._lock:
            count, total = self.count, self.sum
            values = self.reservoir.sorted_values()
        out = {"count": count, "sum": round(total, 6)}
        for q in quantiles:
            out["p%g" % q] = round(percentile(values, q), 6)
        return out


class Histogram(_Metric):
    """Windowed distribution: count + sum + exact recent percentiles."""

    kind = "histogram"

    def __init__(self, registry, name, help="", label_names=(),
                 reservoir_size=4096):
        super(Histogram, self).__init__(registry, name, help, label_names)
        self._reservoir_size = reservoir_size

    def _make_child(self):
        return _HistogramChild(self._lock, self._reservoir_size)

    def observe(self, value):
        self._default().observe(value)

    def percentile(self, q):
        return self._default().percentile(q)


def _escape_label(value):
    return str(value).replace("\\", "\\\\").replace("\"", "\\\"") \
        .replace("\n", "\\n")


def _fmt_labels(labels, extra=()):
    pairs = list(labels.items()) + list(extra)
    if not pairs:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _escape_label(v))
                             for k, v in pairs)


class MetricsRegistry(object):
    """Thread-safe get-or-create registry of metric families."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}

    # -- creation ----------------------------------------------------------

    def _get_or_create(self, cls, name, help, labels, **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError("invalid metric name %r" % name)
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(
                    self, name, help=help, label_names=labels, **kwargs)
                return metric
        if not isinstance(metric, cls):
            raise ValueError("metric %s already registered as %s"
                             % (name, metric.kind))
        if tuple(labels) != metric.label_names:
            raise ValueError("metric %s already registered with labels %s"
                             % (name, metric.label_names))
        return metric

    def counter(self, name, help="", labels=()):
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(), reservoir_size=4096):
        return self._get_or_create(Histogram, name, help, labels,
                                   reservoir_size=reservoir_size)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def clear(self):
        """Drop every metric (tests only — live handles go stale)."""
        with self._lock:
            self._metrics.clear()

    # -- rendering ---------------------------------------------------------

    def snapshot(self):
        """JSON-able dump of every family and labeled series. Runs
        under the registry lock so each count/sum/percentile triple is
        mutually consistent (mutators take the same lock)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            metrics = list(self._metrics.values())
            for metric in metrics:
                series = []
                for labels, child in metric.series():
                    if metric.kind == "histogram":
                        entry = child.summary()
                    else:
                        entry = {"value": child.value}
                    if labels:
                        entry["labels"] = labels
                    series.append(entry)
                out[metric.kind + "s"][metric.name] = {
                    "help": metric.help, "series": series}
        return out

    def render_prometheus(self):
        """Prometheus text exposition (0.0.4): counters and gauges as
        themselves, histograms as summaries with ``quantile`` labels.
        The snapshot is taken under the registry lock (consistent
        triples); rendering works on the copy."""
        return render_snapshot(self.snapshot())


def render_snapshot(snap):
    """Prometheus text exposition of a :meth:`MetricsRegistry.
    snapshot` dict — THE renderer, shared with the federation's
    merged cluster view (which folds slave series into a snapshot
    before rendering)."""
    families = []
    for kind, ptype in (("counters", "counter"), ("gauges", "gauge"),
                        ("histograms", "summary")):
        for name, family in snap.get(kind, {}).items():
            families.append((name, ptype, family))
    lines = []
    for name, ptype, family in sorted(families):
        if family.get("help"):
            lines.append("# HELP %s %s"
                         % (name, family["help"].replace("\n", " ")))
        lines.append("# TYPE %s %s" % (name, ptype))
        for entry in family.get("series", ()):
            labels = entry.get("labels") or {}
            if ptype == "summary":
                for q, key in ((0.5, "p50"), (0.95, "p95"),
                               (0.99, "p99")):
                    lines.append("%s%s %s" % (
                        name,
                        _fmt_labels(labels, [("quantile", "%g" % q)]),
                        repr(float(entry.get(key, 0.0)))))
                lines.append("%s_count%s %d"
                             % (name, _fmt_labels(labels),
                                int(entry.get("count", 0))))
                lines.append("%s_sum%s %s"
                             % (name, _fmt_labels(labels),
                                repr(float(entry.get("sum", 0.0)))))
            else:
                lines.append("%s%s %s" % (name, _fmt_labels(labels),
                                          repr(float(entry["value"]))))
    return "\n".join(lines) + "\n"


#: THE process-wide registry.
REGISTRY = MetricsRegistry()


def get_registry():
    return REGISTRY
