"""Unified telemetry: the process-wide metrics registry and span tracer
shared by training, the distributed coordinator and the serving engine
(docs/OBSERVABILITY.md).

>>> from veles_tpu import telemetry
>>> reqs = telemetry.get_registry().counter(
...     "myapp_requests_total", "requests", labels=("route",))
>>> reqs.labels(route="/api").inc()
>>> with telemetry.span("work", phase="demo"):
...     pass  # no-op unless telemetry.tracing.enable() ran
"""

from veles_tpu.telemetry import registry, tracing  # noqa: F401
# alerts/federation/health (the cluster observability plane) are
# imported lazily by their consumers to keep bare imports cheap
from veles_tpu.telemetry.registry import (Counter, Gauge, Histogram,  # noqa: F401,E501
                                          MetricsRegistry, Reservoir,
                                          get_registry, percentile)
from veles_tpu.telemetry.tracing import (TraceBuffer, add_complete,  # noqa: F401,E501
                                         get_buffer, request_span, span,
                                         trace_context)
