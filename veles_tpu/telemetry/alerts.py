"""Rule-based SLO alerting over the metrics registry.

An :class:`AlertEngine` evaluates declarative rules against the live
registry (and, through it, the federated slave series' side effects:
health gauges, flight counters) and exposes the result three ways:

* ``veles_alerts_active{rule}`` gauges (1 firing / 0 clear) — the
  series ROADMAP item 3's autoscaler will key off;
* ``/alerts.json`` on the dashboard and the serving frontend
  (:meth:`AlertEngine.report`);
* structured log lines on every transition (logger ``veles.alerts``,
  message is a JSON object — grep-able, shippable).

Three rule kinds::

    # threshold: aggregated series value vs a bound, with hysteresis
    {"name": "serving_p95_high", "metric": "veles_serving_latency_ms",
     "field": "p95", "agg": "max", "op": ">", "threshold": 500.0,
     "for_s": 10.0, "clear_for_s": 10.0}

    # increase: a counter moved by more than `threshold` in `window_s`
    {"name": "non_finite_loss", "kind": "increase",
     "metric": "veles_flight_detector_trips_total",
     "labels": {"detector": "non_finite_loss"}, "window_s": 300.0}

    # burn_rate: multi-window error-budget burn (SRE-workbook style) —
    # fires only when EVERY window burns faster than its factor
    {"name": "serving_shed_burn", "kind": "burn_rate",
     "numerator": "veles_serving_rejected_total",
     "denominator": "veles_serving_requests_total",
     "objective": 0.01, "windows": [[60, 14.4], [300, 6.0]]}

``labels`` match a SUBSET of a series' labels; ``agg`` folds the
matching series (``max``/``min``/``sum``/``avg``); ``field`` picks the
histogram statistic (``p50``/``p95``/``p99``/``count``/``sum``).
Hysteresis: a threshold rule must breach continuously for ``for_s``
before firing and stay clear for ``clear_for_s`` before clearing, so
one noisy sample cannot flap an alert. Rate kinds keep a bounded
sample history per rule and refuse to fire until the history actually
spans the window (no guessing from partial data).

Extra rules load from the JSON file named by ``VELES_ALERT_RULES``
(either ``{"rules": [...]}`` or a bare list).
"""

import collections
import json
import logging
import os
import threading
import time

from veles_tpu.envknob import env_knob
from veles_tpu.telemetry.registry import get_registry

log = logging.getLogger("veles.alerts")

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

_AGGS = {
    "max": max,
    "min": min,
    "sum": sum,
    "avg": lambda values: sum(values) / len(values),
}

_KINDS = ("threshold", "increase", "burn_rate")


class Rule(object):
    """One validated alert rule (see the module docstring)."""

    _FIELDS = frozenset([
        "name", "kind", "metric", "labels", "field", "agg", "op",
        "threshold", "for_s", "clear_for_s", "window_s", "numerator",
        "denominator", "objective", "windows", "severity",
        "description"])

    def __init__(self, name, kind="threshold", metric=None, labels=None,
                 field="value", agg="max", op=">", threshold=None,
                 for_s=0.0, clear_for_s=0.0, window_s=60.0,
                 numerator=None, denominator=None, objective=None,
                 windows=None, severity="warning", description=""):
        if not name:
            raise ValueError("alert rule needs a name")
        if kind not in _KINDS:
            raise ValueError("rule %s: unknown kind %r (one of %s)"
                             % (name, kind, _KINDS))
        if op not in _OPS:
            raise ValueError("rule %s: unknown op %r" % (name, op))
        if agg not in _AGGS:
            raise ValueError("rule %s: unknown agg %r" % (name, agg))
        if kind == "burn_rate":
            if not numerator or not denominator or not objective:
                raise ValueError(
                    "rule %s: burn_rate needs numerator, denominator "
                    "and objective" % name)
            windows = [(float(w), float(f))
                       for w, f in (windows or [(60.0, 14.4),
                                                (300.0, 6.0)])]
        elif not metric:
            raise ValueError("rule %s: needs a metric" % name)
        if kind == "threshold" and threshold is None:
            raise ValueError("rule %s: needs a threshold" % name)
        self.name = name
        self.kind = kind
        self.metric = metric
        self.labels = dict(labels or {})
        self.field = field
        self.agg = agg
        self.op = op
        self.threshold = 0.0 if threshold is None else float(threshold)
        self.for_s = float(for_s)
        self.clear_for_s = float(clear_for_s)
        self.window_s = float(window_s)
        self.numerator = numerator
        self.denominator = denominator
        self.objective = float(objective) if objective else None
        self.windows = windows
        self.severity = severity
        self.description = description

    @classmethod
    def from_dict(cls, spec):
        unknown = set(spec) - cls._FIELDS
        if unknown:
            # a typo'd key would otherwise silently disable the intent
            raise ValueError("alert rule %r: unknown keys %s"
                             % (spec.get("name"), sorted(unknown)))
        return cls(**spec)

    def describe(self):
        out = {"name": self.name, "kind": self.kind,
               "severity": self.severity}
        if self.description:
            out["description"] = self.description
        if self.kind == "burn_rate":
            out.update(numerator=self.numerator,
                       denominator=self.denominator,
                       objective=self.objective,
                       windows=[list(w) for w in self.windows])
        else:
            out.update(metric=self.metric, op=self.op,
                       threshold=self.threshold)
            if self.labels:
                out["labels"] = dict(self.labels)
            if self.kind == "threshold":
                out.update(field=self.field, agg=self.agg,
                           for_s=self.for_s)
            else:
                out["window_s"] = self.window_s
        return out


class _RuleState(object):
    __slots__ = ("firing", "since", "breach_since", "clear_since",
                 "value", "samples")

    def __init__(self):
        self.firing = False
        self.since = None
        self.breach_since = None
        self.clear_since = None
        self.value = None
        self.samples = collections.deque(maxlen=4096)


#: shipped defaults — the series PR 3/4/7/9 already emit. Operators
#: extend (not replace) via VELES_ALERT_RULES.
DEFAULT_RULES = (
    {"name": "serving_p95_high", "metric": "veles_serving_latency_ms",
     "field": "p95", "agg": "max", "op": ">", "threshold": 500.0,
     "for_s": 10.0, "clear_for_s": 10.0,
     "description": "serving p95 latency above 500 ms"},
    {"name": "serving_queue_deep", "metric": "veles_serving_queue_depth",
     "agg": "max", "op": ">", "threshold": 64.0, "for_s": 10.0,
     "clear_for_s": 10.0,
     "description": "admission queue backing up"},
    {"name": "serving_shed_burn", "kind": "burn_rate",
     "numerator": "veles_serving_rejected_total",
     "denominator": "veles_serving_requests_total",
     "objective": 0.01, "windows": [[60.0, 14.4], [300.0, 6.0]],
     "severity": "critical",
     "description": "shedding >1% of requests at multi-window burn"},
    {"name": "serving_cache_collapse",
     "metric": "veles_serving_cache_hit_ratio", "agg": "min",
     "op": "<", "threshold": 0.05, "for_s": 30.0, "clear_for_s": 30.0,
     "description": "result-cache hit ratio collapsed (<5% over the "
                    "recent lookup window) — an invalidation storm or "
                    "a traffic shift away from repeats; the gauge only "
                    "publishes once the window is mature, so an idle "
                    "or cache-less server never fires this"},
    {"name": "autoscale_flap", "kind": "increase",
     "metric": "veles_autoscale_transitions_total", "window_s": 60.0,
     "threshold": 4.0, "clear_for_s": 120.0,
     "description": "5+ replica scale transitions within a minute — "
                    "the hysteresis/cooldown settings are too tight "
                    "for this traffic shape"},
    {"name": "tenant_shed_burn",
     "metric": "veles_serving_tenant_shed_ratio", "agg": "max",
     "op": ">", "threshold": 0.5, "for_s": 10.0, "clear_for_s": 30.0,
     "severity": "critical",
     "description": "some tenant is shedding over half of its recent "
                    "requests — its share is exhausted (raise its "
                    "weight, or its clients must back off)"},
    {"name": "input_starvation",
     "metric": "veles_input_starvation_fraction", "agg": "max",
     "op": ">", "threshold": 0.5, "for_s": 15.0, "clear_for_s": 15.0,
     "description": "step thread starved for input half the time"},
    {"name": "non_finite_loss", "kind": "increase",
     "metric": "veles_flight_detector_trips_total",
     "labels": {"detector": "non_finite_loss"}, "window_s": 300.0,
     "threshold": 0.0, "clear_for_s": 300.0, "severity": "critical",
     "description": "NaN/Inf loss detected by the flight recorder"},
    {"name": "slave_straggler", "metric": "veles_slave_health_state",
     "agg": "max", "op": ">=", "threshold": 1.0, "for_s": 0.0,
     "clear_for_s": 2.0,
     "description": "a slave is flagged straggler by the health scorer"},
    {"name": "slave_dead", "kind": "increase",
     "metric": "veles_slave_drops_total", "window_s": 300.0,
     "threshold": 0.0, "clear_for_s": 300.0, "severity": "critical",
     "description": "a slave was dropped (death/timeout/straggler) "
                    "and its jobs requeued in the last 5 minutes"},
    {"name": "spmd_participant_lost", "kind": "increase",
     "metric": "veles_spmd_participants_lost_total",
     "window_s": 300.0, "threshold": 0.0, "clear_for_s": 300.0,
     "severity": "critical",
     "description": "an SPMD mesh participant was lost in the last 5 "
                    "minutes; the elastic supervisor re-forms the "
                    "mesh at the surviving world size (ISSUE 13)"},
    {"name": "job_stuck", "metric": "veles_sched_oldest_pending_s",
     "agg": "max", "op": ">", "threshold": 300.0, "for_s": 30.0,
     "clear_for_s": 30.0,
     "description": "a scheduler job has been runnable (pending or "
                    "preempted) for over 5 minutes without a grant — "
                    "the pool is oversubscribed or a gang cannot fit"},
    {"name": "preempt_storm", "kind": "increase",
     "metric": "veles_sched_preemptions_total", "window_s": 60.0,
     "threshold": 5.0, "clear_for_s": 120.0,
     "description": "6+ preemptions within a minute — tenants are "
                    "thrashing each other; raise the min-run thrash "
                    "guard or rebalance tenant weights"},
    {"name": "tenant_starvation",
     "metric": "veles_sched_tenant_wait_s", "agg": "max", "op": ">",
     "threshold": 120.0, "for_s": 30.0, "clear_for_s": 30.0,
     "severity": "critical",
     "description": "some tenant's oldest runnable job has waited "
                    "over 2 minutes while others run — weighted-fair "
                    "placement is not reaching it (weights, pool "
                    "size, or a stuck victim gang)"},
    {"name": "job_loss_plateau",
     "metric": "veles_sched_job_loss_age_s", "agg": "max", "op": ">",
     "threshold": 600.0, "for_s": 30.0, "clear_for_s": 30.0,
     "description": "some job's federated training loss has not "
                    "CHANGED for over 10 minutes while its gang keeps "
                    "beating — training is wedged (dead optimizer, "
                    "zero LR, or a stuck input pipeline), not dead"},
    {"name": "job_mfu_collapse",
     "metric": "veles_sched_job_mfu", "agg": "min", "op": "<",
     "threshold": 0.05, "for_s": 60.0, "clear_for_s": 60.0,
     "description": "some job's model FLOPs utilization has sat "
                    "under 5% for a minute — the gang is burning its "
                    "grant on stalls (input wait, host sync, or a "
                    "pathological shard layout)"},
    {"name": "gang_silent",
     "metric": "veles_sched_beat_age_s", "agg": "max", "op": ">",
     "threshold": 30.0, "for_s": 10.0, "clear_for_s": 10.0,
     "severity": "critical",
     "description": "a RUNNING gang has pushed no beat-carried "
                    "telemetry delta for 30+ seconds — its rank-0 "
                    "pusher (or the whole gang) is hung while the "
                    "processes still look alive to the scheduler"},
)


class AlertEngine(object):
    """Evaluates rules; drive via :meth:`start` or external ticks."""

    def __init__(self, registry=None, rules=None,
                 min_eval_interval_s=0.25):
        self._registry = registry or get_registry()
        self._lock = threading.Lock()
        self._rules = []
        self._states = {}
        self._last_eval = 0.0
        self._min_eval_interval_s = min_eval_interval_s
        self._transitions = collections.deque(maxlen=256)
        self._stop = threading.Event()
        self._thread = None
        self._m_active = self._registry.gauge(
            "veles_alerts_active", "1 while the rule fires",
            labels=("rule",))
        self._m_transitions = self._registry.counter(
            "veles_alerts_transitions_total",
            "Alert fire/clear transitions", labels=("rule", "to"))
        self._m_evals = self._registry.counter(
            "veles_alerts_evaluations_total", "Rule evaluation sweeps")
        for spec in (DEFAULT_RULES if rules is None else rules):
            self.add_rule(spec)

    def add_rule(self, rule):
        if not isinstance(rule, Rule):
            rule = Rule.from_dict(dict(rule))
        with self._lock:
            self._rules = [r for r in self._rules
                           if r.name != rule.name] + [rule]
            # ALWAYS a fresh state: a replaced rule must not inherit
            # the old one's sample history (kind/window changes would
            # misjudge or crash) or its firing flag
            self._states[rule.name] = _RuleState()
        return rule

    def load_rules(self, path):
        with open(path) as f:
            spec = json.load(f)
        rules = spec["rules"] if isinstance(spec, dict) else spec
        for rule in rules:
            self.add_rule(rule)

    # -- series resolution -------------------------------------------------

    def _series_values(self, metric, labels, field):
        family = self._registry.get(metric)
        if family is None:
            return []
        values = []
        for series_labels, child in family.series():
            if any(str(series_labels.get(k)) != str(v)
                   for k, v in labels.items()):
                continue
            if family.kind == "histogram":
                if field == "count":
                    values.append(float(child.count))
                elif field == "sum":
                    values.append(float(child.sum))
                else:
                    try:
                        q = float(field.lstrip("p"))
                    except ValueError:
                        q = 95.0
                    values.append(float(child.percentile(q)))
            else:
                values.append(float(child.value))
        return values

    def _value(self, metric, labels, field="value", agg="sum"):
        values = self._series_values(metric, labels, field)
        return _AGGS[agg](values) if values else None

    @staticmethod
    def _window_ref(samples, now, window_s):
        """Newest sample at least ``window_s`` old (None = history too
        short to judge this window — refuse to fire on guesses)."""
        ref = None
        for sample in samples:
            if now - sample[0] >= window_s:
                ref = sample
            else:
                break
        return ref

    # -- evaluation --------------------------------------------------------

    def _check(self, rule, state, now):
        """-> (condition_bool, display_value)."""
        if rule.kind == "threshold":
            value = self._value(rule.metric, rule.labels, rule.field,
                                rule.agg)
            if value is None:
                return False, None
            return _OPS[rule.op](value, rule.threshold), value
        if rule.kind == "increase":
            # an unminted counter is a zero, not an unknown — sample
            # it so the history matures while the run is still quiet
            # (burn_rate below treats absent counters the same way)
            cur = self._value(rule.metric, rule.labels,
                              agg="sum") or 0.0
            state.samples.append((now, cur))
            ref = self._window_ref(state.samples, now, rule.window_s)
            self._prune(state.samples, now, rule.window_s)
            if ref is None:
                return False, 0.0
            inc = cur - ref[1]
            if inc < 0:  # counter reset upstream
                inc = cur
            return _OPS[rule.op](inc, rule.threshold), inc
        # burn_rate
        num = self._value(rule.numerator, rule.labels, agg="sum") or 0.0
        den = self._value(rule.denominator, rule.labels, agg="sum") or 0.0
        state.samples.append((now, num, den))
        longest = max(w for w, _ in rule.windows)
        worst_burn = None
        fired = True
        for window_s, factor in rule.windows:
            ref = self._window_ref(state.samples, now, window_s)
            if ref is None:
                fired = False
                continue
            dn, dd = num - ref[1], den - ref[2]
            rate = (dn / dd) if dd > 0 else 0.0
            burn = rate / rule.objective
            if worst_burn is None or window_s == rule.windows[0][0]:
                worst_burn = burn
            if burn <= factor:
                fired = False
        self._prune(state.samples, now, longest)
        return fired, worst_burn

    @staticmethod
    def _prune(samples, now, window_s):
        # keep a little slack past the window so _window_ref always
        # finds a reference once the history matured
        horizon = now - 2.0 * max(window_s, 1.0)
        while samples and samples[0][0] < horizon:
            samples.popleft()

    def evaluate(self, now=None, force=False):
        """One sweep over every rule. Cheap; call per heartbeat/tick."""
        now = time.time() if now is None else now
        with self._lock:
            if not force and \
                    now - self._last_eval < self._min_eval_interval_s:
                return
            self._last_eval = now
            rules = list(self._rules)
            self._m_evals.inc()
            for rule in rules:
                state = self._states[rule.name]
                try:
                    condition, value = self._check(rule, state, now)
                except Exception:
                    log.warning("alert rule %s failed to evaluate",
                                rule.name, exc_info=True)
                    continue
                state.value = value
                if condition:
                    state.clear_since = None
                    if state.breach_since is None:
                        state.breach_since = now
                    if not state.firing and \
                            now - state.breach_since >= rule.for_s:
                        self._transition(rule, state, True, now)
                else:
                    state.breach_since = None
                    if state.firing:
                        if state.clear_since is None:
                            state.clear_since = now
                        if now - state.clear_since >= rule.clear_for_s:
                            self._transition(rule, state, False, now)
                self._m_active.labels(rule=rule.name).set(
                    1.0 if state.firing else 0.0)

    def _transition(self, rule, state, firing, now):
        state.firing = firing
        state.since = now
        state.breach_since = None
        state.clear_since = None
        to = "firing" if firing else "clear"
        record = {"t": time.time(), "rule": rule.name, "to": to,
                  "severity": rule.severity, "value": state.value,
                  "description": rule.description}
        self._transitions.append(record)
        self._m_transitions.labels(rule=rule.name, to=to).inc()
        # structured line: the message IS a JSON object, so a log
        # shipper needs no custom parser to route on severity/rule
        (log.warning if firing else log.info)(
            "ALERT %s", json.dumps(record, default=str))

    # -- reading / lifecycle ----------------------------------------------

    def active(self):
        with self._lock:
            return sorted(r.name for r in self._rules
                          if self._states[r.name].firing)

    def report(self, evaluate=True):
        """The ``/alerts.json`` body."""
        if evaluate:
            self.evaluate()
        with self._lock:
            rules = []
            for rule in self._rules:
                state = self._states[rule.name]
                entry = rule.describe()
                entry.update(firing=state.firing, value=state.value,
                             since=state.since)
                rules.append(entry)
            return {"generated_t": time.time(), "rules": rules,
                    "transitions": list(self._transitions)}

    def start(self, interval_s=1.0):
        """Background evaluation thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(interval_s,), daemon=True,
                name="alert-engine")
            self._thread.start()
        return self

    def _loop(self, interval_s):
        while not self._stop.wait(interval_s):
            try:
                self.evaluate()
            except Exception:
                log.warning("alert sweep failed", exc_info=True)

    def stop(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)


_engine = None
_engine_lock = threading.Lock()


def get_engine():
    """THE process alert engine: default rules + VELES_ALERT_RULES."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = AlertEngine()
            path = env_knob("VELES_ALERT_RULES")
            if path:
                try:
                    _engine.load_rules(path)
                except (OSError, ValueError, KeyError) as e:
                    log.warning("could not load VELES_ALERT_RULES "
                                "%s: %s", path, e)
        return _engine


def reset_engine():
    """Tests only: stop the thread and drop the singleton."""
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.stop()
        _engine = None
