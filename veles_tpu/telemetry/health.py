"""Per-slave health / straggler scoring with hysteresis.

VELES's master schedules from observed slave behavior (heartbeats with
timeout-based death detection, per-slave load metrics — PAPER.md); the
coordinator's reaper already handles *death*. This module detects the
worse failure mode for a synchronous epoch: the slave that is ALIVE
but slow — the straggler every other slave ends up waiting on.

A :class:`HealthScorer` keeps, per slave, EWMAs of the signals the
control plane already measures (job wall time, heartbeat RTT, exchange
encode/decode time) plus the observed heartbeat cadence. Each
evaluation compares every slave's EWMAs against the **median of its
peers** (ratios, so the score is load- and model-size-invariant) and
adds a **silence** component — heartbeat age over the slave's own
beat-gap EWMA — which is what catches a SIGSTOP'd/paused process
within a few intervals. The score is the worst component ratio.

Hysteresis, both ways:

* entering ``straggler`` needs the score at/above ``enter_ratio`` for
  ``enter_evals`` CONSECUTIVE evaluations, and the job-time component
  only counts once ``job_streak`` consecutive jobs ran slow — so one
  slow job (a GC pause, a shard fault) cannot flap a slave;
* returning to ``healthy`` needs the score below ``exit_ratio`` (a
  LOWER bar than entry) for ``exit_evals`` consecutive evaluations.

State surfaces as ``veles_slave_health_state{slave}`` (0 healthy / 1
straggler) and ``veles_slave_health_score{slave}`` gauges — the series
the SLO alert engine's ``slave_straggler`` rule and ROADMAP item 5's
job-reassignment logic consume — and the ``/cluster.json`` table.
"""

import collections
import logging
import threading
import time

from veles_tpu.telemetry.registry import get_registry, percentile

log = logging.getLogger("veles.health")

#: EWMA smoothing factor for every component
ALPHA = 0.3

#: ratio denominators are floored per component so small absolute
#: values can never look like a 2x straggler — only meaningfully
#: large signals move the score. The RTT floor is deliberately far
#: above loopback/LAN numbers: a slave's own compute holds its GIL
#: and inflates its self-measured heartbeat RTT by tens of ms (seen
#: on a 2-core CPU run), which is load, not a degraded link; a
#: genuinely swapping host or saturated path measures hundreds.
FLOORS_MS = {"rtt_ms": 100.0, "job_ms": 50.0,
             "encode_ms": 10.0, "decode_ms": 10.0}


class _Ewma(object):
    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def update(self, x):
        x = float(x)
        self.value = x if self.value is None else \
            ALPHA * x + (1.0 - ALPHA) * self.value


class _SlaveHealth(object):
    __slots__ = ("ewma", "last_beat", "gap_ewma", "slow_streak",
                 "job_seen", "state", "breach_streak", "clear_streak",
                 "score", "components", "since")

    def __init__(self, now):
        self.ewma = {}            # component -> _Ewma
        self.last_beat = None     # monotonic time of the last beat
        self.gap_ewma = _Ewma()   # observed inter-beat gap (s)
        self.slow_streak = 0      # consecutive slow jobs
        self.job_seen = 0         # jobs observed (warmup gating)
        self.state = "healthy"
        self.breach_streak = 0
        self.clear_streak = 0
        self.score = 1.0
        self.components = {}
        self.since = now


class HealthScorer(object):
    """Scores slaves; thread-safe; cheap enough to run per heartbeat."""

    def __init__(self, registry=None, enter_ratio=2.0, exit_ratio=1.3,
                 enter_evals=2, exit_evals=3, job_streak=2,
                 job_warmup=2, silence_min_s=0.25,
                 min_eval_interval_s=0.05):
        self.enter_ratio = enter_ratio
        self.exit_ratio = exit_ratio
        self.enter_evals = enter_evals
        self.exit_evals = exit_evals
        self.job_streak = job_streak
        self.job_warmup = job_warmup
        self.silence_min_s = silence_min_s
        self._min_eval_interval_s = min_eval_interval_s
        self._lock = threading.Lock()
        self._slaves = {}
        self._medians = {}
        self._last_eval = 0.0
        self._transitions = collections.deque(maxlen=256)
        registry = registry or get_registry()
        self._m_score = registry.gauge(
            "veles_slave_health_score",
            "Worst peer-relative component ratio (1 = at the median)",
            labels=("slave",))
        self._m_state = registry.gauge(
            "veles_slave_health_state",
            "0 healthy, 1 straggler", labels=("slave",))
        self._m_transitions = registry.counter(
            "veles_slave_health_transitions_total",
            "Health state transitions", labels=("slave", "to"))

    # -- feeding -----------------------------------------------------------

    def observe(self, sid, job_ms=None, rtt_ms=None, encode_ms=None,
                decode_ms=None, beat=False, now=None, create=True):
        """Fold one observation batch into the slave's EWMAs.

        ``create=False`` drops observations for unknown slaves —
        callers running OUTSIDE the coordinator lock (the launcher's
        encode/decode timers) use it so a slave reaped mid-callback
        cannot be resurrected as a permanent phantom after
        :meth:`remove` already ran."""
        now = time.monotonic() if now is None else now
        with self._lock:
            st = self._slaves.get(sid)
            if st is None:
                if not create:
                    return
                st = self._slaves[sid] = _SlaveHealth(now)
            if beat:
                if st.last_beat is not None:
                    st.gap_ewma.update(max(now - st.last_beat, 1e-6))
                st.last_beat = now
            if job_ms is not None:
                # a slave's first jobs absorb its XLA compile — honest
                # wall time, dishonest straggler evidence (the peers
                # compiled before it joined): gate them out
                st.job_seen += 1
                if st.job_seen <= self.job_warmup:
                    job_ms = None
            for name, value in (("job_ms", job_ms), ("rtt_ms", rtt_ms),
                                ("encode_ms", encode_ms),
                                ("decode_ms", decode_ms)):
                if value is None:
                    continue
                ewma = st.ewma.get(name)
                if ewma is None:
                    ewma = st.ewma[name] = _Ewma()
                ewma.update(value)
            if job_ms is not None:
                # the raw-job slow streak is the anti-flap guard: the
                # job component only scores once >=job_streak raw jobs
                # in a row ran slower than enter_ratio x the peer median
                median = self._medians.get("job_ms")
                if median is not None and float(job_ms) > \
                        self.enter_ratio * max(median,
                                               FLOORS_MS["job_ms"]):
                    st.slow_streak += 1
                else:
                    st.slow_streak = 0

    # -- scoring -----------------------------------------------------------

    def evaluate(self, now=None, force=False):
        """Re-score every slave (throttled; call freely per beat)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not force and \
                    now - self._last_eval < self._min_eval_interval_s:
                return
            self._last_eval = now
            # peer medians per component (over every slave with data)
            medians = {}
            for name in FLOORS_MS:
                values = sorted(
                    st.ewma[name].value for st in self._slaves.values()
                    if st.ewma.get(name) is not None and
                    st.ewma[name].value is not None)
                if values:
                    medians[name] = percentile(values, 50)
            self._medians = medians
            # expected beat cadence across the fleet: the fallback for
            # a slave silenced before its OWN gap EWMA formed (paused
            # right after its first beat — it must still be flaggable)
            gap_values = sorted(
                st.gap_ewma.value for st in self._slaves.values()
                if st.gap_ewma.value is not None)
            gap_median = percentile(gap_values, 50) if gap_values \
                else None
            for sid, st in self._slaves.items():
                components = {}
                peers = len(self._slaves) - 1
                for name, floor in FLOORS_MS.items():
                    ewma = st.ewma.get(name)
                    if peers < 1 or ewma is None or ewma.value is None \
                            or name not in medians:
                        continue
                    ratio = ewma.value / max(medians[name], floor)
                    if name == "job_ms" and \
                            st.slow_streak < self.job_streak:
                        # one slow job must not flip the state
                        ratio = min(ratio, 1.0)
                    components[name] = round(ratio, 3)
                gap = st.gap_ewma.value
                if gap is None:
                    gap = gap_median
                if st.last_beat is not None and gap is not None:
                    age = now - st.last_beat
                    if age >= self.silence_min_s:
                        components["silence"] = round(
                            age / max(gap, 0.05), 3)
                st.components = components
                st.score = max(components.values()) if components \
                    else 1.0
                self._m_score.labels(slave=sid).set(st.score)
                if st.state == "healthy":
                    st.breach_streak = st.breach_streak + 1 \
                        if st.score >= self.enter_ratio else 0
                    if st.breach_streak >= self.enter_evals:
                        self._transition(sid, st, "straggler", now)
                else:
                    st.clear_streak = st.clear_streak + 1 \
                        if st.score < self.exit_ratio else 0
                    if st.clear_streak >= self.exit_evals:
                        self._transition(sid, st, "healthy", now)
                self._m_state.labels(slave=sid).set(
                    1.0 if st.state == "straggler" else 0.0)

    def _transition(self, sid, st, to, now):
        """State flip + transition log. Caller holds ``self._lock``
        (only ``evaluate`` enters here, under it)."""
        st.state = to
        st.since = now
        st.breach_streak = 0
        st.clear_streak = 0
        self._transitions.append({
            "t": time.time(), "slave": sid, "to": to,
            "score": st.score, "components": dict(st.components)})
        self._m_transitions.labels(slave=sid, to=to).inc()
        (log.warning if to == "straggler" else log.info)(
            "slave %s -> %s (score %.2f, components %s)",
            sid, to, st.score, st.components)

    # -- reading / lifecycle ----------------------------------------------

    def state(self, sid):
        with self._lock:
            st = self._slaves.get(sid)
            return st.state if st is not None else None

    def table(self):
        """``{sid: {state, score, components, state_age_s,
        beat_age_s}}`` — the /cluster.json health columns."""
        now = time.monotonic()
        with self._lock:
            return {sid: {
                "state": st.state,
                "score": round(st.score, 3),
                "components": dict(st.components),
                "state_age_s": round(now - st.since, 3),
                "beat_age_s": None if st.last_beat is None
                else round(now - st.last_beat, 3),
            } for sid, st in self._slaves.items()}

    def transitions(self):
        with self._lock:
            return list(self._transitions)

    def remove(self, sid):
        """Forget a dropped slave and GC its labeled children (the
        transition HISTORY stays in the bounded ring + logs)."""
        with self._lock:
            removed = self._slaves.pop(sid, None)
        self._m_score.remove(slave=sid)
        self._m_state.remove(slave=sid)
        self._m_transitions.remove(slave=sid)
        return removed is not None

    def reset(self):
        with self._lock:
            slaves = list(self._slaves)
            self._slaves.clear()
            self._medians = {}
            self._transitions.clear()
            self._last_eval = 0.0
        for sid in slaves:
            self._m_score.remove(slave=sid)
            self._m_state.remove(slave=sid)
            self._m_transitions.remove(slave=sid)


_scorer = None
_scorer_lock = threading.Lock()


def get_scorer():
    """THE process health scorer (master side)."""
    global _scorer
    with _scorer_lock:
        if _scorer is None:
            _scorer = HealthScorer()
        return _scorer


def reset_scorer():
    """Tests only."""
    global _scorer
    with _scorer_lock:
        if _scorer is not None:
            _scorer.reset()
        _scorer = None
