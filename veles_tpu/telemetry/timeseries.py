"""Bounded per-series metric history: the ``/history.json`` store.

The registry (:mod:`veles_tpu.telemetry.registry`) answers "what is
the value NOW"; this module gives the observability plane memory — a
:class:`SeriesStore` keeps a bounded ring of ``(t, value)`` points per
labeled series, fed from ordinary registry snapshots, and serves the
``/history.json?series=&since=`` query the dashboard sparklines and
ROADMAP item 5's canary comparison read.

Bounding is three-way, so a hostile cardinality or a month-long run
cannot grow the store without limit:

* **resolution** — points landing inside the same ``resolution_s``
  bucket overwrite (last-writer-wins), so a tight ingest loop cannot
  out-append the wall clock;
* **downsample-on-overflow** — when a series ring fills, every other
  point is dropped and the series' resolution doubles (classic RRD
  behaviour): old history gets coarser, it never gets truncated to a
  fixed recent window;
* **retention + max-series** — points older than ``retention_s`` are
  pruned on ingest, and series beyond ``max_series`` are counted into
  ``veles_history_dropped_series_total`` instead of stored.

The store NEVER interpolates: a process that stopped pushing (a
preempted gang, a dead worker) leaves a visible gap between real
points — exactly what an operator reading a preemption window wants.
To keep that property, the snapshot pump skips families that have a
dedicated gap-aware writer (``veles_sched_job_*`` — the scheduler
records those directly, RUNNING gangs only); everything else it
would ingest is a live value whose staleness IS the signal.

Knobs (catalog: docs/CONFIGURATION.md):

* ``VELES_HISTORY_RESOLUTION_S`` — base bucket width (default 0.5 s);
* ``VELES_HISTORY_POINTS`` — ring capacity per series (default 512);
* ``VELES_HISTORY_RETENTION_S`` — max point age (default 3600 s);
* ``VELES_HISTORY_MAX_SERIES`` — store-wide series cap (default 1024);
* ``VELES_HISTORY_INTERVAL_S`` — background pump period (default 1 s).
"""

import threading
import time

from veles_tpu.envknob import env_knob
from veles_tpu.telemetry.registry import get_registry


def _env_float(name, default):
    return env_knob(name, default, parse=float, on_error="default")


def _env_int(name, default):
    return env_knob(name, default, parse=int, on_error="default")


class _Series(object):
    """One labeled series' ring: ``points`` is a list of ``[t, v]``
    ascending in ``t``; ``res_s`` doubles on every overflow."""

    __slots__ = ("points", "res_s")

    def __init__(self, res_s):
        self.points = []
        self.res_s = res_s

    def add(self, t, value, max_points):
        if self.points:
            last_t = self.points[-1][0]
            if t < last_t:
                return          # out-of-order point: drop, never sort
            if int(t // self.res_s) == int(last_t // self.res_s):
                self.points[-1][1] = value   # same bucket: overwrite
                return
        self.points.append([t, value])
        if len(self.points) > max_points:
            # downsample: halve the density, double the resolution —
            # keep the NEWEST point exactly (it anchors "now")
            kept = self.points[::-2]
            kept.reverse()
            self.points = kept
            self.res_s *= 2.0

    def prune(self, horizon):
        points = self.points
        i = 0
        while i < len(points) and points[i][0] < horizon:
            i += 1
        if i:
            del points[:i]


class SeriesStore(object):
    """Bounded history of scalar series, fed from registry snapshots
    (:meth:`ingest`) or single points (:meth:`record`)."""

    # veles_sched_job_*: the scheduler's publish pass records these
    # itself, RUNNING gangs only, so a preemption is a hole in the
    # series. The snapshot pump would re-ingest the stale mirror
    # gauge of a displaced job and bridge that hole — so the pump
    # never touches families that have a gap-aware writer.
    _DEFAULT_EXCLUDE = ("veles_history_", "veles_sched_job_")

    def __init__(self, resolution_s=None, max_points=None,
                 retention_s=None, max_series=None, registry=None,
                 exclude_prefixes=_DEFAULT_EXCLUDE):
        self.resolution_s = float(
            resolution_s if resolution_s is not None
            else _env_float("VELES_HISTORY_RESOLUTION_S", 0.5))
        self.max_points = int(
            max_points if max_points is not None
            else _env_int("VELES_HISTORY_POINTS", 512))
        self.retention_s = float(
            retention_s if retention_s is not None
            else _env_float("VELES_HISTORY_RETENTION_S", 3600.0))
        self.max_series = int(
            max_series if max_series is not None
            else _env_int("VELES_HISTORY_MAX_SERIES", 1024))
        self.exclude_prefixes = tuple(exclude_prefixes)
        self._lock = threading.Lock()
        self._series = {}           # (name, labels_key) -> _Series
        self._stop = threading.Event()
        self._thread = None
        reg = registry or get_registry()
        self._m_series = reg.gauge(
            "veles_history_series", "Series held by the history store")
        self._m_points = reg.counter(
            "veles_history_points_total",
            "Points accepted into the history store")
        self._m_dropped = reg.counter(
            "veles_history_dropped_series_total",
            "Series refused because the store is at max_series")

    # -- writing -----------------------------------------------------------

    @staticmethod
    def _key(name, labels):
        return (name, tuple(sorted((labels or {}).items())))

    def record(self, name, labels, value, now=None):
        """Append one point (used by tests and direct feeders)."""
        now = time.time() if now is None else now
        key = self._key(name, labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    self._m_dropped.inc()
                    return False
                series = self._series[key] = _Series(self.resolution_s)
                self._m_series.set(len(self._series))
            series.add(now, float(value), self.max_points)
            series.prune(now - self.retention_s)
            self._m_points.inc()
        return True

    def ingest(self, snapshot, now=None):
        """Feed every counter/gauge series of a registry snapshot
        (histograms are windows already — the registry serves those)."""
        now = time.time() if now is None else now
        for kind in ("gauges", "counters"):
            for name, family in snapshot.get(kind, {}).items():
                if name.startswith(self.exclude_prefixes):
                    continue
                for entry in family.get("series", ()):
                    if "value" not in entry:
                        continue
                    self.record(name, entry.get("labels") or {},
                                entry["value"], now=now)

    # -- reading -----------------------------------------------------------

    def query(self, series=None, since=None, now=None):
        """The ``/history.json`` body. ``series`` filters by family
        name (exact or prefix); ``since`` returns only points strictly
        newer than the cursor — a poller passes the previous reply's
        ``now`` back and receives just the delta."""
        now = time.time() if now is None else now
        since = float(since) if since is not None else None
        out = []
        with self._lock:
            items = sorted(self._series.items())
            for (name, labels_key), data in items:
                if series and not name.startswith(series):
                    continue
                points = data.points
                if since is not None:
                    points = [p for p in points if p[0] > since]
                out.append({"name": name,
                            "labels": dict(labels_key),
                            "res_s": data.res_s,
                            "points": [list(p) for p in points]})
        return {"now": now, "series": out}

    def series_count(self):
        with self._lock:
            return len(self._series)

    def drop(self, name=None):
        """Drop series (all, or one family) — tests / job GC."""
        with self._lock:
            if name is None:
                self._series.clear()
            else:
                for key in [k for k in self._series if k[0] == name]:
                    del self._series[key]
            self._m_series.set(len(self._series))

    # -- the pump ----------------------------------------------------------

    def start(self, interval_s=None, registry=None):
        """Background snapshot pump (idempotent): every ``interval_s``
        the process registry's snapshot is ingested, so any surface
        serving ``/history.json`` has history without every metric
        producer knowing the store exists."""
        interval_s = float(
            interval_s if interval_s is not None
            else _env_float("VELES_HISTORY_INTERVAL_S", 1.0))
        reg = registry or get_registry()
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(interval_s, reg), daemon=True,
                name="history-pump")
            self._thread.start()
        return self

    def _loop(self, interval_s, registry):
        while not self._stop.wait(interval_s):
            try:
                self.ingest(registry.snapshot())
            except Exception:   # history must never kill its host
                pass

    def stop(self):
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)


_store = None
_store_lock = threading.Lock()


def get_history():
    """THE process history store (created on first use)."""
    global _store
    with _store_lock:
        if _store is None:
            _store = SeriesStore()
        return _store


def reset_history():
    """Tests only: stop the pump and drop the singleton."""
    global _store
    with _store_lock:
        if _store is not None:
            _store.stop()
        _store = None
