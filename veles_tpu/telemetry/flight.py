"""Training flight recorder: bounded black box + failure detectors.

ROADMAP item 5's detection substrate. A :class:`FlightRecorder` keeps a
bounded ring of recent training notes (step completions, loss/metric
deltas, detector observations), the tail of the process log and — when
tracing is on — the most recent spans. When something goes wrong it
writes everything as ONE atomic JSON "flight record" an operator can
load after the fact, the way a post-incident investigation wants it:

* **NaN/Inf loss** — the fused runner feeds every sweep's per-batch
  loss vector to :meth:`FlightRecorder.check_losses`; the first
  non-finite entry trips a record naming the offending epoch + batch;
* **gradient-norm divergence** — per-batch global gradient norms
  (:class:`~veles_tpu.train.step.FusedTrainer` tracks them inside the
  train scan) trip when one exceeds ``VELES_GRAD_SPIKE_FACTOR``× the
  rolling p95 of the preceding window, or goes non-finite;
* **stall watchdog** — the runner arms the watchdog around each
  compiled sweep; if no completion lands within
  ``VELES_STALL_FACTOR``× the rolling p95 of previous sweeps (floored
  at ``VELES_STALL_MIN_S``), the watchdog writes a ``faulthandler``
  all-thread stack dump next to the flight record — the "why is it
  hung" evidence that is unrecoverable once the process is killed;
* **unhandled step exceptions** — the runner's crash path dumps the
  same record before re-raising.

Records land under ``VELES_FLIGHT_DIR`` (default ``flight_records/``)
as ``flight-<utc>-<reason>.json`` via write-to-temp + rename, so a
watching process (or the web dashboard's link) never reads a torn
file. Dumps are rate-limited per reason — a NaN that recurs every
batch must not fill the disk.
"""

import collections
import faulthandler
import json
import logging
import os
import threading
import time

from veles_tpu.envknob import env_knob
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.registry import Reservoir, get_registry

#: how many trailing trace-buffer spans a record embeds
SPAN_TAIL = 200


def _env_float(name, default):
    return env_knob(name, default, parse=float, on_error="default")


class LogTail(logging.Handler):
    """Bounded ring of the most recent formatted log lines."""

    def __init__(self, capacity=200):
        super(LogTail, self).__init__()
        self.records = collections.deque(maxlen=capacity)

    def emit(self, record):
        try:
            self.records.append({
                "t": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": record.getMessage()})
        except Exception:  # a broken record must not break training
            pass

    def tail(self):
        return list(self.records)


class FlightRecorder(object):
    """The black box. One per process (:func:`get_recorder`)."""

    def __init__(self, capacity=512, log_capacity=200, out_dir=None,
                 stall_factor=None, stall_min_s=None,
                 grad_spike_factor=None, poll_s=1.0,
                 min_dump_interval_s=5.0):
        self.out_dir = out_dir or env_knob(
            "VELES_FLIGHT_DIR", "flight_records")
        self.stall_factor = (stall_factor if stall_factor is not None
                             else _env_float("VELES_STALL_FACTOR", 10.0))
        self.stall_min_s = (stall_min_s if stall_min_s is not None
                            else _env_float("VELES_STALL_MIN_S", 60.0))
        self.grad_spike_factor = (
            grad_spike_factor if grad_spike_factor is not None
            else _env_float("VELES_GRAD_SPIKE_FACTOR", 25.0))
        self._notes = collections.deque(maxlen=capacity)
        self._log_tail = LogTail(log_capacity)
        self._lock = threading.Lock()
        self._durations = Reservoir(128)    # sweep seconds
        self._grad_norms = Reservoir(512)   # recent finite norms
        self._grad_seen = 0
        self._last_dump = {}                # reason -> perf_counter
        self._last_path = None
        self._min_dump_interval_s = min_dump_interval_s
        self._dump_listeners = []
        registry = get_registry()
        self._m_records = registry.counter(
            "veles_flight_records_total",
            "Flight records written", labels=("reason",))
        self._m_trips = registry.counter(
            "veles_flight_detector_trips_total",
            "Detector trips (may be rate-limited before dumping)",
            labels=("detector",))
        # watchdog state
        self._poll_s = poll_s
        self._armed = None        # (label, perf_deadline) or None
        self._watch_stop = threading.Event()
        self._watch_thread = None
        logging.getLogger().addHandler(self._log_tail)

    # -- the ring ----------------------------------------------------------

    def note(self, kind, **data):
        data["t"] = time.time()
        data["kind"] = kind
        with self._lock:
            self._notes.append(data)

    def notes(self):
        with self._lock:
            return list(self._notes)

    # -- step bookkeeping + detectors --------------------------------------

    def observe_step(self, phase, duration_s, loss=None, epoch=None):
        """One completed sweep: feeds the stall watchdog's rolling p95
        and the ring."""
        with self._lock:
            self._durations.add(duration_s)
        self.note("step", phase=phase, epoch=epoch,
                  ms=round(duration_s * 1e3, 3),
                  loss=None if loss is None else float(loss))

    def check_losses(self, losses, epoch=None, phase="train"):
        """Trip on the first non-finite entry of a sweep's per-batch
        loss vector. Returns the flight-record path when tripped."""
        import numpy
        values = numpy.asarray(losses, numpy.float64).reshape(-1)
        finite = numpy.isfinite(values)
        if finite.all():
            return None
        batch = int(numpy.argmin(finite))
        self._m_trips.labels(detector="non_finite_loss").inc()
        return self.dump("non_finite_loss", epoch=epoch, phase=phase,
                         batch=batch, value=repr(values[batch]),
                         step="epoch %s batch %d of %s sweep"
                              % (epoch, batch, phase))

    def observe_grad_norms(self, norms, epoch=None):
        """Per-batch global gradient norms of one train sweep: trip on
        non-finite or a spike above factor× the rolling p95 of the
        PRECEDING window (so the spike does not judge itself)."""
        import numpy
        values = numpy.asarray(norms, numpy.float64).reshape(-1)
        path = None
        for batch, value in enumerate(values):
            if not numpy.isfinite(value):
                self._m_trips.labels(detector="grad_norm").inc()
                path = path or self.dump(
                    "non_finite_grad_norm", epoch=epoch, batch=batch,
                    step="epoch %s batch %d" % (epoch, batch))
                continue
            with self._lock:
                seen = self._grad_seen
                p95 = self._grad_norms.percentile(95) if seen else 0.0
            if seen >= 32 and value > self.grad_spike_factor * max(
                    p95, 1e-30):
                self._m_trips.labels(detector="grad_norm").inc()
                path = path or self.dump(
                    "grad_norm_divergence", epoch=epoch, batch=batch,
                    norm=float(value), rolling_p95=float(p95),
                    factor=self.grad_spike_factor,
                    step="epoch %s batch %d" % (epoch, batch))
            with self._lock:
                self._grad_norms.add(value)
                self._grad_seen += 1
        if len(values):
            finite = values[numpy.isfinite(values)]
            self.note("grad_norms", epoch=epoch,
                      last=float(values[-1]),
                      max=float(finite.max()) if len(finite) else None)
        return path

    # -- stall watchdog ----------------------------------------------------

    def _stall_deadline_s(self):
        with self._lock:
            values = self._durations.sorted_values()
        if len(values) < 3:   # no steady state yet (first sweep holds
            return None       # the whole compile) — do not watch
        from veles_tpu.telemetry.registry import percentile
        return max(self.stall_factor * percentile(values, 95),
                   self.stall_min_s)

    def step_begin(self, label):
        """Arm the watchdog for one sweep (no-op until a rolling p95
        exists). Starts the watcher thread on first use."""
        deadline = self._stall_deadline_s()
        if deadline is None:
            return
        with self._lock:
            self._armed = (label, time.perf_counter() + deadline,
                           deadline)
            if self._watch_thread is None:
                self._watch_thread = threading.Thread(
                    target=self._watch_loop, daemon=True,
                    name="flight-watchdog")
                self._watch_thread.start()

    def step_end(self):
        with self._lock:
            self._armed = None

    def _watch_loop(self):
        while not self._watch_stop.wait(self._poll_s):
            with self._lock:
                armed = self._armed
            if armed is None:
                continue
            label, deadline, budget = armed
            if time.perf_counter() < deadline:
                continue
            with self._lock:
                # fire once per arm; step_end clears it anyway
                self._armed = None
            self._m_trips.labels(detector="stall").inc()
            self.dump("stall", step=label, budget_s=round(budget, 3),
                      stall_factor=self.stall_factor,
                      dump_stacks=True)

    # -- dump listeners ----------------------------------------------------

    def add_dump_listener(self, fn):
        """``fn(reason, path, context)`` runs after every successful
        dump — the hook a distributed slave uses to notify its master
        so ONE correlated cluster record replaces N disjoint files."""
        with self._lock:
            self._dump_listeners.append(fn)
        return fn

    def remove_dump_listener(self, fn):
        with self._lock:
            if fn in self._dump_listeners:
                self._dump_listeners.remove(fn)

    # -- dumping -----------------------------------------------------------

    def record_exception(self, exc, step=None):
        """The crash path: dump before the exception unwinds the run."""
        return self.dump("exception", step=step,
                         exception=type(exc).__name__,
                         message=str(exc))

    def dump(self, reason, dump_stacks=False, **context):
        """Write one flight record atomically; returns its path (or
        None when rate-limited / the directory is unwritable)."""
        now = time.perf_counter()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and \
                    now - last < self._min_dump_interval_s:
                return None
            self._last_dump[reason] = now
        try:
            os.makedirs(self.out_dir, exist_ok=True)
        except OSError:
            return None
        # name must be unique across PROCESSES sharing a flight dir
        # (master + slaves tripping on the same NaN batch in the same
        # second): rate-limiting is per-process state, and os.replace
        # would silently destroy the other black boxes right when an
        # incident investigation needs them — so the host, pid and a
        # per-process sequence number join the stamp
        import socket
        with self._lock:
            self._seq = getattr(self, "_seq", 0) + 1
            seq = self._seq
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        base = "flight-%s-%s-%s-%d-%d" % (
            stamp, reason, socket.gethostname(), os.getpid(), seq)
        path = os.path.join(self.out_dir, base + ".json")
        stacks_path = None
        if dump_stacks:
            # the stacks are the part that evaporates if the operator
            # kills the stuck process — write them FIRST
            stacks_path = os.path.join(self.out_dir, base + ".stacks.txt")
            try:
                with open(stacks_path, "w") as f:
                    faulthandler.dump_traceback(file=f, all_threads=True)
            except Exception:
                stacks_path = None
        from veles_tpu.telemetry import profiler
        record = {
            "reason": reason,
            "time": time.time(),
            "context": context,
            "notes": self.notes(),
            "log_tail": self._log_tail.tail(),
            "spans": tracing.get_buffer().events()[-SPAN_TAIL:],
            "metrics": get_registry().snapshot(),
            "phases_ms": profiler.phase_report(),
            "stacks_file": stacks_path,
        }
        tmp = "%s.%d.tmp" % (path, os.getpid())
        try:
            with open(tmp, "w") as f:
                json.dump(record, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        with self._lock:
            self._last_path = path
            listeners = list(self._dump_listeners)
        self._m_records.labels(reason=reason).inc()
        logging.getLogger("flight").error(
            "flight record (%s) written to %s", reason, path)
        for fn in listeners:
            try:  # a broken notifier must not mask the record itself
                fn(reason, path, dict(context))
            except Exception:
                logging.getLogger("flight").warning(
                    "flight dump listener failed", exc_info=True)
        return path

    def last_record_path(self):
        with self._lock:
            return self._last_path

    def stop(self):
        self._watch_stop.set()
        # swap under the lock, join outside it (the watcher takes the
        # same lock to dump; joining while holding it would deadlock)
        with self._lock:
            thread, self._watch_thread = self._watch_thread, None
        if thread is not None:
            thread.join(timeout=5)
        logging.getLogger().removeHandler(self._log_tail)


_recorder = None
_recorder_lock = threading.Lock()


def get_recorder():
    """THE process flight recorder (created on first use)."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def last_record_path():
    with _recorder_lock:
        return _recorder.last_record_path() if _recorder else None


def reset_recorder():
    """Tests only: detach the log handler and drop the singleton."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            _recorder.stop()
        _recorder = None


def load_record(path):
    """Parse a flight record back (the operator/test loading path)."""
    with open(path) as f:
        record = json.load(f)
    for key in ("reason", "time", "notes", "log_tail", "metrics"):
        if key not in record:
            raise ValueError("not a flight record: missing %r" % key)
    return record
