"""Performance attribution: cost analysis, roofline, memory, phases.

The PR 4 telemetry core records *how long* things take; this module
attributes *where* the FLOPs, bytes and seconds go, so the MFU plateau
(ROADMAP item 2) and the cold-start wall (item 4) can be chased with
numbers instead of ablations:

* **per-op cost attribution** — every jitted computation the system
  runs (train/eval segments, serving replica forwards, autotuned
  Pallas candidates) registers with the :class:`CostBook`, which
  harvests XLA's ``Compiled.cost_analysis()`` (analytic FLOPs and
  bytes-accessed of the whole executable) and pairs it with the op's
  *measured* wall time from the registry to publish achieved FLOP/s,
  arithmetic intensity and a compute-vs-memory-bound roofline verdict
  against the device's peak specs (``veles_op_flops``,
  ``veles_op_bytes``, ``veles_op_ms``);

* **step MFU** — the train segment's analytic FLOPs over its measured
  wall time, as a fraction of device peak (``veles_step_mfu``) — the
  number BENCH rounds have been estimating indirectly;

* **startup phases** — :func:`phase` marks the first-class cold-start
  stages (``dataset_generate``, ``dataset_load``, ``autotune_load``,
  ``compile``, ``warmup``, ``first_step``) as spans + one-shot
  ``veles_phase_ms{phase}`` gauges, so a bench round can prove which
  stage a cold-start fix actually killed;

* **memory** — :class:`MemorySampler` periodically folds
  ``device.memory_stats()`` (live/peak HBM per device) and the host
  RSS into gauges; :func:`dump_memory_profile` writes
  ``jax.profiler.device_memory_profile`` (per-buffer attribution,
  pprof format) alongside a ``--trace-out`` dump.

Everything here is advisory instrumentation: every harvest path is
wrapped so a cost-analysis failure can never take down training, and
``VELES_COST_ATTRIBUTION=0`` turns harvesting off entirely.
"""

import json
import os
import threading
import time

from veles_tpu.envknob import env_flag, env_knob
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.registry import get_registry

#: (peak dense TFLOP/s, HBM GB/s) per JAX ``device_kind`` prefix —
#: public per-chip specs, bf16 peak where the hardware has one. The
#: roofline ridge point is their ratio. Unknown kinds (CPU included)
#: fall back to the VELES_PEAK_TFLOPS / VELES_HBM_GBPS env overrides,
#: else attribution reports absolute numbers with MFU/verdict omitted.
DEVICE_SPECS = (
    ("TPU v6", (918.0, 1640.0)),
    ("TPU v5p", (459.0, 2765.0)),
    ("TPU v5e", (197.0, 819.0)),
    ("TPU v5 lite", (197.0, 819.0)),
    ("TPU v4", (275.0, 1228.0)),
    ("TPU v3", (123.0, 900.0)),
    ("TPU v2", (45.0, 700.0)),
)


def _env_positive(name):
    """float(env) or None — a typo'd override must degrade to
    "unknown peak" (no MFU/verdict), never unwind a training sweep."""
    value = env_knob(name, parse=float, on_error="default")
    return value if value is not None and value > 0 else None


def device_spec(device=None):
    """``(peak_flops_per_s, hbm_bytes_per_s)`` for ``device`` (default:
    the first local device), or ``(None, None)`` when unknown."""
    tflops = _env_positive("VELES_PEAK_TFLOPS")
    gbps = _env_positive("VELES_HBM_GBPS")
    if tflops and gbps:
        return tflops * 1e12, gbps * 1e9
    kind = ""
    try:
        if device is None:
            import jax
            device = jax.local_devices()[0]
        kind = device.device_kind
    except Exception:
        pass
    for prefix, (tf, gb) in DEVICE_SPECS:
        if kind.startswith(prefix):
            return tf * 1e12, gb * 1e9
    return ((tflops * 1e12 if tflops else None),
            (gbps * 1e9 if gbps else None))


def attribution_enabled():
    return env_flag("VELES_COST_ATTRIBUTION", True)


def _first(costs, *keys):
    """cost_analysis() returns one dict per program; sum a key over
    them (TPU returns a single-element list, CPU sometimes several)."""
    if isinstance(costs, dict):
        costs = [costs]
    total = 0.0
    for c in costs or ():
        for key in keys:
            if key in c:
                total += float(c[key])
                break
    return total


def harvest_cost_analysis(compiled):
    """``{"flops": f, "bytes": b}`` from a ``jax.stages.Compiled`` (or
    anything with ``cost_analysis()``); None when unavailable."""
    try:
        costs = compiled.cost_analysis()
    except Exception:
        return None
    if not costs:
        return None
    return {"flops": _first(costs, "flops"),
            "bytes": _first(costs, "bytes accessed")}


#: bytes per element for the HLO shape tokens collective outputs use
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

_COLLECTIVE_RE = None


def collective_bytes_estimate(compiled):
    """Per-execution bytes moved by the COMPILER-INSERTED collectives
    of a partitioned program (ISSUE 15): ``{"bytes": b, "count": n}``,
    or None when the program text is unavailable.

    ``cost_analysis()`` reports only whole-program aggregates (no
    per-instruction-category split on any backend this repo meets), so
    the collective share is read from the optimized HLO itself: the
    summed output-shape bytes of every ``all-reduce`` / ``all-gather``
    / ``all-to-all`` / ``collective-permute`` / ``reduce-scatter``
    instruction, per participating device. Async pairs are counted
    once via their ``-done`` half — a ``-start``'s result tuple
    aliases the operand buffers too, which would double the bytes —
    while synchronous lowerings (CPU) match on the bare name. An estimate — the gradient psum's wire
    traffic depends on the ICI algorithm — but it moves exactly when
    the partitioning moves, which is what the gauge is for."""
    global _COLLECTIVE_RE
    import re
    if _COLLECTIVE_RE is None:
        _COLLECTIVE_RE = (
            re.compile(r"=\s*([^=]*?)\s"
                       r"(?:all-reduce|all-gather|all-to-all|"
                       r"collective-permute|reduce-scatter|"
                       r"collective-broadcast)(?:-done)?\("),
            re.compile(r"([a-z]\w*)\[([0-9,]*)\]"))
    line_re, shape_re = _COLLECTIVE_RE
    try:
        texts = compiled.as_text()
    except Exception:
        return None
    if not texts:
        return None
    if isinstance(texts, str):
        texts = [texts]
    total = 0
    count = 0
    for text in texts:
        for match in line_re.finditer(text):
            count += 1
            for dtype, dims in shape_re.findall(match.group(1)):
                size = _HLO_DTYPE_BYTES.get(dtype)
                if size is None:
                    continue
                n = 1
                for d in dims.split(","):
                    if d:
                        n *= int(d)
                total += n * size
    return {"bytes": total, "count": count}


class CostBook(object):
    """Per-op ledger: analytic cost (harvested once per op) joined with
    measured wall time (observed per call) and the device roofline.

    ``note_cost(op, flops, bytes)`` records analytics directly (the
    autotuner path — it computes kernel FLOPs itself);
    ``harvest(op, jit_fn, args, kwargs)`` lowers+compiles the function
    for its cost analysis — with the persistent XLA cache warm this is
    cheap, and it runs at most once per op name.
    """

    def __init__(self, registry=None):
        registry = registry or get_registry()
        self._lock = threading.Lock()
        self._costs = {}          # op -> {"flops", "bytes"}
        self._harvested = set()   # op names already attempted
        self._g_flops = registry.gauge(
            "veles_op_flops", "Analytic FLOPs per execution of a "
            "compiled op (XLA cost model)", labels=("op",))
        self._g_bytes = registry.gauge(
            "veles_op_bytes", "Analytic bytes accessed per execution "
            "of a compiled op (XLA cost model)", labels=("op",))
        self._h_ms = registry.histogram(
            "veles_op_ms", "Measured wall time per compiled-op call",
            labels=("op",))
        self._g_mfu = registry.gauge(
            "veles_step_mfu", "Model FLOPs utilization of the train "
            "step (analytic FLOPs / measured time / device peak)")
        self._g_coll = registry.gauge(
            "veles_op_collective_bytes",
            "Estimated bytes moved per execution by the "
            "compiler-inserted collectives of a partitioned op "
            "(summed HLO collective output shapes, per device)",
            labels=("op",))

    # -- recording ---------------------------------------------------------

    def note_cost(self, op, flops, bytes_accessed):
        with self._lock:
            self._costs[op] = {"flops": float(flops),
                               "bytes": float(bytes_accessed)}
            self._harvested.add(op)
        self._g_flops.labels(op=op).set(flops)
        self._g_bytes.labels(op=op).set(bytes_accessed)

    def needs_harvest(self, op):
        if not attribution_enabled():
            return False
        with self._lock:
            return op not in self._harvested

    def harvest(self, op, jit_fn, args, kwargs=None):
        """Lower+compile ``jit_fn`` at ``args`` and record its cost
        analysis under ``op``. Never raises; at most one attempt per
        op (failures record an empty entry so they are not retried on
        the hot path)."""
        with self._lock:
            if op in self._harvested:
                return
            self._harvested.add(op)
        try:
            with tracing.span("cost_harvest", op=op):
                compiled = jit_fn.lower(*args, **(kwargs or {})).compile()
            cost = harvest_cost_analysis(compiled)
        except Exception:
            cost = None
        if cost is None:
            return
        # the partitioned (GSPMD) ops also surface their collective
        # share — zero collectives is a meaningful reading too (a
        # "sharded" step that inserted none is not actually sharded)
        coll = collective_bytes_estimate(compiled)
        if coll is not None:
            cost["collective_bytes"] = coll["bytes"]
            cost["collective_count"] = coll["count"]
        with self._lock:
            self._costs[op] = cost
        self._g_flops.labels(op=op).set(cost["flops"])
        self._g_bytes.labels(op=op).set(cost["bytes"])
        if coll is not None:
            self._g_coll.labels(op=op).set(coll["bytes"])

    def observe_ms(self, op, elapsed_s):
        self._h_ms.labels(op=op).observe(elapsed_s * 1e3)

    def cost(self, op):
        with self._lock:
            return dict(self._costs.get(op) or {}) or None

    # -- derived -----------------------------------------------------------

    def record_step_mfu(self, op, elapsed_s):
        """Set ``veles_step_mfu`` from one measured execution of ``op``
        (the train segment). Returns the MFU or None."""
        cost = self.cost(op)
        peak, _ = device_spec()
        if not cost or not cost["flops"] or not peak or elapsed_s <= 0:
            return None
        mfu = cost["flops"] / elapsed_s / peak
        self._g_mfu.set(mfu)
        return mfu

    def report(self):
        """The attribution table: one row per op with analytic cost,
        measured time (registry percentiles) and the roofline verdict.
        JSON-able — this is what ``/profile.json`` and
        ``profile_step.py --attribution`` render."""
        peak_flops, peak_bw = device_spec()
        ridge = (peak_flops / peak_bw
                 if peak_flops and peak_bw else None)
        with self._lock:
            costs = {op: dict(c) for op, c in self._costs.items()}
        measured = {}
        for labels, child in self._h_ms.series():
            measured[labels.get("op")] = child.summary()
        ops = []
        for op in sorted(set(costs) | set(measured)):
            cost = costs.get(op) or {}
            times = measured.get(op) or {}
            row = {"op": op,
                   "flops": cost.get("flops"),
                   "bytes": cost.get("bytes"),
                   "calls": times.get("count", 0),
                   "p50_ms": times.get("p50"),
                   "p95_ms": times.get("p95")}
            if "collective_bytes" in cost:
                row["collective_bytes"] = cost["collective_bytes"]
                row["collective_count"] = cost.get("collective_count")
            flops, byts = cost.get("flops"), cost.get("bytes")
            if flops and byts:
                row["arithmetic_intensity"] = flops / byts
                if ridge is not None:
                    row["bound"] = ("compute"
                                    if row["arithmetic_intensity"] >= ridge
                                    else "memory")
            p50 = times.get("p50")
            if flops and p50:
                row["achieved_tflops"] = flops / (p50 / 1e3) / 1e12
                if peak_flops:
                    row["utilization"] = (flops / (p50 / 1e3) /
                                          peak_flops)
            if byts and p50:
                row["achieved_gbps"] = byts / (p50 / 1e3) / 1e9
            ops.append(row)
        out = {"ops": ops,
               "device": {"peak_tflops": (peak_flops / 1e12
                                          if peak_flops else None),
                          "hbm_gbps": (peak_bw / 1e9
                                       if peak_bw else None),
                          "ridge_flops_per_byte": ridge}}
        try:
            out["step_mfu"] = self._g_mfu.value
        except ValueError:  # never set this process
            out["step_mfu"] = None
        return out


_book = None
_book_lock = threading.Lock()


def get_cost_book():
    global _book
    with _book_lock:
        if _book is None:
            _book = CostBook()
        return _book


def reset_cost_book():
    """Tests only: drop the book so a fresh registry gets fresh gauges."""
    global _book
    with _book_lock:
        _book = None


class timed_op(object):
    """Context manager timing one execution of a named op into the
    cost book (span + ``veles_op_ms``); the cheap always-on half of
    attribution (the harvest half is one-time)."""

    __slots__ = ("op", "_start", "_book")

    def __init__(self, op, book=None):
        self.op = op
        self._book = book or get_cost_book()

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        elapsed = time.perf_counter() - self._start
        self._book.observe_ms(self.op, elapsed)
        tracing.add_complete("op:%s" % self.op, self._start, elapsed)
        return False


# -- startup phases ----------------------------------------------------------

PHASES = ("dataset_generate", "dataset_load", "autotune_load",
          "compile", "warmup", "replica_warmup", "pipeline_fill",
          "offload_plan", "first_step")

_phase_lock = threading.Lock()
_phase_ms = {}  # phase -> cumulative ms this process


class _Phase(object):
    __slots__ = ("name", "_start")

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        elapsed = time.perf_counter() - self._start
        record_phase(self.name, elapsed)
        tracing.add_complete("phase:%s" % self.name, self._start,
                             elapsed)
        return False


def phase(name):
    """Span + ``veles_phase_ms{phase}`` for one startup stage. Phases
    ACCUMULATE within a process (two datasets load = one total), which
    is the quantity a cold-start bench wants."""
    return _Phase(name)


def record_phase(name, elapsed_s):
    with _phase_lock:
        _phase_ms[name] = _phase_ms.get(name, 0.0) + elapsed_s * 1e3
        total = _phase_ms[name]
    get_registry().gauge(
        "veles_phase_ms", "Cumulative startup-phase wall time",
        labels=("phase",)).labels(phase=name).set(total)


def phase_report():
    """``{phase: ms}`` in canonical order (extras appended)."""
    with _phase_lock:
        snap = dict(_phase_ms)
    out = {}
    for name in PHASES:
        if name in snap:
            out[name] = round(snap.pop(name), 3)
    for name in sorted(snap):
        out[name] = round(snap[name], 3)
    return out


def reset_phases():
    """Tests only."""
    with _phase_lock:
        _phase_ms.clear()


# -- memory ------------------------------------------------------------------


def host_rss_bytes():
    """Resident set size of this process, or None off-Linux."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def sample_memory(registry=None):
    """One sample of per-device HBM + host RSS into gauges. Returns
    the JSON-able sample (what ``/profile.json`` embeds)."""
    registry = registry or get_registry()
    g_live = registry.gauge(
        "veles_hbm_live_bytes", "Live device memory", labels=("device",))
    g_peak = registry.gauge(
        "veles_hbm_peak_bytes", "Peak device memory", labels=("device",))
    g_limit = registry.gauge(
        "veles_hbm_limit_bytes", "Device memory capacity",
        labels=("device",))
    g_rss = registry.gauge("veles_host_rss_bytes", "Host process RSS")
    sample = {"devices": {}, "host_rss_bytes": None}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        devices = ()
    for dev in devices:
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            stats = {}
        if not stats:
            continue
        label = "%s:%d" % (dev.platform, dev.id)
        live = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        entry = {}
        if live is not None:
            g_live.labels(device=label).set(live)
            entry["live_bytes"] = int(live)
        if peak is not None:
            g_peak.labels(device=label).set(peak)
            entry["peak_bytes"] = int(peak)
        if limit is not None:
            g_limit.labels(device=label).set(limit)
            entry["limit_bytes"] = int(limit)
        if entry:
            sample["devices"][label] = entry
    rss = host_rss_bytes()
    if rss is not None:
        g_rss.set(rss)
        sample["host_rss_bytes"] = rss
    return sample


class MemorySampler(object):
    """Daemon thread folding :func:`sample_memory` into the registry
    every ``interval`` seconds. Start once per process; stop() is only
    needed by tests (the thread is a daemon)."""

    def __init__(self, interval=5.0, registry=None):
        self.interval = float(interval)
        self._registry = registry
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="memory-sampler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                sample_memory(self._registry)
            except Exception:
                pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_sampler = None


def start_memory_sampler(interval=None):
    """Process-wide sampler (idempotent). ``VELES_MEMORY_SAMPLE_S``
    overrides the interval; 0 disables."""
    global _sampler
    if interval is None:
        env = _env_positive("VELES_MEMORY_SAMPLE_S")
        if env is None and \
                env_knob("VELES_MEMORY_SAMPLE_S") is not None:
            return None  # explicit 0 / unparsable: sampling off
        interval = env if env is not None else 5.0
    if interval <= 0:
        return None
    with _book_lock:
        if _sampler is None:
            _sampler = MemorySampler(interval=interval).start()
    return _sampler


def stop_memory_sampler():
    """Join the process-wide sampler (tests / orderly shutdown)."""
    global _sampler
    with _book_lock:
        sampler, _sampler = _sampler, None
    if sampler is not None:
        sampler.stop()


def dump_memory_profile(path):
    """Write ``jax.profiler.device_memory_profile()`` (per-buffer HBM
    attribution, pprof gzip) to ``path``. Returns True on success —
    callers pair this with a ``--trace-out`` dump."""
    try:
        import jax.profiler
        blob = jax.profiler.device_memory_profile()
        tmp = "%s.%d.tmp" % (path, os.getpid())
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return True
    except Exception:
        return False


# -- the /profile.json payload ----------------------------------------------


def profile_report():
    """Everything the observability surfaces render: attribution table,
    step MFU, startup phases, the latest memory sample, and the last
    flight-record path (when the recorder has written one)."""
    from veles_tpu.telemetry import flight
    report = get_cost_book().report()
    report["phases_ms"] = phase_report()
    try:
        report["memory"] = sample_memory()
    except Exception:
        report["memory"] = None
    report["flight_record"] = flight.last_record_path()
    return report


def render_profile_json():
    return json.dumps(profile_report())
