"""Mid-workflow interaction (re-designs ``veles/interaction.py:49``).

:class:`Shell` embeds an IPython console inside a running workflow:
link it into the loop and press ``i``+Enter while training — the next
time the unit fires it drops into a console with ``workflow`` and
``units`` in scope. Non-TTY runs are no-ops, so the unit is safe to
leave wired in production configs.

The manhole/SIGUSR debugging of the reference's thread pool
(``veles/thread_pool.py:520-568``) survives as
:func:`install_stack_dump_handler` (``SIGUSR1`` → all thread stacks to
stderr) and :func:`debug_deadlocks` (warn at exit when extra threads
are still alive).
"""

import select
import signal
import sys
import threading
import traceback

from veles_tpu.distributable import TriviallyDistributable
from veles_tpu.units import Unit


class Shell(Unit, TriviallyDistributable):
    """Runs embedded IPython when the user asks for it."""

    BANNER1 = "\nveles_tpu interactive console"
    BANNER2 = "Type in 'workflow' or 'units' to start"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("view_group", "SERVICE")
        super(Shell, self).__init__(workflow, **kwargs)
        #: force-interact on the next run() regardless of stdin (tests,
        #: programmatic use)
        self.interact_next_run = False

    def init_unpickled(self):
        super(Shell, self).init_unpickled()
        self.shell_ = None

    @property
    def interactive(self):
        launcher = self.launcher
        return bool(launcher is not None and
                    getattr(launcher, "is_interactive", False))

    def initialize(self, **kwargs):
        if self.interactive:
            return  # already inside a REPL: embedding would recurse
        try:
            from IPython.terminal.embed import InteractiveShellEmbed
        except ImportError:
            self.warning("IPython is not available; Shell disabled")
            return
        self.shell_ = InteractiveShellEmbed(banner1=self.BANNER1,
                                            banner2=self.BANNER2)

    def interact(self, extra_locals=None):
        workflow = self.workflow                      # noqa: F841
        units = list(self.workflow.units)             # noqa: F841
        ns = dict(locals())
        ns.update(extra_locals or {})
        if self.shell_ is None:
            self.warning("no shell to interact with")
            return
        self.shell_(local_ns=ns)

    def run(self):
        if self.interact_next_run:
            self.interact_next_run = False
            self.interact()
            return
        if self.interactive or self.shell_ is None or not sys.stdin.isatty():
            return
        # one non-blocking peek at stdin: 'i' + Enter opens the console
        ready, _, _ = select.select([sys.stdin], [], [], 0)
        if ready and sys.stdin.readline()[:1] == "i":
            self.interact()


class Manhole(object):
    """UNIX-socket debug REPL (the reference's bundled manhole,
    ``veles/external/manhole.py`` + ``thread_pool.py:527-533``).

    ``Manhole(locals={"workflow": wf}).start()`` listens on an abstract
    unix socket; connect with ``socket`` + a line-based client (or
    ``nc -U``) and evaluate Python in the provided namespace. Each
    line is evaluated (expression → repr sent back) or executed.
    """

    def __init__(self, path=None, locals=None):
        self.path = path
        self.locals = dict(locals or {})
        self._listener = None
        self._accepting = False
        self._own_dir = None

    def start(self):
        import os
        import socket
        import tempfile
        if self.path is None:
            # a private 0700 directory: a world-writable /tmp path is
            # both squat-able and, under a loose umask, connectable by
            # other local users (this is an eval() endpoint)
            self._own_dir = tempfile.mkdtemp(prefix="veles_tpu_manhole_")
            self.path = os.path.join(self._own_dir, "manhole.sock")
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.path)
        os.chmod(self.path, 0o600)
        self._listener.listen(2)
        self._accepting = True
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="Manhole").start()
        return self

    def _accept_loop(self):
        listener = self._listener  # stop() may null the attribute
        while self._accepting:
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(sock,),
                             daemon=True, name="Manhole-client").start()

    def _serve(self, sock):
        f = sock.makefile("rw")
        with sock:
            f.write("veles_tpu manhole (%s)\n>>> " %
                    ", ".join(sorted(self.locals)) )
            f.flush()
            for line in f:
                line = line.rstrip("\n")
                if line in ("exit", "quit", "exit()", "quit()"):
                    return
                try:
                    try:
                        result = eval(line, self.locals)  # noqa: S307
                        if result is not None:
                            f.write(repr(result) + "\n")
                    except SyntaxError:
                        exec(line, self.locals)  # noqa: S102
                except SystemExit:
                    return
                except BaseException as e:
                    f.write("%s: %s\n" % (type(e).__name__, e))
                f.write(">>> ")
                f.flush()

    def stop(self):
        import os
        import shutil
        self._accepting = False
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        if self.path and os.path.exists(self.path):
            os.unlink(self.path)
        if self._own_dir is not None:
            shutil.rmtree(self._own_dir, ignore_errors=True)
            self._own_dir = None


def print_thread_stacks(file=None):
    """Dump every live thread's stack (``thread_pool.py:536-546``)."""
    file = file or sys.stderr
    tmap = {thr.ident: thr.name for thr in threading.enumerate()}
    for tid, stack in sys._current_frames().items():
        print("-" * 80, file=file)
        print("Thread #%d (%s):" % (tid, tmap.get(tid, "<unknown>")),
              file=file)
        traceback.print_stack(stack, file=file)
    file.flush()


def install_stack_dump_handler(signum=None):
    """SIGUSR1 → stack dump on demand (``thread_pool.py:520-525``).

    Only callable from the main thread (signal module restriction);
    returns the previous handler.
    """
    if signum is None:
        signum = getattr(signal, "SIGUSR1", None)
        if signum is None:  # pragma: no cover - non-POSIX
            return None
    return signal.signal(signum, lambda sig, frame: print_thread_stacks())


#: thread names that legitimately outlive the run
KNOWN_RUNNING_THREADS = (
    "MainThread", "pydevd", "status-notifier", "web-status",
    "graphics", "-http", "-accept", "heartbeat",
)


def debug_deadlocks(file=None):
    """Warn + dump stacks if suspicious threads are still alive
    (``thread_pool.py:552-568``). Returns the suspects."""
    suspects = [
        thr for thr in threading.enumerate()
        if thr.is_alive() and not thr.daemon and
        not any(name in thr.name for name in KNOWN_RUNNING_THREADS)]
    if suspects:
        print("Possible deadlock: %d non-daemon threads still alive: %s"
              % (len(suspects), [t.name for t in suspects]),
              file=file or sys.stderr)
        print_thread_stacks(file=file)
    return suspects
