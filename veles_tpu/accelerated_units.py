"""Device-backed compute units.

Re-designs ``veles/accelerated_units.py``. The reference's
AcceleratedUnit assembled OpenCL/CUDA source (defines + Jinja2), built
programs with an on-disk binary cache, and rebound
``ocl_run``/``cuda_run``/``numpy_run`` per device. On TPU the whole
pipeline collapses:

* "kernel source assembly" → a pure JAX function; static shapes/dtypes
  are its closure, so re-`jit` per configuration replaces re-templating;
* "program build + binary cache" → XLA compilation + its persistent
  compilation cache (`jax.config.jax_compilation_cache_dir`);
* backend rebinding survives: units implement ``jax_init``/``jax_run``
  (used by both the tpu and cpu devices) and optionally
  ``numpy_init``/``numpy_run`` (oracle path); :meth:`AcceleratedUnit.
  initialize` binds the right pair exactly like the reference's
  ``assign_backend_methods`` (``veles/backends.py:244-262``).
"""

from veles_tpu.backends import default_device
from veles_tpu.config import root
from veles_tpu.memory import Array
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow

#: maps Device.BACKEND → method prefix units implement
_METHOD_PREFIX = {"tpu": "jax", "cpu": "jax", "numpy": "numpy"}


class AcceleratedUnit(Unit):
    """Base for units whose run() executes on the device."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.force_numpy = kwargs.pop(
            "force_numpy", root.common.engine.get("force_numpy", False))
        self.sync_run = kwargs.pop("sync_run", False)
        super(AcceleratedUnit, self).__init__(workflow, **kwargs)
        self.device = None

    def init_unpickled(self):
        super(AcceleratedUnit, self).init_unpickled()
        self._backend_run_ = None
        self._jit_cache_ = {}

    # -- device binding ----------------------------------------------------

    def initialize(self, device=None, **kwargs):
        self.device = device if device is not None else default_device()
        prefix = self._method_prefix()
        if prefix != "numpy":
            # per-device kernel-plan consultation: pull this device's
            # persistent autotune database into memory before the unit
            # traces, the way the reference loaded its per-device
            # BLOCK_SIZE cache before building programs
            # (``veles/backends.py:672-731``). One disk read per
            # process; never fatal (a missing/corrupt cache is empty).
            from veles_tpu.ops import autotune
            try:
                autotune.warm()
            except Exception:
                pass
        init_fn = getattr(self, prefix + "_init", None)
        self._backend_run_ = getattr(self, prefix + "_run")
        if init_fn is not None:
            init_fn()
        return None

    def _method_prefix(self):
        if self.force_numpy or self.device is None or not self.device.exists:
            return "numpy"
        return _METHOD_PREFIX[self.device.backend_name]

    # -- run dispatch ------------------------------------------------------

    def run(self):
        result = self._backend_run_()
        if self.sync_run and self.device is not None:
            self.device.sync()
        return result

    def numpy_run(self):
        raise NotImplementedError(
            "%s has no numpy fallback" % type(self).__name__)

    def jax_run(self):
        raise NotImplementedError(
            "%s has no jax implementation" % type(self).__name__)

    # -- helpers -----------------------------------------------------------

    def init_vectors(self, *arrays):
        """Attach Arrays to this unit's device (devmem allocation)."""
        for arr in arrays:
            if isinstance(arr, Array):
                arr.initialize(self.device)

    def unmap_vectors(self, *arrays):
        """Flush host writes before launching device compute."""
        for arr in arrays:
            if isinstance(arr, Array):
                arr.unmap()

    def map_vectors_read(self, *arrays):
        for arr in arrays:
            if isinstance(arr, Array):
                arr.map_read()

    def jit(self, fn, **jit_kwargs):
        """jit ``fn`` once per (fn, options); placed on this device."""
        key = (fn, tuple(sorted(jit_kwargs.items())))
        cached = self._jit_cache_.get(key)
        if cached is None:
            import jax
            cached = jax.jit(fn, **jit_kwargs)
            self._jit_cache_[key] = cached
        return cached


class DeviceBenchmark(object):
    """Computing-power estimation (``accelerated_units.py:706-824``)."""

    _cache = {}

    @classmethod
    def estimate(cls, device, size=1000, repeats=3):
        key = (getattr(device, "BACKEND", None),
               getattr(device, "device_index", 0), size, repeats)
        if key not in cls._cache:
            if device is None or not device.exists:
                cls._cache[key] = 1.0
            else:
                from veles_tpu.ops.benchmark import gemm_benchmark
                cls._cache[key] = gemm_benchmark(
                    size=size, repeats=repeats,
                    device=device)["computing_power"]
        return cls._cache[key]


class AcceleratedWorkflow(Workflow):
    """Workflow owning a device; passes it down at initialize.

    (``veles/accelerated_units.py:843-858``)
    """

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super(AcceleratedWorkflow, self).__init__(workflow, **kwargs)
        self.device = None

    def initialize(self, device=None, **kwargs):
        self.device = device if device is not None else default_device()
        kwargs["device"] = self.device
        return super(AcceleratedWorkflow, self).initialize(**kwargs)

    @property
    def computing_power(self):
        return DeviceBenchmark.estimate(self.device)

    def __getstate__(self):
        state = super(AcceleratedWorkflow, self).__getstate__()
        state["device"] = None  # re-attached on initialize after restore
        return state
