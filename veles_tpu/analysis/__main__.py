"""``python -m veles_tpu.analysis`` — run the checkers, apply the
baseline, exit non-zero on any unsuppressed finding.

Default invocation analyzes the full ``veles_tpu/`` tree against the
committed baseline and the doc contracts; this is what the CI lint
gate runs (``scripts/lint_gate.py`` adds the gate bookkeeping on top,
mirroring ``perf_gate.py``).
"""

import argparse
import os
import sys

from veles_tpu.analysis import core

DOC_FILES = ("docs/OBSERVABILITY.md", "docs/CONFIGURATION.md",
             "docs/STATIC_ANALYSIS.md", "docs/TELEMETRY.md",
             "docs/SERVING.md", "docs/ELASTIC.md", "docs/GSPMD.md",
             "docs/PERF.md", "README.md")

#: non-package files that legitimately mint metrics / read knobs —
#: scanned so set-difference checks (MET004) see the whole story
AUX_FILES = ("bench.py", "scripts")


def repo_root_of(path):
    """Nearest ancestor of ``path`` containing veles_tpu/ (the repo
    checkout the doc contracts live in)."""
    path = os.path.abspath(path)
    while True:
        if os.path.isdir(os.path.join(path, "veles_tpu")):
            return path
        parent = os.path.dirname(path)
        if parent == path:
            return os.getcwd()
        path = parent


def build_project(paths, repo_root, complete=None):
    if complete is None:
        # a run over the whole package may assert set-difference
        # contracts (docs naming dead code); partial runs must not
        complete = any(
            os.path.abspath(p) == os.path.join(repo_root, "veles_tpu")
            for p in paths)
    docs = [os.path.join(repo_root, d) for d in DOC_FILES]
    aux = [os.path.join(repo_root, a) for a in AUX_FILES]
    return core.Project.load(
        paths, repo_root, doc_paths=docs,
        aux_paths=[a for a in aux if os.path.exists(a)],
        complete=complete)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.analysis",
        description="veles-analyze: lock-order, tracer-hygiene and "
                    "contract-drift checkers")
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="files/dirs to analyze (default: the veles_tpu package)")
    parser.add_argument(
        "--baseline", default=None, metavar="JSON",
        help="suppression baseline "
             "(default scripts/lint_baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, suppressing nothing")
    parser.add_argument(
        "--write-baseline", metavar="JSON",
        help="write current findings as a suppression baseline "
             "(requires --reason) and exit 0")
    parser.add_argument(
        "--reason", default="",
        help="reason recorded on every suppression --write-baseline "
             "emits")
    parser.add_argument(
        "--checker", action="append", dest="checkers",
        choices=("locks", "tracer", "metrics", "knobs"),
        help="run only this checker (repeatable; default all)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text")
    args = parser.parse_args(argv)

    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    repo_root = repo_root_of(here)
    paths = args.paths or [os.path.join(repo_root, "veles_tpu")]
    project = build_project(paths, repo_root)
    findings = core.run_all(project, args.checkers)

    if args.write_baseline:
        if not args.reason.strip():
            parser.error("--write-baseline requires --reason "
                         "(every suppression must say why)")
        core.write_baseline(args.write_baseline, findings, args.reason)
        print("wrote %d suppression(s) to %s"
              % (len(findings), args.write_baseline))
        return 0

    baseline = {}
    if not args.no_baseline:
        baseline_path = args.baseline or os.path.join(
            repo_root, "scripts", "lint_baseline.json")
        baseline = core.load_baseline(baseline_path)
    new, suppressed, stale = core.apply_baseline(findings, baseline)

    if args.format == "json":
        import json
        print(json.dumps({
            "new": [f.render() for f in new],
            "suppressed": [f.render() for f in suppressed],
            "stale_suppressions": stale,
            "files_analyzed": len(project.modules),
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        if suppressed:
            print("-- %d baseline-suppressed finding(s) not shown "
                  "(see scripts/lint_baseline.json)" % len(suppressed))
        for fp in stale:
            print("-- stale suppression %s: no checker produces it "
                  "any more — remove it from the baseline" % fp)
        print("veles-analyze: %d file(s), %d finding(s), %d new"
              % (len(project.modules), len(findings), len(new)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
