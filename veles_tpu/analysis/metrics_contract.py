"""Metric-contract checker: code <-> docs/OBSERVABILITY.md <-> alerts.

The telemetry plane has three parties that must agree on family names:
the code minting them through
:class:`veles_tpu.telemetry.registry.MetricsRegistry`, the catalog in
``docs/OBSERVABILITY.md`` that operators build dashboards from, and
the ``DEFAULT_RULES`` in :mod:`veles_tpu.telemetry.alerts` that page
on them. Drift between any two is silent until an alert never fires or
a dashboard panel stays blank.

Codes:

* **MET001** — a family minted in code (``registry.counter/gauge/
  histogram("veles_...")``) does not appear in the OBSERVABILITY.md
  catalog.
* **MET002** — a ``.labels(...)`` value built from an f-string /
  ``%`` / ``.format`` expression: unbounded label cardinality is the
  classic way a metrics registry eats the heap. Label values must come
  from bounded sets (literals, enum-ish variables).
* **MET003** — an alert rule references a series (metric, numerator or
  denominator) whose family is never minted anywhere in the tree.
* **MET004** — a catalog row in OBSERVABILITY.md names a family no
  code mints (docs rot in the other direction). Only checked on a
  complete-tree run.

Family extraction is syntactic: first positional string-literal
argument of a ``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)``
call whose value starts with ``veles_``. Calls with a non-literal
first argument (e.g. ``numpy.histogram(data, bins)``) are skipped by
construction.
"""

import ast
import re

from veles_tpu.analysis.core import Finding

MINTERS = frozenset(("counter", "gauge", "histogram"))

#: a family name never ends in '_' (that's a prose prefix mention
#: like ``veles_serving_cache_*``)
FAMILY_RE = re.compile(r"\bveles_[a-z0-9_]*[a-z0-9]\b")

#: doc tokens the regex matches that are not metric families
NOT_FAMILIES = frozenset(("veles_tpu", "veles_cache_dir"))


def _minted_families(modules):
    """{family: (relpath, line)} across ``modules``."""
    out = {}
    for mod in modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in MINTERS
                    and node.args):
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str) \
                    and first.value.startswith("veles_"):
                out.setdefault(first.value, (mod.relpath, node.lineno))
    return out


def _label_calls(mod):
    """Yield (line, argnode) for every value passed to ``.labels()``."""
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "labels"):
            for arg in list(node.args) + [k.value for k in node.keywords]:
                yield node.lineno, arg


def _is_unbounded(arg):
    """Format-expression label values — the unbounded-cardinality
    shapes worth flagging."""
    if isinstance(arg, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in arg.values)
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod):
        return True   # "x-%s" % val
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Attribute) \
            and arg.func.attr == "format":
        return True
    return False


def _alert_series(mod):
    """Series names referenced by ``DEFAULT_RULES`` (a pure literal —
    ``ast.literal_eval``-able by design)."""
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id == "DEFAULT_RULES"):
            continue
        try:
            rules = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            return [(node.lineno, None,
                     "DEFAULT_RULES is no longer a pure literal — the "
                     "alert contract cannot be statically checked")]
        out = []
        for rule in rules:
            for field in ("metric", "numerator", "denominator"):
                name = rule.get(field)
                if name:
                    out.append((node.lineno,
                                "%s.%s" % (rule.get("name", "?"), field),
                                name))
        return out
    return []


def _family_of(series):
    """'veles_x_total{label="a"}' -> 'veles_x_total'."""
    return series.partition("{")[0]


def check(project):
    findings = []
    all_modules = list(project.modules) + list(project.aux)
    minted = _minted_families(all_modules)
    doc_text = "\n".join(project.docs.values())
    doc_families = set(FAMILY_RE.findall(doc_text))

    # MET001: minted but undocumented -------------------------------
    for family, (relpath, line) in sorted(minted.items()):
        if family not in doc_families:
            findings.append(Finding(
                "metrics", "MET001", relpath, line,
                "metric family %s is minted here but missing from the "
                "docs/OBSERVABILITY.md catalog" % family,
                key=family))

    # MET002: unbounded label values --------------------------------
    for mod in project.modules:
        if mod.tree is None:
            continue
        for line, arg in _label_calls(mod):
            if _is_unbounded(arg):
                findings.append(Finding(
                    "metrics", "MET002", mod.relpath, line,
                    "format-expression label value: label sets must "
                    "be bounded (enum-like), not interpolated",
                    key="labels@%d" % line))

    # MET003: alert rules over unminted families --------------------
    for mod in all_modules:
        if mod.tree is None or not mod.relpath.endswith("alerts.py"):
            continue
        for line, where, series in _alert_series(mod):
            if where is None:
                findings.append(Finding(
                    "metrics", "MET003", mod.relpath, line, series,
                    key="rules-literal"))
                continue
            family = _family_of(series)
            if family not in minted:
                findings.append(Finding(
                    "metrics", "MET003", mod.relpath, line,
                    "alert rule %s references %s but no code mints "
                    "that family" % (where, family),
                    key="%s.%s" % (where, family)))

    # MET004: documented but never minted (complete runs only). Only
    # catalog TABLE rows count — prose may mention prefixes, module
    # paths and examples that are not family declarations.
    if project.complete:
        catalog = set()
        for relpath, text in project.docs.items():
            if not relpath.endswith("OBSERVABILITY.md"):
                continue
            for docline in text.splitlines():
                if docline.lstrip().startswith("|"):
                    catalog.update(FAMILY_RE.findall(docline))
        catalog -= NOT_FAMILIES
        for family in sorted(catalog - set(minted)):
            findings.append(Finding(
                "metrics", "MET004", "docs/OBSERVABILITY.md", 0,
                "catalog lists %s but no code mints it" % family,
                key=family))
    return findings
