"""Lock-discipline checker.

Deadlocks and torn state don't show up in tier-1 runs — they need the
right interleaving on a pod. What CAN be checked statically:

* **LOCK001** — an attribute that the class consistently writes under
  ``with self._lock:`` is also written with no lock held. The unlocked
  write is the bug surface: a reader under the lock can observe the
  torn update. ``__init__``/``__new__`` are exempt (no concurrent
  reader exists yet), as are methods named ``*_locked`` or whose
  docstring says the caller holds the lock.
* **LOCK002** — lock-order cycle: somewhere lock A is held while B is
  acquired, and elsewhere B is held while A is acquired (directly or
  through a same-module call chain). Two threads taking the two paths
  concurrently deadlock.
* **LOCK003** — re-acquisition of a non-reentrant ``threading.Lock``
  on a path that already holds it (directly nested ``with``, or a call
  to a method that takes the same lock). Self-deadlock on first
  execution of that path; ``RLock``/``Condition`` (reentrant) are
  exempt.

Acquisition tracking is lexical (``with <lock>:`` blocks) plus an
interprocedural fixpoint over same-module calls (``self.method()`` and
module-level functions), which is exactly the scope VELES' locking
actually spans — no lock in this tree is passed across modules.
"""

import ast

from veles_tpu.analysis.core import (
    Finding, dotted_name, import_aliases)

#: method calls that mutate their receiver (write-equivalent)
MUTATORS = frozenset((
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "add", "discard", "update", "setdefault", "sort", "reverse",
    "popleft", "appendleft",
))

#: constructors recognised as locks: name -> reentrant?
LOCK_TYPES = {
    "threading.Lock": False,
    "threading.RLock": True,
    "threading.Condition": True,   # default Condition wraps an RLock
    "multiprocessing.Lock": False,
    "multiprocessing.RLock": True,
}

EXEMPT_METHODS = frozenset((
    "__init__", "__new__", "__del__", "__enter__", "__exit__",
    "__getstate__", "__setstate__",
    # the VELES constructor-after-unpickle idiom: runs before any
    # other thread can see the instance, like __init__
    "init_unpickled",
))
EXEMPT_DOC_MARKERS = ("caller holds", "lock held", "holding the lock",
                      "under the lock", "not thread-safe",
                      "single-threaded")


def _lock_ctor(node, aliases):
    """'Lock'/'RLock'/... when ``node`` is a recognised lock
    constructor call, else None. Returns (name, reentrant)."""
    if not isinstance(node, ast.Call):
        return None
    target = dotted_name(node.func)
    if target is None:
        return None
    head, _, rest = target.partition(".")
    canon = aliases.get(head, head)
    full = canon + ("." + rest if rest else "")
    if full in LOCK_TYPES:
        return full, LOCK_TYPES[full]
    return None


def _self_attr(node):
    """'x' for ``self.x``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Unit(object):
    """One lock scope: a class (locks are ``self.attr``) or the module
    itself (locks are module-global names)."""

    def __init__(self, name, relpath):
        self.name = name          # class name or '<module>'
        self.relpath = relpath
        self.locks = {}           # lock attr/name -> reentrant?
        self.lock_lines = {}      # lock attr/name -> def line
        # per function: list of events, each
        #   ("acquire", lock, line, frozenset(held_before))
        #   ("write", attr, line, frozenset(held))
        #   ("call", callee, line, frozenset(held))
        self.events = {}
        self.exempt = set()       # function names exempt from LOCK001

    def lock_id(self, lock):
        return "%s.%s" % (self.name, lock)


def _is_exempt(func):
    if func.name in EXEMPT_METHODS or func.name.endswith("_locked"):
        return True
    # whitespace-normalized: reflowed docstrings may wrap a marker
    doc = " ".join((ast.get_docstring(func) or "").lower().split())
    return any(marker in doc for marker in EXEMPT_DOC_MARKERS)


class _FuncWalker(object):
    """Lexical walk of one function body tracking the held-lock set."""

    def __init__(self, unit, lock_names, is_method):
        self.unit = unit
        self.lock_names = lock_names   # names valid in this scope
        self.is_method = is_method
        self.events = []

    def _lock_of(self, expr):
        """Lock name acquired by a with-item / .acquire() target."""
        if self.is_method:
            attr = _self_attr(expr)
            if attr in self.lock_names:
                return attr
        elif isinstance(expr, ast.Name) and expr.id in self.lock_names:
            return expr.id
        return None

    def walk(self, body, held):
        for stmt in body:
            self.stmt(stmt, held)

    def stmt(self, node, held):
        if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            inner = held
            for item in node.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self.events.append(
                        ("acquire", lock, node.lineno, frozenset(inner)))
                    inner = inner | {lock}
                else:
                    self.scan_expr(item.context_expr, held)
            self.walk(node.body, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run later, under their own discipline
        # writes ------------------------------------------------------
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                self.record_write(tgt, held)
            self.scan_expr(node.value, held)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            self.record_write(node.target, held)
            if node.value is not None:
                self.scan_expr(node.value, held)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                self.record_write(tgt, held)
        elif isinstance(node, ast.Expr):
            self.scan_expr(node.value, held)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.scan_expr(node.value, held)
        elif isinstance(node, (ast.If, ast.While)):
            self.scan_expr(node.test, held)
            self.walk(node.body, held)
            self.walk(node.orelse, held)
        elif isinstance(node, ast.For):
            self.scan_expr(node.iter, held)
            self.walk(node.body, held)
            self.walk(node.orelse, held)
        elif isinstance(node, ast.Try):
            self.walk(node.body, held)
            for handler in node.handlers:
                self.walk(handler.body, held)
            self.walk(node.orelse, held)
            self.walk(node.finalbody, held)
        elif isinstance(node, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.scan_expr(child, held)

    def record_write(self, target, held):
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.record_write(elt, held)
            return
        if isinstance(target, ast.Subscript):
            # self.d[k] = v mutates self.d
            target = target.value
        attr = _self_attr(target) if self.is_method else None
        if attr is not None and attr not in self.lock_names:
            self.events.append(
                ("write", attr, target.lineno, frozenset(held)))

    def scan_expr(self, node, held):
        """Find calls inside an expression: lock ops, receiver
        mutations, and same-scope calls for the closure."""
        for call in [n for n in ast.walk(node)
                     if isinstance(n, ast.Call)]:
            func = call.func
            if isinstance(func, ast.Attribute):
                # <lock>.acquire() — an acquisition site
                lock = self._lock_of(func.value)
                if lock is not None and func.attr == "acquire":
                    self.events.append(("acquire", lock, call.lineno,
                                        frozenset(held)))
                    continue
                if lock is not None:
                    continue  # .release()/.locked(): not a write
                # self.attr.append(...) — a mutation of self.attr
                attr = _self_attr(func.value) if self.is_method else None
                if attr is not None and func.attr in MUTATORS:
                    self.events.append(("write", attr, call.lineno,
                                        frozenset(held)))
                # self.method(...) — closure edge
                callee = _self_attr(func) if self.is_method else None
                if callee is not None:
                    self.events.append(("call", callee, call.lineno,
                                        frozenset(held)))
            elif isinstance(func, ast.Name):
                self.events.append(("call", func.id, call.lineno,
                                    frozenset(held)))


def _collect_units(mod, aliases):
    units = []
    tree = mod.tree
    # module-level unit: global locks + top-level functions ----------
    modunit = _Unit("<module>", mod.relpath)
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            ctor = _lock_ctor(node.value, aliases)
            if ctor:
                name = node.targets[0].id
                modunit.locks[name] = ctor[1]
                modunit.lock_lines[name] = node.lineno
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            walker = _FuncWalker(modunit, set(modunit.locks), False)
            walker.walk(node.body, frozenset())
            modunit.events[node.name] = walker.events
            if _is_exempt(node):
                modunit.exempt.add(node.name)
    if modunit.locks:
        units.append(modunit)
    # class units ----------------------------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        unit = _Unit(node.name, mod.relpath)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                ctor = _lock_ctor(sub.value, aliases)
                if ctor:
                    for tgt in sub.targets:
                        attr = _self_attr(tgt)
                        if attr:
                            unit.locks[attr] = ctor[1]
                            unit.lock_lines[attr] = sub.lineno
        if not unit.locks:
            continue
        for sub in node.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walker = _FuncWalker(unit, set(unit.locks), True)
                walker.walk(sub.body, frozenset())
                unit.events[sub.name] = walker.events
                if _is_exempt(sub):
                    unit.exempt.add(sub.name)
        units.append(unit)
    return units


def _effective_acquires(unit):
    """Fixpoint: function -> every lock it may acquire, including via
    same-unit calls."""
    eff = {name: set(lock for kind, lock, _, _ in events
                     if kind == "acquire")
           for name, events in unit.events.items()}
    changed = True
    while changed:
        changed = False
        for name, events in unit.events.items():
            for kind, callee, _, _ in events:
                if kind != "call" or callee not in eff:
                    continue
                extra = eff[callee] - eff[name]
                if extra:
                    eff[name] |= extra
                    changed = True
    return eff


def _check_unit(unit, findings):
    eff = _effective_acquires(unit)

    # -- LOCK001: guarded attribute written outside the lock ---------
    guarded = {}     # attr -> lock most often held at writes
    writes = {}      # attr -> [(func, line, held)]
    for func, events in unit.events.items():
        if func in ("__init__", "__new__"):
            continue
        for kind, attr, line, held in events:
            if kind == "write":
                writes.setdefault(attr, []).append((func, line, held))
    for attr, sites in writes.items():
        locked = [s for s in sites if s[2]]
        if not locked:
            continue
        # the discipline lock: one the class actually uses for attr
        lock_votes = {}
        for _, _, held in locked:
            for lock in held:
                lock_votes[lock] = lock_votes.get(lock, 0) + 1
        lock = max(sorted(lock_votes), key=lambda k: lock_votes[k])
        for func, line, held in sites:
            if held or func in unit.exempt:
                continue
            findings.append(Finding(
                "locks", "LOCK001", unit.relpath, line,
                "%s.%s writes self.%s without holding self.%s "
                "(other writes hold it)" % (
                    unit.name, func, attr, lock),
                key="%s.%s.%s" % (unit.name, func, attr)))

    # -- LOCK002/LOCK003: ordering edges & self-deadlock -------------
    edges = {}   # (lockA, lockB) -> (line, func)
    for func, events in unit.events.items():
        for kind, what, line, held in events:
            if not held:
                continue
            if kind == "acquire":
                acquired = {what}
            elif kind == "call" and what in eff:
                acquired = eff[what]
            else:
                continue
            for b in acquired:
                for a in held:
                    if a == b:
                        if not unit.locks.get(a, True):
                            findings.append(Finding(
                                "locks", "LOCK003", unit.relpath, line,
                                "%s.%s re-acquires non-reentrant lock "
                                "self.%s while already holding it"
                                % (unit.name, func, a),
                                key="%s.%s.%s" % (unit.name, func, a)))
                    else:
                        edges.setdefault((a, b), (line, func))
    for (a, b), (line, func) in sorted(edges.items()):
        if (b, a) in edges and a < b:  # report each cycle once
            other_line, other_func = edges[(b, a)]
            findings.append(Finding(
                "locks", "LOCK002", unit.relpath, line,
                "lock-order cycle in %s: %s takes %s then %s; "
                "%s (line %d) takes %s then %s" % (
                    unit.name, func, a, b,
                    other_func, other_line, b, a),
                key="%s.%s.%s" % (unit.name, a, b)))


def check(project):
    findings = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        aliases = import_aliases(mod.tree)
        for unit in _collect_units(mod, aliases):
            _check_unit(unit, findings)
    return findings
