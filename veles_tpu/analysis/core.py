"""Shared analyzer plumbing: findings, fingerprints, baselines.

A :class:`Finding` is one checker hit. Its *fingerprint* hashes
``checker | code | repo-relative path | key`` — deliberately NOT the
line number, so a baseline entry survives edits elsewhere in the file.
``key`` is whatever identifies the finding within the file (a
qualified function name, an attribute, a metric family, a knob name);
two distinct findings in one file must differ in ``key``.

The baseline (``scripts/lint_baseline.json``) is the ratchet: legacy
debt is recorded there with a human-written reason, anything NOT in it
fails the gate. An empty baseline means the tree is clean — the state
this PR leaves the repo in. Stale entries (fingerprints no checker
produces any more) are reported by the gate so the file shrinks as
debt is paid, mirroring ``scripts/perf_gate.py``'s
baseline-plus-hard-fail design.
"""

import ast
import hashlib
import json
import os


BASELINE_SCHEMA = "veles-lint-baseline/1"


class Finding(object):
    """One checker hit, ordered by (path, line, code)."""

    __slots__ = ("checker", "code", "path", "line", "message", "key")

    def __init__(self, checker, code, path, line, message, key):
        self.checker = checker
        self.code = code
        self.path = path        # repo-relative, '/'-separated
        self.line = int(line)
        self.message = message
        self.key = key

    @property
    def fingerprint(self):
        blob = "|".join((self.checker, self.code, self.path, self.key))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def render(self):
        return "%s:%d: %s %s [%s]" % (
            self.path, self.line, self.code, self.message,
            self.fingerprint)

    def sort_key(self):
        return (self.path, self.line, self.code, self.key)

    def __repr__(self):
        return "Finding(%s)" % self.render()


class Module(object):
    """One parsed source file. ``tree`` is None on a syntax error (the
    error itself becomes a CORE001 finding — an unparseable file must
    fail the gate, not vanish from it)."""

    def __init__(self, path, relpath, source, tree, error=None):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.error = error

    @classmethod
    def parse(cls, path, relpath):
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
            return cls(path, relpath, source, tree)
        except SyntaxError as e:
            return cls(path, relpath, source, None, error=e)


class Project(object):
    """The unit the checkers run over.

    ``modules``   parsed python files under the analyzed roots.
    ``docs``      {relpath: text} of the markdown contracts.
    ``aux``       extra parsed files (bench.py, scripts/) that may
                  legitimately mint metrics or read knobs but are not
                  themselves being linted.
    ``complete``  True when the analyzed roots cover the whole package
                  — gates the set-difference checks (doc entries with
                  no code counterpart) that would false-positive on a
                  partial file list.
    """

    def __init__(self, modules, docs=None, aux=None, complete=False):
        self.modules = modules
        self.docs = docs or {}
        self.aux = aux or []
        self.complete = complete

    @classmethod
    def load(cls, paths, repo_root, doc_paths=(), aux_paths=(),
             complete=False):
        modules = [Module.parse(p, _rel(p, repo_root))
                   for p in _expand(paths)]
        docs = {}
        for p in doc_paths:
            if os.path.isfile(p):
                with open(p, encoding="utf-8", errors="replace") as f:
                    docs[_rel(p, repo_root)] = f.read()
        aux = [Module.parse(p, _rel(p, repo_root))
               for p in _expand(aux_paths)]
        return cls(modules, docs, aux, complete=complete)

    def parse_errors(self):
        out = []
        for mod in self.modules:
            if mod.error is not None:
                out.append(Finding(
                    "core", "CORE001", mod.relpath,
                    mod.error.lineno or 0,
                    "syntax error: %s" % mod.error.msg,
                    key="syntax"))
        return out


def _rel(path, root):
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    return rel.replace(os.sep, "/")


def _expand(paths):
    """Files and directories -> sorted list of .py files."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git"))
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.join(dirpath, name))
        elif p.endswith(".py") and os.path.isfile(p):
            out.append(p)
    return out


def run_all(project, checkers=None):
    """Every checker over ``project`` -> sorted finding list."""
    from veles_tpu.analysis import knobs, locks, metrics_contract, tracer
    table = {
        "locks": locks.check,
        "tracer": tracer.check,
        "metrics": metrics_contract.check,
        "knobs": knobs.check,
    }
    names = checkers or sorted(table)
    findings = list(project.parse_errors())
    for name in names:
        findings.extend(table[name](project))
    findings.sort(key=Finding.sort_key)
    return findings


# -- baseline ----------------------------------------------------------------


def load_baseline(path):
    """{fingerprint: entry} from the committed baseline (empty when the
    file does not exist — a missing baseline suppresses nothing)."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("schema") != BASELINE_SCHEMA:
        raise ValueError("unrecognized baseline schema %r in %s"
                         % (data.get("schema"), path))
    out = {}
    for entry in data.get("suppressions", ()):
        fp = entry.get("fingerprint")
        if not fp:
            raise ValueError("baseline entry without fingerprint: %r"
                             % (entry,))
        if not entry.get("reason", "").strip():
            raise ValueError(
                "baseline suppression %s has no reason — every "
                "suppression must say WHY it is acceptable" % fp)
        out[fp] = entry
    return out


def write_baseline(path, findings, reason):
    """Serialize ``findings`` as suppressions (``--write-baseline``)."""
    entries = [
        {"fingerprint": f.fingerprint,
         "code": f.code,
         "location": "%s:%d" % (f.path, f.line),
         "summary": f.message[:120],
         "reason": reason}
        for f in sorted(findings, key=Finding.sort_key)]
    data = {"schema": BASELINE_SCHEMA, "suppressions": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")


def apply_baseline(findings, baseline):
    """-> (new, suppressed, stale_fingerprints)."""
    new, suppressed = [], []
    seen = set()
    for f in findings:
        fp = f.fingerprint
        if fp in baseline:
            suppressed.append(f)
            seen.add(fp)
        else:
            new.append(f)
    stale = sorted(set(baseline) - seen)
    return new, suppressed, stale


# -- small AST helpers shared by the checkers --------------------------------


def dotted_name(node):
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree):
    """{local name: canonical dotted module} for a module's imports.

    ``import numpy as np`` -> {"np": "numpy"};
    ``from jax import numpy as jnp`` -> {"jnp": "jax.numpy"};
    ``from time import monotonic`` -> {"monotonic": "time.monotonic"}.
    """
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = (
                    node.module + "." + a.name)
    return aliases


def resolve_call(node, aliases):
    """Canonical dotted target of a Call ('time.time', 'numpy.random.
    uniform', ...) with the module's import aliases folded in."""
    name = dotted_name(node.func if isinstance(node, ast.Call) else node)
    if not name:
        return None
    head, _, rest = name.partition(".")
    canon = aliases.get(head)
    if canon:
        return canon + ("." + rest if rest else "")
    return name
