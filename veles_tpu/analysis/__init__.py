"""veles-analyze: the repo-native static analysis plane.

Four AST checkers encode contracts the test suite cannot see —
they hold *between* runs, across threads, or between code and docs:

* :mod:`veles_tpu.analysis.locks` — lock discipline. Attributes
  consistently written under ``with self._lock:`` must not be written
  outside it, lock acquisition order must be acyclic, and a
  non-reentrant ``threading.Lock`` must not be re-acquired on a path
  that already holds it.
* :mod:`veles_tpu.analysis.tracer` — JAX tracer hygiene. Host-impure
  calls (``time.*``, ``numpy.random``, ``print``, ``.item()``,
  captured-container mutation, ``os.environ``) must not be reachable
  from inside a ``jit`` / ``pallas_call`` / ``custom_vjp``-traced
  function: they run at trace time, silently bake one value into the
  compiled program, and diverge on cache hits.
* :mod:`veles_tpu.analysis.metrics_contract` — every metric family
  minted through :mod:`veles_tpu.telemetry.registry` appears in the
  docs/OBSERVABILITY.md catalog, label values come from bounded sets
  (no f-strings), and every series referenced by
  ``telemetry/alerts.py`` DEFAULT_RULES resolves to a real family.
* :mod:`veles_tpu.analysis.knobs` — every ``VELES_*`` env knob is
  documented (docs/CONFIGURATION.md) and parsed through the shared
  empty-string-safe :func:`veles_tpu.envknob.env_knob` helper.

Pure stdlib ``ast`` — no third-party dependency, no imports of the
analyzed code, finishes in seconds on the full tree. Findings carry
``file:line`` plus a stable fingerprint (independent of line numbers)
so the committed baseline (``scripts/lint_baseline.json``) survives
unrelated edits. ``python -m veles_tpu.analysis`` runs everything;
``scripts/lint_gate.py`` is the CI gate (mirrors ``perf_gate.py``:
hard-fails on any finding not in the baseline, and CI proves the gate
can fail by running it against a known-bad fixture).
"""

from veles_tpu.analysis.core import (  # noqa: F401
    Finding, Module, Project, load_baseline, run_all, write_baseline)
