"""JAX tracer-hygiene checker.

A function traced by ``jit`` / ``pallas_call`` / ``scan`` /
``custom_vjp`` runs its Python body ONCE per compile cache entry.
Host-impure operations inside it don't fail — they silently bake the
trace-time value into the compiled program and never run again on
cache hits, which is how "the timestamp metric stopped moving" and
"np.random gives the same draw every step" bugs are born. These are
invisible to tests (first call looks right) — exactly what static
analysis is for.

Codes:

* **TRACE001** ``print(...)`` — runs at trace time only; use
  ``jax.debug.print`` for per-execution output.
* **TRACE002** ``time.*()`` — freezes one wall-clock read into the
  program.
* **TRACE003** ``numpy.random.*`` / stdlib ``random.*`` — one draw,
  reused forever; use ``jax.random`` with explicit keys
  (:mod:`veles_tpu.prng`).
* **TRACE004** ``.item()`` / ``float(tracer)``-style host sync — a
  concretization error at best, a silent constant at worst.
* **TRACE005** mutation of captured state (``self.x = ...``,
  ``captured_list.append(...)``) — happens once at trace time, not
  per step.
* **TRACE006** ``os.environ`` reads — bakes the trace-time
  environment into compiled code; read knobs outside and pass values
  in.

Roots are found from decorators (``@jax.jit``,
``@functools.partial(jax.jit, ...)``, ``@jax.custom_vjp``), wrapper
calls (``jax.jit(f)``, ``pl.pallas_call(kernel, ...)``,
``jax.lax.scan/while_loop/cond/fori_loop`` body arguments,
``f.defvjp(fwd, bwd)``), then taint-propagated through calls to
functions defined in the same module. Calls routed through the
sanctioned escape hatches (``jax.debug.print``, ``jax.debug.callback``,
``jax.pure_callback``, ``jax.experimental.io_callback``) are exempt.
"""

import ast

from veles_tpu.analysis.core import (
    Finding, dotted_name, import_aliases, resolve_call)
from veles_tpu.analysis.locks import MUTATORS

#: decorators that make the decorated function a traced root
TRACING_DECORATORS = frozenset((
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.custom_vjp", "jax.custom_jvp", "jax.checkpoint", "jax.remat",
))

#: wrapper call -> positional args that are traced callables
WRAPPER_ARGS = {
    "jax.jit": (0,), "jax.pmap": (0,), "jax.vmap": (0,),
    "jax.grad": (0,), "jax.value_and_grad": (0,),
    "jax.custom_vjp": (0,), "jax.custom_jvp": (0,),
    "jax.checkpoint": (0,), "jax.remat": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.cond": (1, 2),
    "jax.lax.fori_loop": (2,),
    "jax.lax.map": (0,),
    "jax.lax.associative_scan": (0,),
    "jax.experimental.pallas.pallas_call": (0,),
    "jax.experimental.shard_map.shard_map": (0,),
}

#: calls whose arguments are the sanctioned host-callback escape hatch
CALLBACK_OK = frozenset((
    "jax.debug.print", "jax.debug.callback", "jax.pure_callback",
    "jax.experimental.io_callback", "jax.debug.breakpoint",
))

#: canonical impure call prefixes -> finding code
IMPURE_PREFIXES = (
    ("time.", "TRACE002", "wall-clock read"),
    ("numpy.random.", "TRACE003", "host RNG draw"),
    ("random.", "TRACE003", "host RNG draw"),
)

ENV_READS = frozenset(("os.environ.get", "os.getenv"))


def _decorator_roots(func, aliases):
    """True when one of ``func``'s decorators traces it."""
    for dec in func.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = resolve_call(ast.Call(func=target, args=[], keywords=[]),
                            aliases)
        if name in TRACING_DECORATORS:
            return True
        # functools.partial(jax.jit, static_argnums=...)
        if name == "functools.partial" and isinstance(dec, ast.Call) \
                and dec.args:
            inner = resolve_call(
                ast.Call(func=dec.args[0], args=[], keywords=[]),
                aliases)
            if inner in TRACING_DECORATORS:
                return True
    return False


def _collect_functions(tree):
    """Every function def in the module, keyed by (qualname is not
    needed — taint resolves by local/bare name)."""
    funcs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, []).append(node)
    return funcs


def _callable_name(node):
    """Bare name of a callable reference in an argument position."""
    if isinstance(node, ast.Name):
        return node.id
    attr = dotted_name(node)
    if attr and attr.startswith("self."):
        return attr.split(".", 1)[1]
    return None


def _find_roots(tree, aliases, funcs):
    roots = {}

    def add(name, why):
        if name in funcs and name not in roots:
            roots[name] = why
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _decorator_roots(node, aliases):
                roots.setdefault(node.name, "decorated traced function")
        elif isinstance(node, ast.Call):
            target = resolve_call(node, aliases)
            if target in WRAPPER_ARGS:
                for pos in WRAPPER_ARGS[target]:
                    if pos < len(node.args):
                        name = _callable_name(node.args[pos])
                        if name:
                            add(name, "passed to %s" % target)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "defvjp":
                for arg in node.args:
                    name = _callable_name(arg)
                    if name:
                        add(name, "custom_vjp rule")
    return roots


def _taint(roots, funcs):
    """Propagate traced-ness through same-module calls."""
    traced = dict(roots)
    queue = list(roots)
    while queue:
        name = queue.pop()
        for node in funcs.get(name, ()):
            for call in [n for n in ast.walk(node)
                         if isinstance(n, ast.Call)]:
                callee = _callable_name(call.func)
                if callee in funcs and callee not in traced:
                    traced[callee] = "called from traced %s" % name
                    queue.append(callee)
    return traced


def _local_names(func):
    """Names bound inside ``func`` (params + assignments): mutating
    these at trace time is fine — they are trace-local."""
    names = set()
    args = func.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in ast.walk(func):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            names.add(node.name)
    return names


def _scan_traced(mod, func, why, aliases, findings):
    locals_ = _local_names(func)
    skip = set()   # nodes inside sanctioned callback calls

    for node in ast.walk(func):
        if isinstance(node, ast.Call) \
                and resolve_call(node, aliases) in CALLBACK_OK:
            for sub in ast.walk(node):
                skip.add(id(sub))

    def emit(code, line, what, key_tail):
        findings.append(Finding(
            "tracer", code, mod.relpath, line,
            "%s inside traced %s (%s)" % (what, func.name, why),
            key="%s.%s" % (func.name, key_tail)))

    for node in ast.walk(func):
        if id(node) in skip or node is func:
            continue
        if isinstance(node, ast.Call):
            target = resolve_call(node, aliases)
            if target == "print":
                emit("TRACE001", node.lineno,
                     "print() runs at trace time only", "print")
                continue
            if target in ENV_READS:
                emit("TRACE006", node.lineno,
                     "os.environ read bakes trace-time env in",
                     "environ")
                continue
            if target:
                matched = False
                for prefix, code, what in IMPURE_PREFIXES:
                    if target.startswith(prefix):
                        emit(code, node.lineno,
                             "%s %s() freezes one value" % (
                                 what, target), target)
                        matched = True
                        break
                if matched:
                    continue
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    emit("TRACE004", node.lineno,
                         ".item() host sync", "item")
                    continue
                recv = node.func.value
                if node.func.attr in MUTATORS:
                    recv_name = dotted_name(recv)
                    if recv_name and recv_name.split(".")[0] \
                            not in locals_:
                        emit("TRACE005", node.lineno,
                             "mutation of captured %r happens once "
                             "at trace time" % recv_name,
                             "mut.%s" % recv_name)
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            target = dotted_name(node.value)
            if target == "os.environ":
                emit("TRACE006", node.lineno,
                     "os.environ read bakes trace-time env in",
                     "environ")
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                base = tgt.value if isinstance(tgt, ast.Subscript) \
                    else tgt
                name = dotted_name(base)
                if name and "." in name \
                        and name.split(".")[0] == "self":
                    emit("TRACE005", tgt.lineno,
                         "write to captured %s happens once at "
                         "trace time" % name, "set.%s" % name)


def check(project):
    findings = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        aliases = import_aliases(mod.tree)
        funcs = _collect_functions(mod.tree)
        roots = _find_roots(mod.tree, aliases, funcs)
        if not roots:
            continue
        traced = _taint(roots, funcs)
        for name, why in sorted(traced.items()):
            for func in funcs[name]:
                _scan_traced(mod, func, why, aliases, findings)
    return findings
