"""Env-knob contract checker.

``VELES_*`` environment variables are the operational API of this
tree: benches, CI, the elastic supervisor and the serving plane all
speak it. The contract (see :mod:`veles_tpu.envknob`):

* **KNOB001** — a ``VELES_*`` variable is read in code but documented
  nowhere (docs/CONFIGURATION.md is the catalog; any docs/*.md or
  README mention satisfies the checker). An undocumented knob is one
  nobody can discover and everybody eventually collides with.
* **KNOB002** — a raw ``os.environ.get`` / ``os.getenv`` /
  ``os.environ[...]`` read of a ``VELES_*`` name outside
  ``envknob.py``. Raw reads reintroduce the empty-string crash class
  (``float(os.environ.get("X") or "")``) that
  :func:`veles_tpu.envknob.env_knob` exists to kill. Membership tests
  (``"X" in os.environ``) and writes (``env["X"] = ...``,
  ``setdefault``) are fine — the hazard is parsing reads.
* **KNOB003** — a ``VELES_*`` read inside an ``add_argument(...)``
  call. An env-var buried in an argparse ``default=`` is evaluated at
  parser-build time and silently shadows later environment changes;
  resolve the knob at use time instead.

Names are resolved through module-level string constants
(``ENV_WORLD = "VELES_ELASTIC_WORLD"`` ... ``env_knob(ENV_WORLD)``),
the pattern the elastic supervisor uses for its worker contract.
"""

import ast
import re

from veles_tpu.analysis.core import Finding, dotted_name, resolve_call
from veles_tpu.analysis.core import import_aliases

KNOB_RE = re.compile(r"\bVELES_[A-Z0-9_]+\b")

RAW_READ_CALLS = frozenset(("os.environ.get", "os.getenv"))
HELPER_CALLS = frozenset((
    "env_knob", "env_flag",
    "veles_tpu.envknob.env_knob", "veles_tpu.envknob.env_flag"))


def _str_consts(tree):
    """Module-level ``NAME = "VELES_..."`` constants."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _knob_name(node, consts):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        value = node.value
    elif isinstance(node, ast.Name):
        value = consts.get(node.id)
    else:
        return None
    if value and KNOB_RE.fullmatch(value):
        return value
    return None


def _reads(mod, aliases, consts):
    """Yield (name, line, raw, node) for every VELES_* env read."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            target = resolve_call(node, aliases)
            if target in RAW_READ_CALLS and node.args:
                name = _knob_name(node.args[0], consts)
                if name:
                    yield name, node.lineno, True, node
            elif target in HELPER_CALLS and node.args:
                name = _knob_name(node.args[0], consts)
                if name:
                    yield name, node.lineno, False, node
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and dotted_name(node.value) == "os.environ":
            name = _knob_name(node.slice, consts)
            if name:
                yield name, node.lineno, True, node


def check(project):
    findings = []
    doc_text = "\n".join(project.docs.values())
    documented = set(KNOB_RE.findall(doc_text))

    for mod in project.modules:
        if mod.tree is None:
            continue
        aliases = import_aliases(mod.tree)
        consts = _str_consts(mod.tree)
        is_helper = mod.relpath.endswith("envknob.py")

        argparse_spans = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add_argument":
                argparse_spans.append(
                    set(id(n) for n in ast.walk(node)))

        seen = set()
        for name, line, raw, node in _reads(mod, aliases, consts):
            if project.docs and name not in documented \
                    and (name, "KNOB001") not in seen:
                seen.add((name, "KNOB001"))
                findings.append(Finding(
                    "knobs", "KNOB001", mod.relpath, line,
                    "%s is read here but documented in no docs/*.md "
                    "— add it to docs/CONFIGURATION.md" % name,
                    key="doc.%s" % name))
            if raw and not is_helper \
                    and (name, "KNOB002", line) not in seen:
                seen.add((name, "KNOB002", line))
                findings.append(Finding(
                    "knobs", "KNOB002", mod.relpath, line,
                    "raw environment read of %s — route it through "
                    "veles_tpu.envknob.env_knob (empty-string-safe, "
                    "one parse contract)" % name,
                    key="raw.%s" % name))
            if any(id(node) in span for span in argparse_spans) \
                    and (name, "KNOB003") not in seen:
                seen.add((name, "KNOB003"))
                findings.append(Finding(
                    "knobs", "KNOB003", mod.relpath, line,
                    "%s read inside add_argument(): the value is "
                    "frozen at parser build and shadows the "
                    "environment — resolve it at use time" % name,
                    key="argparse.%s" % name))
    return findings
