"""Normalization strategy registry (``veles/normalization.py:110-662``).

Each normalizer has pickleable state, an ``analyze(train_data)`` pass
and ``normalize``/``denormalize`` transforms, and registers under a
string key (the loaders' ``normalization_type``).
"""

import numpy


class NormalizerRegistry(type):
    normalizers = {}

    def __init__(cls, name, bases, namespace):
        super(NormalizerRegistry, cls).__init__(name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if mapping:
            NormalizerRegistry.normalizers[mapping] = cls

    @staticmethod
    def make(name, **kwargs):
        try:
            cls = NormalizerRegistry.normalizers[name]
        except KeyError:
            raise ValueError("unknown normalization %r (have %s)" %
                             (name, sorted(NormalizerRegistry.normalizers)))
        return cls(**kwargs)


class NormalizerBase(object, metaclass=NormalizerRegistry):
    MAPPING = None
    is_identity = False

    def __init__(self, **kwargs):
        self.state = {}

    def analyze(self, data):
        pass

    def normalize(self, data):
        raise NotImplementedError

    def denormalize(self, data):
        raise NotImplementedError

    def __getstate__(self):
        return self.__dict__

    def __setstate__(self, state):
        self.__dict__.update(state)


class NoneNormalizer(NormalizerBase):
    """Identity."""

    MAPPING = "none"
    is_identity = True

    def normalize(self, data):
        return data

    def denormalize(self, data):
        return data


class LinearNormalizer(NormalizerBase):
    """Scale to [interval.min, interval.max] from observed min/max."""

    MAPPING = "linear"

    def __init__(self, interval=(-1.0, 1.0), **kwargs):
        super(LinearNormalizer, self).__init__(**kwargs)
        self.interval = tuple(interval)

    def analyze(self, data):
        flat = data.reshape(len(data), -1)
        self.state["dmin"] = flat.min(axis=0)
        self.state["dmax"] = flat.max(axis=0)

    def _coeffs(self):
        dmin, dmax = self.state["dmin"], self.state["dmax"]
        span = numpy.where(dmax > dmin, dmax - dmin, 1.0)
        lo, hi = self.interval
        return dmin, span, lo, hi

    def normalize(self, data):
        shape = data.shape
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        dmin, span, lo, hi = self._coeffs()
        return (lo + (flat - dmin) / span * (hi - lo)).reshape(shape)

    def denormalize(self, data):
        shape = data.shape
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        dmin, span, lo, hi = self._coeffs()
        return ((flat - lo) / (hi - lo) * span + dmin).reshape(shape)


class MeanDispersionNormalizer(NormalizerBase):
    """(x - mean) / (max - min) per feature (``normalization.py:284``)."""

    MAPPING = "mean_disp"

    def analyze(self, data):
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        self.state["mean"] = flat.mean(axis=0)
        spread = flat.max(axis=0) - flat.min(axis=0)
        self.state["rdisp"] = numpy.where(
            spread > 0, 1.0 / numpy.maximum(spread, 1e-12), 1.0
        ).astype(numpy.float32)

    def normalize(self, data):
        shape = data.shape
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        return ((flat - self.state["mean"]) * self.state["rdisp"]
                ).reshape(shape)

    def denormalize(self, data):
        shape = data.shape
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        return (flat / self.state["rdisp"] + self.state["mean"]
                ).reshape(shape)


class ExternalMeanNormalizer(NormalizerBase):
    """Subtract an externally supplied mean array (file or ndarray)."""

    MAPPING = "external_mean"

    def __init__(self, mean_source=None, scale=1.0, **kwargs):
        super(ExternalMeanNormalizer, self).__init__(**kwargs)
        if isinstance(mean_source, str):
            mean_source = numpy.load(mean_source)
        if mean_source is None:
            raise ValueError("external_mean needs mean_source")
        self.mean = numpy.asarray(mean_source, numpy.float32)
        self.scale = scale

    def normalize(self, data):
        return (data.astype(numpy.float32) - self.mean) * self.scale

    def denormalize(self, data):
        return data / self.scale + self.mean


class RangeLinearNormalizer(NormalizerBase):
    """Fixed a-priori range scale (e.g. uint8 images: /127.5 - 1)."""

    MAPPING = "range_linear"

    def __init__(self, source_range=(0.0, 255.0), target_range=(-1.0, 1.0),
                 **kwargs):
        super(RangeLinearNormalizer, self).__init__(**kwargs)
        self.source_range = tuple(source_range)
        self.target_range = tuple(target_range)

    def normalize(self, data):
        s0, s1 = self.source_range
        t0, t1 = self.target_range
        return ((data.astype(numpy.float32) - s0) / (s1 - s0) *
                (t1 - t0) + t0)

    def denormalize(self, data):
        s0, s1 = self.source_range
        t0, t1 = self.target_range
        return (data - t0) / (t1 - t0) * (s1 - s0) + s0


class ExpNormalizer(NormalizerBase):
    """sigmoid-ish exponential squashing (``normalization.py`` exp)."""

    MAPPING = "exp"

    def normalize(self, data):
        return 1.0 / (1.0 + numpy.exp(-data.astype(numpy.float32))) * 2 - 1

    def denormalize(self, data):
        p = (data + 1.0) / 2.0
        p = numpy.clip(p, 1e-7, 1 - 1e-7)
        return numpy.log(p / (1 - p))


class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map learned from data (``pointwise``)."""

    MAPPING = "pointwise"

    def analyze(self, data):
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        dmin, dmax = flat.min(axis=0), flat.max(axis=0)
        span = numpy.where(dmax > dmin, dmax - dmin, 1.0)
        self.state["mul"] = (2.0 / span).astype(numpy.float32)
        self.state["add"] = (-1.0 - dmin * 2.0 / span).astype(numpy.float32)

    def normalize(self, data):
        shape = data.shape
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        return (flat * self.state["mul"] + self.state["add"]).reshape(shape)

    def denormalize(self, data):
        shape = data.shape
        flat = data.reshape(len(data), -1).astype(numpy.float32)
        return ((flat - self.state["add"]) / self.state["mul"]
                ).reshape(shape)
