"""Reproducible random number generation.

Re-designs ``veles/prng/random_generator.py``: a registry of named,
seeded generators (``get(key)``) whose state is part of snapshots, with
the save/restore discipline that keeps unit initialization from
perturbing the stream (``veles/units.py:859-885``).

Host-side streams use ``numpy.random.RandomState`` (picklable, stable
across versions). Device-side randomness uses JAX's counter-based PRNG:
each generator deterministically derives ``jax`` keys from its seed and a
split counter, so a restored snapshot continues the *same* key sequence —
the TPU answer to the reference's xorshift1024* state arrays
(``veles/prng/uniform.py:49``, ``cuda/random.cu:46-73``).
"""

import hashlib
import threading

import numpy


class RandomGenerator(object):
    """One named random stream: numpy host stream + JAX key chain."""

    def __init__(self, key):
        self.key = key
        self._lock = threading.Lock()
        self.seed_value = None
        self.state = numpy.random.RandomState()
        self._jax_counter = 0

    def seed(self, seed, dtype=None, count=None):
        """Seed from an int, bytes, array or a file path ("/dev/urandom").

        ``dtype``/``count`` mirror the reference's file-seeding signature
        (``veles/__main__.py:483-537``): read ``count`` items of ``dtype``.
        """
        if isinstance(seed, str):
            with open(seed, "rb") as f:
                raw = f.read((count or 16) *
                             numpy.dtype(dtype or numpy.uint8).itemsize)
            seed = numpy.frombuffer(raw, dtype=dtype or numpy.uint8)
        if isinstance(seed, numpy.ndarray):
            seed = int.from_bytes(
                hashlib.sha256(seed.tobytes()).digest()[:8], "little")
        elif isinstance(seed, (bytes, bytearray)):
            seed = int.from_bytes(
                hashlib.sha256(bytes(seed)).digest()[:8], "little")
        with self._lock:
            self.seed_value = int(seed) & 0xFFFFFFFF
            self.state = numpy.random.RandomState(self.seed_value)
            self._jax_counter = 0
        return self

    # -- host-side sampling ------------------------------------------------

    def randint(self, low, high=None, size=None):
        with self._lock:
            return self.state.randint(low, high, size)

    def rand(self, *shape):
        with self._lock:
            return self.state.rand(*shape)

    def normal(self, loc=0.0, scale=1.0, size=None):
        with self._lock:
            return self.state.normal(loc, scale, size)

    def uniform(self, low=0.0, high=1.0, size=None):
        with self._lock:
            return self.state.uniform(low, high, size)

    def shuffle(self, arr):
        with self._lock:
            self.state.shuffle(arr)

    def permutation(self, n):
        with self._lock:
            return self.state.permutation(n)

    def fill(self, arr, vmin=-1.0, vmax=1.0):
        """In-place uniform fill (the reference's weight-filler contract)."""
        with self._lock:
            arr[...] = self.state.uniform(vmin, vmax, arr.shape).astype(
                arr.dtype)

    def fill_normal(self, arr, mean=0.0, stddev=1.0):
        with self._lock:
            arr[...] = self.state.normal(mean, stddev, arr.shape).astype(
                arr.dtype)

    # -- device-side keys ---------------------------------------------------

    def jax_key(self):
        """Next JAX PRNG key in this generator's deterministic chain."""
        import jax
        with self._lock:
            counter = self._jax_counter
            self._jax_counter += 1
        base = (self.seed_value if self.seed_value is not None
                else 0xC0FFEE) & 0xFFFFFFFF
        return jax.random.fold_in(jax.random.PRNGKey(base), counter)

    # -- state management ---------------------------------------------------

    def save_state(self):
        with self._lock:
            return (self.state.get_state(), self._jax_counter,
                    self.seed_value)

    def restore_state(self, saved):
        state, counter, seed_value = saved
        # under the lock: a sampler racing a checkpoint restore must
        # see the old state or the new one, never half of each
        with self._lock:
            self.state.set_state(state)
            self._jax_counter = counter
            self.seed_value = seed_value

    def __getstate__(self):
        return {"key": self.key, "seed_value": self.seed_value,
                "numpy_state": self.state.get_state(),
                "jax_counter": self._jax_counter}

    def __setstate__(self, state):
        self.key = state["key"]
        self._lock = threading.Lock()
        self.seed_value = state["seed_value"]
        self.state = numpy.random.RandomState()
        self.state.set_state(state["numpy_state"])
        self._jax_counter = state["jax_counter"]


_generators = {}
_registry_lock = threading.Lock()


def get(key="default"):
    """The named-generator registry (``prng/random_generator.py:289``)."""
    with _registry_lock:
        gen = _generators.get(key)
        if gen is None:
            # stable across processes (hash() is randomized per process)
            gen = RandomGenerator(key).seed(str(key).encode())
            _generators[key] = gen
        return gen


def dump_states():
    """Every named generator's state as plain picklable data —
    ``{key: (numpy_state_tuple, jax_counter, seed_value)}``.

    The master ships this in the elastic-join resync (ISSUE 12) so a
    slave joining mid-run continues the SAME random streams as the
    fleet instead of restarting them from its seeds; the payload rides
    the restricted-unpickle wire codec (str/int/ndarray only)."""
    with _registry_lock:
        generators = dict(_generators)
    return {key: gen.save_state() for key, gen in generators.items()}


def restore_states(states):
    """Inverse of :func:`dump_states`: overwrite (or create) each
    named generator with the shipped state."""
    for key, saved in (states or {}).items():
        get(key).restore_state(saved)
