"""Deterministic golden datasets for accuracy-parity runs.

The reference publishes MNIST error baselines (1.48% FC / 0.73% conv,
``docs/source/manualrst_veles_algorithms.rst:32``); this environment
has zero network egress, so the real IDX files cannot be fetched
(``MnistIdxLoader``/``downloader.py`` handle them when they exist).
This module provides the committed fallback VERDICT r1 asked for: a
procedurally generated handwritten-digit dataset that is deterministic
from a seed, has real intra-class variation (per-sample affine warps,
stroke-thickness variants, noise, occlusion speckle), and is hard
enough that validation error tracks genuine model quality — a
half-broken optimizer does NOT reach the thresholds
(`tests/test_parity.py` keeps a deliberately-crippled run above them).

28×28 float32 images in [0, 1], labels int32 0-9, MNIST-shaped.
"""

import numpy

#: 5×7 glyph bitmaps (one string row per scanline, '#' = ink)
_GLYPHS = {
    0: ["#####", "#...#", "#...#", "#...#", "#...#", "#...#", "#####"],
    1: ["..#..", ".##..", "..#..", "..#..", "..#..", "..#..", ".###."],
    2: ["#####", "....#", "....#", "#####", "#....", "#....", "#####"],
    3: ["#####", "....#", "....#", ".####", "....#", "....#", "#####"],
    4: ["#...#", "#...#", "#...#", "#####", "....#", "....#", "....#"],
    5: ["#####", "#....", "#....", "#####", "....#", "....#", "#####"],
    6: ["#####", "#....", "#....", "#####", "#...#", "#...#", "#####"],
    7: ["#####", "....#", "...#.", "..#..", "..#..", ".#...", ".#..."],
    8: ["#####", "#...#", "#...#", "#####", "#...#", "#...#", "#####"],
    9: ["#####", "#...#", "#...#", "#####", "....#", "....#", "#####"],
}


def _base_glyph(digit):
    rows = _GLYPHS[digit]
    img = numpy.array([[1.0 if c == "#" else 0.0 for c in row]
                       for row in rows], numpy.float32)
    return img


def _render(digit, rng, size=28):
    """One sample: upscaled glyph -> random affine -> noise."""
    from scipy import ndimage
    glyph = _base_glyph(digit)
    # stroke-thickness variant: optional dilation of the 5x7 mask
    if rng.rand() < 0.4:
        glyph = ndimage.grey_dilation(glyph, size=(1, 2))
    # upscale to ~20x14 with smoothing (soft strokes)
    scale_y = (14.0 + rng.uniform(-2, 3)) / glyph.shape[0]
    scale_x = (10.0 + rng.uniform(-2, 3)) / glyph.shape[1]
    big = ndimage.zoom(glyph, (scale_y, scale_x), order=1)
    big = ndimage.gaussian_filter(big, rng.uniform(0.4, 0.9))
    # paste centered on the canvas
    canvas = numpy.zeros((size, size), numpy.float32)
    oy = (size - big.shape[0]) // 2
    ox = (size - big.shape[1]) // 2
    canvas[oy:oy + big.shape[0], ox:ox + big.shape[1]] = big
    # random affine about the center: rotation, shear, translation
    theta = rng.uniform(-0.30, 0.30)          # ±17°
    shear = rng.uniform(-0.25, 0.25)
    c, s = numpy.cos(theta), numpy.sin(theta)
    mat = numpy.array([[c, -s + shear], [s, c]], numpy.float32)
    center = numpy.array([size / 2, size / 2])
    offset = center - mat @ center + rng.uniform(-2.5, 2.5, size=2)
    warped = ndimage.affine_transform(canvas, mat, offset=offset,
                                      order=1, mode="constant")
    # amplitude jitter + additive noise + salt speckle
    warped *= rng.uniform(0.7, 1.0)
    warped += rng.normal(0.0, 0.08, warped.shape).astype(numpy.float32)
    n_speckle = rng.randint(0, 6)
    if n_speckle:
        ys = rng.randint(0, size, n_speckle)
        xs = rng.randint(0, size, n_speckle)
        warped[ys, xs] = rng.uniform(0.5, 1.0, n_speckle)
    return numpy.clip(warped, 0.0, 1.0).astype(numpy.float32)


class golden_digits(object):
    """Provider for :class:`MnistWorkflow`: calling it yields
    ``(train_x, train_y, valid_x, valid_y)``, deterministic from
    ``seed``. A class (not a closure) so loaders holding it stay
    picklable inside snapshots; the rendered arrays are cached after
    the first call (~1 ms/sample of scipy warps otherwise re-paid by
    every workflow built on the same provider)."""

    def __init__(self, n_train=12000, n_valid=2000, seed=2026, size=28):
        self.n_train = n_train
        self.n_valid = n_valid
        self.seed = seed
        self.size = size
        self._cache_ = None

    def __call__(self):
        if self._cache_ is None:
            rng = numpy.random.RandomState(self.seed)
            total = self.n_train + self.n_valid
            labels = rng.randint(0, 10, total).astype(numpy.int32)
            images = numpy.stack([_render(int(lbl), rng, self.size)
                                  for lbl in labels])
            self._cache_ = (images[:self.n_train],
                            labels[:self.n_train],
                            images[self.n_train:],
                            labels[self.n_train:])
        return self._cache_

    def __getstate__(self):
        # the cache regenerates deterministically: never pickle 200MB
        state = dict(self.__dict__)
        state["_cache_"] = None
        return state


def _render_object(klass, rng, size=32):
    """One 32x32x3 'golden objects' sample: a procedural SHAPE on a
    random background. Hues are random PER SAMPLE (never per class), so
    color carries no class signal — the classifier must read shape,
    which is what keeps the analog non-trivial for a convnet and
    hopeless for color-histogram shortcuts."""
    yy, xx = numpy.mgrid[0:size, 0:size].astype(numpy.float32)
    cy = size / 2 + rng.uniform(-4, 4)
    cx = size / 2 + rng.uniform(-4, 4)
    r = rng.uniform(6, 10)
    dy, dx = yy - cy, xx - cx
    theta = rng.uniform(0, numpy.pi)
    ry = dy * numpy.cos(theta) - dx * numpy.sin(theta)
    rx = dy * numpy.sin(theta) + dx * numpy.cos(theta)
    if klass == 0:      # disc
        mask = (dy ** 2 + dx ** 2) < r ** 2
    elif klass == 1:    # filled square (rotated)
        mask = numpy.maximum(abs(ry), abs(rx)) < r * 0.8
    elif klass == 2:    # triangle
        mask = (ry > -r * 0.6) & (abs(rx) < (r * 0.8 - ry) * 0.6)
    elif klass == 3:    # ring
        d2 = dy ** 2 + dx ** 2
        mask = (d2 < r ** 2) & (d2 > (r * 0.55) ** 2)
    elif klass == 4:    # cross
        mask = ((abs(ry) < r * 0.3) | (abs(rx) < r * 0.3)) & \
            (numpy.maximum(abs(ry), abs(rx)) < r)
    elif klass == 5:    # stripes along the rotated axis
        mask = (numpy.sin(ry * numpy.pi / rng.uniform(2.5, 4.0)) > 0) & \
            ((dy ** 2 + dx ** 2) < (r * 1.3) ** 2)
    elif klass == 6:    # checkerboard patch
        mask = ((numpy.sin(ry * 1.1) > 0) ^ (numpy.sin(rx * 1.1) > 0)) & \
            (numpy.maximum(abs(ry), abs(rx)) < r)
    elif klass == 7:    # two discs
        off = r * 0.75
        mask = ((dy - off) ** 2 + (dx) ** 2 < (r * 0.55) ** 2) | \
            ((dy + off) ** 2 + (dx) ** 2 < (r * 0.55) ** 2)
    elif klass == 8:    # hollow square frame
        m = numpy.maximum(abs(ry), abs(rx))
        mask = (m < r * 0.9) & (m > r * 0.5)
    else:               # crescent: disc minus shifted disc
        d2 = dy ** 2 + dx ** 2
        mask = (d2 < r ** 2) & \
            ((dy - r * 0.5) ** 2 + (dx - r * 0.3) ** 2 > (r * 0.85) ** 2)
    fg = rng.uniform(0.2, 1.0, 3).astype(numpy.float32)
    bg = rng.uniform(0.0, 0.8, 3).astype(numpy.float32)
    # guarantee some figure/ground contrast or the shape can vanish
    while float(numpy.abs(fg - bg).max()) < 0.3:
        fg = rng.uniform(0.2, 1.0, 3).astype(numpy.float32)
        bg = rng.uniform(0.0, 0.8, 3).astype(numpy.float32)
    img = numpy.where(mask[..., None], fg, bg).astype(numpy.float32)
    # distractor bar (never class-informative: same for all classes)
    if rng.rand() < 0.5:
        y0 = rng.randint(0, size - 3)
        img[y0:y0 + 2, :, :] = rng.uniform(0, 1, 3)
    img += rng.normal(0, 0.18, img.shape).astype(numpy.float32)
    return numpy.clip(img, 0.0, 1.0)


class golden_objects(object):
    """CIFAR-shaped committed analog (VERDICT r3 missing #4): 10
    procedural shape classes at 32x32x3, deterministic from ``seed``.
    Same provider contract and caching behavior as golden_digits."""

    def __init__(self, n_train=10000, n_valid=2000, seed=2027, size=32):
        self.n_train = n_train
        self.n_valid = n_valid
        self.seed = seed
        self.size = size
        self._cache_ = None

    def __call__(self):
        if self._cache_ is None:
            rng = numpy.random.RandomState(self.seed)
            total = self.n_train + self.n_valid
            labels = rng.randint(0, 10, total).astype(numpy.int32)
            images = numpy.stack([_render_object(int(lbl), rng, self.size)
                                  for lbl in labels])
            self._cache_ = (images[:self.n_train],
                            labels[:self.n_train],
                            images[self.n_train:],
                            labels[self.n_train:])
        return self._cache_

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_cache_"] = None
        return state
