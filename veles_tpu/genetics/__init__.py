"""Genetic hyperparameter optimization (``veles/genetics/``).

The reference optimizes ``Config`` tuneables with gray-coded chromosomes
and a population evolved by roulette/tournament selection, four crossover
and four mutation operators (``veles/genetics/core.py:133-801``); fitness
of a chromosome is a full training run executed in a subprocess
(``veles/genetics/optimization_workflow.py:223-288``), farmed out to
slaves through the IDistributable protocol.

This package re-provides that capability TPU-natively: evaluation runs
are ordinary ``veles_tpu`` training invocations (each a single-controller
JAX process owning the chip), so the genetic layer stays pure host-side
Python and parallelism is population-level task farming — exactly the
reference's model (SURVEY.md §2.4 strategy 2).
"""

from veles_tpu.genetics.core import (Chromosome, Population,  # noqa: F401
                                     gray_encode, gray_decode)
from veles_tpu.genetics.optimizer import (GeneticsOptimizer,  # noqa: F401
                                          Tune, fix_config,
                                          collect_tuneables)
