"""Tune-able config leaves + the GeneticsOptimizer driver.

Re-designs ``veles/genetics/config.py`` (Tuneable/Range declared inline
in config files) and ``veles/genetics/optimization_workflow.py:70-288``
(GeneticsOptimizer: patch the config per chromosome, run the model in a
subprocess, read fitness from the results file, distribute pending
chromosomes to slaves through IDistributable).

Design change vs the reference: :class:`Tune` subclasses ``float``, so a
config file containing ``root.lr = Tune(0.03, 0.001, 0.1)`` runs
*unchanged* when not optimizing — no config-patching pass needed for the
regular path (the reference needs ``fix_config`` to strip Tuneables;
ours is provided for parity but is a no-op value-wise).
"""

import json
import os
import subprocess
import sys
import tempfile

from veles_tpu import prng
from veles_tpu.config import Config, root
from veles_tpu.distributable import Distributable, IDistributable
from veles_tpu.genetics.core import Population


class Tune(float):
    """A float config leaf marked as optimizable: Tune(default, min, max)."""

    def __new__(cls, default, min_value, max_value):
        self = super(Tune, cls).__new__(cls, default)
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        return self

    def __repr__(self):
        return "Tune(%s, %s, %s)" % (float(self), self.min_value,
                                     self.max_value)

    # Tune survives config pickling inside snapshots
    def __getnewargs__(self):
        return (float(self), self.min_value, self.max_value)


def collect_tuneables(node=None, path="root"):
    """Walk the config tree, return [(dotted_path, Tune), ...] sorted."""
    node = root if node is None else node
    found = []
    for key, value in node.items():
        child_path = "%s.%s" % (path, key)
        if isinstance(value, Config):
            found.extend(collect_tuneables(value, child_path))
        elif isinstance(value, Tune):
            found.append((child_path, value))
    found.sort(key=lambda kv: kv[0])
    return found


def fix_config(node=None):
    """Replace Tune leaves with their plain-float defaults (parity with
    the reference's ``fix_config``, ``veles/genetics/config.py``)."""
    node = root if node is None else node
    for key, value in node.items():
        if isinstance(value, Config):
            fix_config(value)
        elif isinstance(value, Tune):
            setattr(node, key, float(value))


class EvaluationError(Exception):
    """A fitness run failed (``optimization_workflow.py:64``)."""


class GeneticsOptimizer(Distributable, IDistributable):
    """Evolve Tune leaves to maximize a fitness metric.

    Two evaluation paths:

    * ``evaluator=callable({path: value}) -> float`` — in-process, used
      by tests and by meta-workflows that can score without training;
    * default — run ``python -m veles_tpu workflow config path=value ...
      --result-file tmp.json`` as a subprocess (the reference's ``_exec``,
      ``optimization_workflow.py:268-288``) and read the fitness back.

    Fitness is looked up in the results JSON under ``fitness_key``
    ("fitness" by default, then "EvaluationFitness"); if neither exists,
    the negated first numeric metric is used so "smaller error is better"
    workflows optimize correctly without modification.
    """

    def __init__(self, workflow_file=None, config_file=None,
                 generations=10, population_size=20, evaluator=None,
                 fitness_key="fitness", result_file=None, seed=None,
                 extra_argv=(), rand=None, warm=True, **kwargs):
        super(GeneticsOptimizer, self).__init__(**kwargs)
        #: keep ONE evaluator process alive across chromosomes (no JAX
        #: import/compile from the second fitness run on — VERDICT r2
        #: #6); False reproduces the reference's cold re-exec
        self.warm = warm
        self._pool_ = None
        self.workflow_file = workflow_file
        self.config_file = config_file
        self.generations = int(generations)
        self.population_size = int(population_size)
        self.evaluator = evaluator
        self.fitness_key = fitness_key
        self.result_file = result_file
        self.seed = seed if seed is not None else 1234
        self.extra_argv = list(extra_argv)
        self.rand = rand or prng.get()
        self.tuneables = collect_tuneables()
        if not self.tuneables:
            raise ValueError(
                "no Tune() leaves found in the config tree — nothing to "
                "optimize (declare e.g. root.lr = Tune(0.03, 0.001, 0.1))")
        self.population = Population(
            [t.min_value for _, t in self.tuneables],
            [t.max_value for _, t in self.tuneables],
            size=self.population_size, rand=self.rand)
        self.on_generation = None  # callback(population) for plots/logs

    # -- chromosome <-> config ---------------------------------------------

    def overrides_for(self, chromo):
        """{dotted.path: value} mapping for one chromosome."""
        return {path: float(v) for (path, _), v in
                zip(self.tuneables, chromo.numeric)}

    def _get_pool(self):
        if self._pool_ is None:
            from veles_tpu.parallel.warm_pool import WarmPool
            self._pool_ = WarmPool(workers=1)
            # slave-mode evaluations never pass through run()'s
            # finally — reap the evaluator at interpreter exit too.
            # Registered ONCE per instance: a close_pool/_get_pool
            # cycle (every run(); each scheduler-driven generation)
            # must not stack a fresh atexit entry pinning this
            # optimizer alive per recreation
            if not self._atexit_registered_:
                import atexit
                atexit.register(self.close_pool)
                self._atexit_registered_ = True
        return self._pool_

    def close_pool(self):
        if getattr(self, "_pool_", None) is not None:
            self._pool_.close()
            self._pool_ = None

    def _evaluate_subprocess(self, values):
        argv = [self.workflow_file]
        if self.config_file:
            argv.append(self.config_file)
        argv.extend("%s=%r" % (path, value)
                    for path, value in values.items())
        fd, result_path = tempfile.mkstemp(suffix=".json",
                                           prefix="veles_tpu_fitness_")
        os.close(fd)
        argv.extend(["--result-file", result_path,
                     "-s", str(self.seed), "-v", "warning"])
        argv.extend(self.extra_argv)
        if self.warm:
            # warm evaluator (the worker deletes the result file; the
            # finally covers a worker that died before getting there)
            try:
                reply = self._get_pool().run(argv,
                                             result_file=result_path)
            except (RuntimeError, OSError, ValueError) as e:
                # hard evaluator death: keep genetics' raise-on-failure
                # semantics, but route it through the module's own
                # failure type (the pool already replaced the worker)
                raise EvaluationError("fitness evaluator died: %s" % e)
            finally:
                try:
                    os.unlink(result_path)
                except OSError:
                    pass
            if not reply.get("ok"):
                raise EvaluationError(
                    "fitness run failed: %s" %
                    reply.get("error", reply.get("code")))
            return self._fitness_from_results(reply["result"])
        try:
            full = [sys.executable, "-m", "veles_tpu"] + argv
            proc = subprocess.run(full, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT)
            if proc.returncode != 0:
                raise EvaluationError(
                    "fitness run failed (%d): %s" %
                    (proc.returncode, proc.stdout[-2000:].decode(
                        errors="replace")))
            with open(result_path) as f:
                results = json.load(f)
        finally:
            try:
                os.unlink(result_path)
            except OSError:
                pass
        return self._fitness_from_results(results)

    def _fitness_from_results(self, results):
        for key in (self.fitness_key, "EvaluationFitness"):
            if key in results:
                return float(results[key])
        for value in results.values():
            if isinstance(value, (int, float)):
                return -float(value)
        raise EvaluationError("no numeric metric in results %r" % results)

    def evaluate(self, chromo):
        values = self.overrides_for(chromo)
        if self.evaluator is not None:
            chromo.fitness = float(self.evaluator(values))
        else:
            chromo.fitness = self._evaluate_subprocess(values)
        self.debug("fitness %.6g for %s", chromo.fitness, values)
        return chromo.fitness

    # -- driver ------------------------------------------------------------

    @property
    def best(self):
        return self.population.best

    def run(self):
        try:
            for _ in range(self.generations):
                for chromo in self.population.pending:
                    self.evaluate(chromo)
                best = self.population.best
                self.info(
                    "generation %d: best=%.6g avg=%.6g %s",
                    self.population.generation, best.fitness,
                    self.population.average_fitness,
                    self.overrides_for(best))
                if self.on_generation is not None:
                    self.on_generation(self.population)
                if self.population.generation < self.generations - 1:
                    self.population.update()
        finally:
            self.close_pool()
        self._write_results()
        return self.population.best

    def _write_results(self):
        if not self.result_file:
            return
        best = self.population.best
        with open(self.result_file, "w") as f:
            json.dump({"fitness": best.fitness,
                       "config": self.overrides_for(best),
                       "generations": self.population.generation + 1,
                       "population_size": self.population_size}, f,
                      indent=2)
        self.info("wrote best config to %s", self.result_file)

    # -- task farming over the coordinator (strategy 2, SURVEY.md §2.4) ----
    #
    # Each job is one pending chromosome's override dict; the update is
    # its fitness. ``drop_slave`` requeues chromosomes a dead slave held
    # (the reference's elastic-recovery semantics,
    # ``optimization_workflow.py:181-221``).

    def init_unpickled(self):
        super(GeneticsOptimizer, self).init_unpickled()
        self._dispatched_ = {}
        self._pool_ = None
        self._atexit_registered_ = False

    @property
    def has_data_for_slave(self):
        return bool(self.population.pending or
                    all(c.fitness is not None for c in self.population) and
                    self.population.generation < self.generations)

    def generate_data_for_slave(self, slave):
        pending = [c for c in self.population.pending
                   if id(c) not in {id(x) for lst in
                                    self._dispatched_.values()
                                    for x in lst}]
        if not pending and not self.population.pending:
            if self.population.generation >= self.generations - 1:
                return None
            self.population.update()
            pending = self.population.pending
        if not pending:
            return None
        chromo = pending[0]
        self._dispatched_.setdefault(slave, []).append(chromo)
        return {"index": self.population.chromosomes.index(chromo),
                "values": self.overrides_for(chromo)}

    def apply_data_from_master(self, data):
        self._job_ = data

    def generate_data_for_master(self):
        values = self._job_["values"]
        if self.evaluator is not None:
            fitness = float(self.evaluator(values))
        else:
            fitness = self._evaluate_subprocess(values)
        return {"index": self._job_["index"], "fitness": fitness}

    def apply_data_from_slave(self, data, slave):
        chromo = self.population.chromosomes[data["index"]]
        chromo.fitness = data["fitness"]
        held = self._dispatched_.get(slave, [])
        self._dispatched_[slave] = [c for c in held if c is not chromo]

    def drop_slave(self, slave):
        self._dispatched_.pop(slave, None)
