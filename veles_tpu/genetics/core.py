"""Chromosome + Population: the genetic-algorithm engine.

Re-designs ``veles/genetics/core.py``. The reference keeps chromosomes as
gray-code *strings* and converts with list ``index()`` lookups
(``core.py:70-120``); here genes are fixed-point integers gray-coded with
the closed-form ``n ^ (n >> 1)`` transform and decoded by prefix-XOR —
same semantics (small genotype steps = small phenotype steps), vectorized
with numpy instead of string scanning.

Operators kept at parity (``veles/genetics/core.py``):
* selection: roulette (:578), random (:596), tournament (:605)
* crossover: pointed (:633), uniform (:672), arithmetic (:707),
  geometric (:747)
* mutation: binary_point (:260), altering (:277), gaussian (:310),
  uniform (:346)
"""

import numpy

from veles_tpu import prng
from veles_tpu.distributable import Pickleable


def gray_encode(n):
    """Binary-reflected gray code of a non-negative int (or array)."""
    return n ^ (n >> 1)


def gray_decode(g):
    """Inverse of :func:`gray_encode` via the XOR-shift cascade."""
    n = numpy.array(g, dtype=numpy.int64, copy=True)
    shift = 1
    while shift < 64:
        n ^= n >> shift
        shift *= 2
    return n


class Chromosome(Pickleable):
    """One candidate: a vector of genes, each a float in [min, max].

    The genotype is the per-gene fixed-point integer
    ``round((value - min) / (max - min) * (2**bits - 1))`` stored
    gray-coded; binary operators work on that code, numeric operators on
    the float vector (the reference's dual binary/numeric representation,
    ``core.py:145-204``).
    """

    BITS = 16

    def __init__(self, min_values, max_values, values=None, codes=None,
                 rand=None):
        super(Chromosome, self).__init__()
        self.min_values = numpy.asarray(min_values, dtype=numpy.float64)
        self.max_values = numpy.asarray(max_values, dtype=numpy.float64)
        self.fitness = None
        rand = rand or prng.get()
        if codes is not None:
            self.codes = numpy.asarray(codes, dtype=numpy.int64)
        elif values is not None:
            self.codes = self._encode(numpy.asarray(values,
                                                    dtype=numpy.float64))
        else:
            span = self.max_values - self.min_values
            vals = self.min_values + rand.rand(len(span)) * span
            self.codes = self._encode(vals)

    # -- genotype <-> phenotype -------------------------------------------

    @property
    def size(self):
        return len(self.min_values)

    @property
    def full_scale(self):
        return (1 << self.BITS) - 1

    def _encode(self, values):
        span = numpy.maximum(self.max_values - self.min_values, 1e-30)
        frac = numpy.clip((values - self.min_values) / span, 0.0, 1.0)
        ints = numpy.round(frac * self.full_scale).astype(numpy.int64)
        return gray_encode(ints)

    @property
    def numeric(self):
        """Decoded float values, always inside [min, max]."""
        ints = gray_decode(self.codes).astype(numpy.float64)
        frac = numpy.clip(ints / self.full_scale, 0.0, 1.0)
        return self.min_values + frac * (self.max_values - self.min_values)

    def copy(self):
        clone = Chromosome(self.min_values, self.max_values,
                           codes=self.codes.copy())
        clone.fitness = self.fitness
        return clone

    # -- mutation (``core.py:257-369``) -----------------------------------

    def mutate(self, kind, n_points, probability, rand=None):
        getattr(self, "mutation_" + kind)(n_points, probability,
                                          rand or prng.get())
        self.fitness = None

    def mutation_binary_point(self, n_points, probability, rand):
        """Flip random bits of random genes."""
        for _ in range(n_points):
            if rand.rand() >= probability:
                continue
            gene = rand.randint(self.size)
            bit = rand.randint(self.BITS)
            self.codes[gene] ^= (1 << bit)

    def mutation_altering(self, n_points, probability, rand):
        """Swap bit values between two random (gene, bit) positions."""
        for _ in range(n_points):
            if rand.rand() >= probability:
                continue
            g1, g2 = rand.randint(self.size), rand.randint(self.size)
            b1, b2 = rand.randint(self.BITS), rand.randint(self.BITS)
            v1 = (self.codes[g1] >> b1) & 1
            v2 = (self.codes[g2] >> b2) & 1
            self.codes[g1] = (self.codes[g1] & ~(1 << b1)) | (v2 << b1)
            self.codes[g2] = (self.codes[g2] & ~(1 << b2)) | (v1 << b2)

    def mutation_gaussian(self, n_points, probability, rand):
        """Add N(0, span/10) noise to random genes (numeric domain)."""
        values = self.numeric
        span = self.max_values - self.min_values
        for _ in range(n_points):
            if rand.rand() >= probability:
                continue
            gene = rand.randint(self.size)
            values[gene] += rand.normal(0.0, max(span[gene] / 10.0, 1e-30))
        numpy.clip(values, self.min_values, self.max_values, out=values)
        self.codes = self._encode(values)

    def mutation_uniform(self, n_points, probability, rand):
        """Resample random genes uniformly in their range."""
        values = self.numeric
        for _ in range(n_points):
            if rand.rand() >= probability:
                continue
            gene = rand.randint(self.size)
            values[gene] = (self.min_values[gene] + rand.rand() *
                            (self.max_values[gene] - self.min_values[gene]))
        self.codes = self._encode(values)

    def __repr__(self):
        return "<Chromosome %s fitness=%s>" % (
            numpy.array2string(self.numeric, precision=4), self.fitness)


class Population(Pickleable):
    """A set of chromosomes evolved generation by generation.

    Mirrors ``veles/genetics/core.py:371-801``: elitism keeps the best
    half, selection picks parents, crossover + mutation refill the
    population; ``pending`` yields chromosomes awaiting fitness so the
    optimizer (or its slaves) can evaluate them out of order.
    """

    def __init__(self, min_values, max_values, size=20, rand=None,
                 crossover_rate=0.9, mutation_probability=0.3):
        super(Population, self).__init__()
        self.min_values = numpy.asarray(min_values, dtype=numpy.float64)
        self.max_values = numpy.asarray(max_values, dtype=numpy.float64)
        self.size = int(size)
        self.generation = 0
        self.crossover_rate = crossover_rate
        self.mutation_probability = mutation_probability
        self.crossovers = ("pointed", "uniform", "arithmetic", "geometric")
        self.mutations = ("binary_point", "altering", "gaussian", "uniform")
        self.rand = rand or prng.get()
        self.chromosomes = [Chromosome(self.min_values, self.max_values,
                                       rand=self.rand)
                            for _ in range(self.size)]

    # -- container --------------------------------------------------------

    def __len__(self):
        return len(self.chromosomes)

    def __getitem__(self, i):
        return self.chromosomes[i]

    def __iter__(self):
        return iter(self.chromosomes)

    @property
    def pending(self):
        """Chromosomes whose fitness is not yet known."""
        return [c for c in self.chromosomes if c.fitness is None]

    @property
    def evaluated(self):
        return [c for c in self.chromosomes if c.fitness is not None]

    @property
    def best(self):
        done = self.evaluated
        return max(done, key=lambda c: c.fitness) if done else None

    @property
    def average_fitness(self):
        done = self.evaluated
        return (sum(c.fitness for c in done) / len(done)) if done else None

    # -- selection (``core.py:573-616``) ----------------------------------

    def select_roulette(self):
        """Fitness-proportionate pick (shifted to non-negative)."""
        done = self.evaluated
        fits = numpy.array([c.fitness for c in done], dtype=numpy.float64)
        fits = fits - fits.min() + 1e-12
        wheel = numpy.cumsum(fits / fits.sum())
        return done[int(numpy.searchsorted(wheel, self.rand.rand()))]

    def select_random(self):
        return self.evaluated[self.rand.randint(len(self.evaluated))]

    def select_tournament(self, k=3):
        done = self.evaluated
        picks = [done[self.rand.randint(len(done))]
                 for _ in range(min(k, len(done)))]
        return max(picks, key=lambda c: c.fitness)

    def select(self):
        kind = ("roulette", "tournament", "random")[self.rand.randint(3)]
        return getattr(self, "select_" + kind)()

    # -- crossover (``core.py:618-786``) ----------------------------------

    def cross_pointed(self, a, b):
        """k-point crossover on the flat gray bitstring."""
        bits = Chromosome.BITS
        total = a.size * bits
        k = 1 + self.rand.randint(3)
        points = sorted(self.rand.randint(1, total, size=k).tolist())
        codes = a.codes.copy()
        src = (a, b)
        which, prev = 0, 0
        for point in points + [total]:
            if which:
                for pos in range(prev, point):
                    gene, bit = divmod(pos, bits)
                    other = (src[1].codes[gene] >> bit) & 1
                    codes[gene] = ((codes[gene] & ~(1 << bit)) |
                                   (other << bit))
            which ^= 1
            prev = point
        return Chromosome(self.min_values, self.max_values, codes=codes)

    def cross_uniform(self, a, b):
        """Each bit independently from either parent."""
        mask = numpy.asarray(
            self.rand.randint(0, 1 << Chromosome.BITS, size=a.size),
            dtype=numpy.int64)
        codes = (a.codes & mask) | (b.codes & ~mask)
        return Chromosome(self.min_values, self.max_values, codes=codes)

    def cross_arithmetic(self, a, b):
        """Per-gene convex blend in the numeric domain."""
        t = self.rand.rand(a.size)
        values = t * a.numeric + (1.0 - t) * b.numeric
        return Chromosome(self.min_values, self.max_values, values=values)

    def cross_geometric(self, a, b):
        """Per-gene geometric mean (in range-relative coordinates)."""
        span = numpy.maximum(self.max_values - self.min_values, 1e-30)
        fa = numpy.clip((a.numeric - self.min_values) / span, 1e-12, 1.0)
        fb = numpy.clip((b.numeric - self.min_values) / span, 1e-12, 1.0)
        t = self.rand.rand(a.size)
        frac = numpy.power(fa, t) * numpy.power(fb, 1.0 - t)
        values = self.min_values + frac * span
        return Chromosome(self.min_values, self.max_values, values=values)

    def cross(self, a, b):
        kind = self.crossovers[self.rand.randint(len(self.crossovers))]
        return getattr(self, "cross_" + kind)(a, b)

    # -- generation step (``core.py:525-571``) ----------------------------

    def update(self):
        """Advance one generation. All fitnesses must be known."""
        if self.pending:
            raise ValueError("%d chromosomes still pending evaluation"
                             % len(self.pending))
        ranked = sorted(self.chromosomes, key=lambda c: c.fitness,
                        reverse=True)
        survivors = ranked[:max(2, self.size // 2)]
        children = []
        while len(survivors) + len(children) < self.size:
            if self.rand.rand() < self.crossover_rate:
                child = self.cross(self.select(), self.select())
            else:
                child = self.select().copy()
            child.mutate(
                self.mutations[self.rand.randint(len(self.mutations))],
                n_points=2, probability=self.mutation_probability,
                rand=self.rand)
            children.append(child)
        self.chromosomes = survivors + children
        self.generation += 1
        return self
