"""HTTP frontend of the serving engine.

Speaks the same request contract as
:class:`~veles_tpu.restful_api.RESTfulAPI` (``{"input": ...,
"codec": "list"|"base64"[, "shape", "type", "id"]}`` → ``{"result":
...[, "id"]}``) so existing clients move over unchanged, plus:

* ``POST <path>/batch`` — ``{"inputs": [...], "codec": "list"}`` (or
  base64 with a leading batch dim in ``shape``): the rows ride the same
  dynamic batcher and come back as ``{"results": [...]}`` in order.
* ``GET /metrics`` — Prometheus text exposition of the process-wide
  telemetry registry (serving + any co-resident training series).
* ``GET /metrics.json`` — the JSON metrics snapshot
  (:class:`~veles_tpu.serving.metrics.ServingMetrics`).
* ``GET /profile.json`` — the performance-attribution report
  (:func:`veles_tpu.telemetry.profiler.profile_report`): per-bucket
  forward cost/roofline rows, memory sample, startup phases.
* ``GET /healthz`` — liveness + current model name/version.

A client-supplied ``X-Request-Id`` header (or the body's ``"id"``)
becomes the trace id of the request's span, so a single request can be
found in a ``--trace-out`` dump by the id the client already logs.

Admission control is the engine's bounded queue: overload returns
**HTTP 503 with a Retry-After header** immediately — the frontend never
parks a client thread behind a saturated accelerator.

Run standalone: ``python -m veles_tpu serve --model <snapshot|package>``
(see :func:`main` for flags, ``docs/SERVING.md`` for the operations
guide). With ``--web-status host:port`` the frontend pushes its metrics
block to the dashboard, rendered in ``/status.html``.
"""

import argparse
import concurrent.futures
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy

from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.restful_api import (_NumpyJSONEncoder, parse_payload,
                                   respond_json)
from veles_tpu.serving.engine import DynamicBatcher, EngineOverloaded
from veles_tpu.serving.metrics import ServingMetrics
from veles_tpu.serving.model_store import ModelStore
from veles_tpu.serving.replica import ReplicaPool
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.registry import get_registry


class _FrontendHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        self.server.frontend.debug("http: " + fmt, *args)

    def do_POST(self):
        self.server.frontend.handle_post(self)

    def do_GET(self):
        self.server.frontend.handle_get(self)


class _FrontendServer(ThreadingHTTPServer):
    daemon_threads = True
    # socketserver's default accept backlog is 5: a burst of >5
    # concurrent connects overflows the SYN queue and the spilled
    # clients stall in kernel retransmit (~1s each) — for a server
    # whose whole point is absorbing concurrent bursts, the backlog
    # must exceed the expected client count
    request_queue_size = 128


class ServingFrontend(Logger):
    """The serving process: model store + replica pool + batcher + HTTP.

    ``model`` may be a :class:`ServeableModel` or a path/URI the store
    can load. ``swap_model(source)`` hot-swaps live traffic onto a new
    version (drain each replica in turn, promote, re-warm).
    """

    def __init__(self, model, host="", port=8180, path="/api",
                 replicas=1, max_batch_size=64, batch_timeout_ms=5.0,
                 max_queue=256, response_timeout=30.0, warm=True):
        super(ServingFrontend, self).__init__()
        self.store = ModelStore()
        if isinstance(model, str):
            model = self.store.load(model)
        else:
            self.store.add(model, version=model.version)
        self.path = path
        self.response_timeout = float(response_timeout)
        self.metrics = ServingMetrics()
        self.metrics.set_model(model.name, model.version)
        self.pool = ReplicaPool(model, n_replicas=replicas,
                                max_batch_size=max_batch_size, warm=warm)
        self.engine = DynamicBatcher(
            self.pool, max_batch_size=max_batch_size,
            batch_timeout_ms=batch_timeout_ms, max_queue=max_queue,
            metrics=self.metrics)
        self._server = _FrontendServer((host, port), _FrontendHandler)
        self._server.frontend = self
        self.address = self._server.server_address
        self._thread = None
        self._reporter = None
        # continuous SLO evaluation (p95 / queue-depth / shed-burn
        # rules) — the series item 3's autoscaler will consume
        from veles_tpu.telemetry import alerts
        alerts.get_engine().start()

    @property
    def port(self):
        return self.address[1]

    @property
    def model(self):
        return self.pool.model

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serving-http")
        self._thread.start()
        self.info("serving %s v%d on %s:%d%s (%d replica(s), "
                  "max batch %d)", self.model.name, self.model.version,
                  self.address[0] or "0.0.0.0", self.port, self.path,
                  len(self.pool.replicas), self.pool.max_batch_size)
        return self

    def stop(self):
        if self._reporter is not None:
            self._reporter.stop()
            self._reporter = None
        self._server.shutdown()
        self._server.server_close()
        self.engine.stop()
        self.pool.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def swap_model(self, source, name=None, version=None):
        """Load + register a new model version and promote the pool to
        it (drain-old / promote-new, one replica at a time)."""
        if isinstance(source, str):
            model = self.store.load(source, name=name or self.model.name,
                                    version=version)
        else:
            model = self.store.add(source, version=version)
        if tuple(model.sample_shape) != tuple(self.model.sample_shape):
            raise ValueError(
                "refusing hot-swap: new sample shape %s != serving %s"
                % (model.sample_shape, self.model.sample_shape))
        self.pool.swap(model)
        self.metrics.set_model(model.name, model.version)
        return model

    def report_to(self, web_status_address, interval=2.0, name=None):
        """Push the metrics block to a web_status dashboard."""
        self._reporter = _StatusReporter(
            self, web_status_address, interval=interval,
            name=name or self.model.name)
        self._reporter.start()
        return self._reporter

    # -- HTTP plumbing -----------------------------------------------------

    @staticmethod
    def _respond(handler, code, payload, headers=None):
        respond_json(handler, code, payload, headers=headers)

    def _fail(self, handler, endpoint, message, code=400, rid=None,
              headers=None, t0=None):
        if code == 503:
            # expected shedding under overload — hundreds per second;
            # the rejected_total metric is the operator's signal
            self.debug(message)
        else:
            self.warning(message)
        payload = {"error": message}
        if rid is not None:
            payload["id"] = rid
        self._respond(handler, code, payload, headers=headers)
        self.metrics.record_request(
            endpoint, code,
            (time.time() - t0) * 1000.0 if t0 else None)

    def handle_get(self, handler):
        if handler.path.startswith("/profile.json"):
            from veles_tpu.telemetry import profiler
            self._respond(handler, 200, profiler.profile_report())
        elif handler.path.startswith("/alerts.json"):
            from veles_tpu.telemetry import alerts
            self._respond(handler, 200, alerts.get_engine().report())
        elif handler.path.startswith("/metrics.json"):
            self._respond(handler, 200, self.metrics.snapshot())
        elif handler.path.startswith("/metrics"):
            body = get_registry().render_prometheus().encode("utf-8")
            handler.send_response(200)
            handler.send_header("Content-Type",
                                "text/plain; version=0.0.4")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif handler.path.startswith("/healthz"):
            self._respond(handler, 200, {
                "status": "ok", "model": self.model.name,
                "version": self.model.version,
                "sample_shape": list(self.model.sample_shape)})
        else:
            self._respond(handler, 404, {"error": "not found"})

    def handle_post(self, handler):
        t0 = time.time()
        # same body-drain discipline as restful_api: unread bytes on a
        # keep-alive connection corrupt the next request
        if handler.headers.get("Transfer-Encoding"):
            handler.close_connection = True
            self._fail(handler, handler.path, "Content-Length required "
                       "(Transfer-Encoding is not supported)", code=411,
                       t0=t0)
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            raw = handler.rfile.read(length)
        except (TypeError, ValueError):
            handler.close_connection = True
            self._fail(handler, handler.path, "Invalid Content-Length",
                       t0=t0)
            return
        if handler.path == self.path:
            endpoint, batched = self.path, False
        elif handler.path == self.path + "/batch":
            endpoint, batched = self.path + "/batch", True
        else:
            self._fail(handler, handler.path,
                       "API path %s is not supported" % handler.path,
                       code=404, t0=t0)
            return
        ctype = (handler.headers.get("Content-Type") or "").split(";")[0]
        if ctype.strip() != "application/json":
            self._fail(handler, endpoint, "Unsupported Content-Type "
                       "(must be \"application/json\")", t0=t0)
            return
        try:
            request = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self._fail(handler, endpoint, "Failed to parse JSON", t0=t0)
            return
        rid = request.get("id") if isinstance(request, dict) else None
        # request-id → trace-id bridge: the span for this request (and
        # everything under it) carries the client's X-Request-Id / "id"
        trace_id = tracing.trace_id_from_request(handler.headers, rid)
        try:
            with tracing.request_span("http:%s" % endpoint,
                                      trace_id=trace_id):
                if batched:
                    self._serve_batch(handler, endpoint, request, rid, t0)
                else:
                    self._serve_one(handler, endpoint, request, rid, t0)
        except EngineOverloaded as e:
            self._fail(handler, endpoint, str(e), code=503, rid=rid,
                       headers={"Retry-After": str(e.retry_after)},
                       t0=t0)

    def _serve_one(self, handler, endpoint, request, rid, t0):
        data, error = parse_payload(request)
        if error is not None:
            self._fail(handler, endpoint, error, rid=rid, t0=t0)
            return
        try:
            future = self.engine.submit(data)
        except ValueError as e:
            self._fail(handler, endpoint, "Invalid input value: %s" % e,
                       rid=rid, t0=t0)
            return
        self._await_and_reply(handler, endpoint, [future], rid, t0,
                              single=True)

    def _serve_batch(self, handler, endpoint, request, rid, t0):
        if not isinstance(request, dict) or "codec" not in request or \
                ("inputs" not in request and "input" not in request):
            self._fail(handler, endpoint, "Invalid input format: there "
                       "must be \"inputs\" and \"codec\" attributes",
                       rid=rid, t0=t0)
            return
        if "inputs" in request:
            rows_spec = request["inputs"]
            if not isinstance(rows_spec, list) or not rows_spec:
                self._fail(handler, endpoint,
                           "\"inputs\" must be a non-empty array",
                           rid=rid, t0=t0)
                return
            if request["codec"] == "list":
                try:
                    rows = [numpy.array(r, numpy.float32)
                            for r in rows_spec]
                except (TypeError, ValueError):
                    self._fail(handler, endpoint,
                               "Invalid input array format", rid=rid,
                               t0=t0)
                    return
            else:
                rows = []
                for r in rows_spec:
                    data, error = parse_payload(
                        dict(request, input=r, inputs=None))
                    if error is not None:
                        self._fail(handler, endpoint, error, rid=rid,
                                   t0=t0)
                        return
                    rows.append(data)
        else:
            # base64 with a leading batch dim in "shape"
            data, error = parse_payload(request)
            if error is not None:
                self._fail(handler, endpoint, error, rid=rid, t0=t0)
                return
            rows = list(data)
        futures = []
        try:
            for row in rows:
                futures.append(self.engine.submit(row))
        except ValueError as e:
            # rows already admitted still complete; their results are
            # simply dropped with the failed request
            self._fail(handler, endpoint, "Invalid input value: %s" % e,
                       rid=rid, t0=t0)
            return
        self._await_and_reply(handler, endpoint, futures, rid, t0,
                              single=False)

    def _await_and_reply(self, handler, endpoint, futures, rid, t0,
                         single):
        try:
            deadline = t0 + self.response_timeout
            results = [f.result(timeout=max(deadline - time.time(),
                                            0.001))
                       for f in futures]
        except concurrent.futures.TimeoutError:
            self._fail(handler, endpoint,
                       "The model did not respond in time", code=500,
                       rid=rid, t0=t0)
            return
        except EngineOverloaded:
            raise
        except Exception as e:
            self._fail(handler, endpoint, "inference failed: %s"
                       % (str(e) or type(e).__name__), code=500,
                       rid=rid, t0=t0)
            return
        if single:
            payload = {"result": results[0]}
        else:
            payload = {"results": results}
        if rid is not None:
            payload["id"] = rid
        self._respond(handler, 200, payload)
        self.metrics.record_request(endpoint, 200,
                                    (time.time() - t0) * 1000.0)


class _StatusReporter(Logger):
    """POSTs the serving block to web_status ``/update`` periodically
    (the serving analog of the Launcher's status notifier)."""

    def __init__(self, frontend, address, interval=2.0, name="serving"):
        super(_StatusReporter, self).__init__()
        if isinstance(address, str):
            host, _, port = address.partition(":")
            address = (host or "127.0.0.1", int(port or 8090))
        self.url = "http://%s:%d/update" % tuple(address)
        self.frontend = frontend
        self.interval = interval
        self.name = name
        self.id = str(uuid.uuid4())
        self._started = time.time()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-status")
        self._thread.start()
        return self

    def _payload(self):
        return {
            "id": self.id,
            "name": self.name,
            "mode": "serve",
            "master": self.frontend.address[0] or "localhost",
            "time": time.time() - self._started,
            "units": len(self.frontend.pool.replicas),
            "stopped": False,
            "serving": self.frontend.metrics.dashboard_block(),
        }

    def _post_once(self):
        import urllib.request
        try:
            req = urllib.request.Request(
                self.url,
                data=json.dumps(self._payload(),
                                cls=_NumpyJSONEncoder).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=2.0)
        except Exception as e:
            self.debug("web_status push failed: %s", e)

    def _loop(self):
        while not self._stop.wait(self.interval):
            self._post_once()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def main(argv=None):
    """``python -m veles_tpu serve ...`` / ``veles-tpu-serve``."""
    parser = argparse.ArgumentParser(
        prog="veles_tpu serve",
        description="dynamic-batching inference server")
    parser.add_argument("--model", required=True,
                        help="snapshot file/dir/URI or export package")
    parser.add_argument("--name", default=None,
                        help="model name in the store (default: from "
                             "the artifact)")
    parser.add_argument("--host", default="")
    parser.add_argument("--port", type=int, default=8180)
    parser.add_argument("--path", default=root.common.api.path)
    parser.add_argument("--replicas", type=int, default=1)
    parser.add_argument("--max-batch-size", type=int, default=64)
    parser.add_argument("--batch-timeout-ms", type=float, default=5.0)
    parser.add_argument("--max-queue", type=int, default=256,
                        help="admission bound; beyond it requests get "
                             "503 + Retry-After")
    parser.add_argument("--response-timeout", type=float, default=30.0)
    parser.add_argument("--web-status", default=None, metavar="HOST:PORT",
                        help="push serving metrics to this dashboard")
    parser.add_argument("--trace-out", default=None, metavar="FILE",
                        help="enable span tracing and dump the trace "
                             "buffer (Chrome trace-event JSON, open in "
                             "Perfetto) to FILE at exit")
    parser.add_argument("-v", "--verbosity", default="info",
                        choices=["debug", "info", "warning", "error"])
    args = parser.parse_args(argv)
    import logging

    from veles_tpu.logger import setup_logging
    setup_logging(getattr(logging, args.verbosity.upper()))
    if args.trace_out:
        tracing.enable()
        import os
        try:  # don't merge into a stale file from a previous run
            os.remove(args.trace_out)
        except OSError:
            pass
    from veles_tpu.telemetry import profiler
    profiler.start_memory_sampler()
    store = ModelStore()
    model = store.load(args.model, name=args.name)
    frontend = ServingFrontend(
        model, host=args.host, port=args.port, path=args.path,
        replicas=args.replicas, max_batch_size=args.max_batch_size,
        batch_timeout_ms=args.batch_timeout_ms, max_queue=args.max_queue,
        response_timeout=args.response_timeout)
    frontend.store = store
    if args.web_status:
        frontend.report_to(args.web_status)
    frontend.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        frontend.stop()
        if args.trace_out:
            n = tracing.get_buffer().dump(args.trace_out,
                                          process_name="serve")
            frontend.info("wrote %d trace events to %s", n,
                          args.trace_out)
            if profiler.dump_memory_profile(args.trace_out + ".memprof"):
                frontend.info("wrote device memory profile to %s.memprof",
                              args.trace_out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
