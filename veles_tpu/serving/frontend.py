"""HTTP frontend of the serving engine.

Speaks the same request contract as
:class:`~veles_tpu.restful_api.RESTfulAPI` (``{"input": ...,
"codec": "list"|"base64"[, "shape", "type", "id"]}`` → ``{"result":
...[, "id"]}``) so existing clients move over unchanged, plus:

* ``POST <path>/batch`` — ``{"inputs": [...], "codec": "list"}`` (or
  base64 with a leading batch dim in ``shape``): the rows ride the same
  dynamic batcher and come back as ``{"results": [...]}`` in order.
* **Multi-model routing** (ISSUE 14): one process hosts N models from
  the same :class:`ModelStore` — ``POST <path>/<model>`` (and
  ``<path>/<model>/batch``) routes by model name, each model with its
  own replica pool, result cache, tenant buckets and autoscaler; the
  bare ``<path>`` stays wired to the default (first) model so
  single-model clients never change.
* ``GET /metrics`` — Prometheus text exposition of the process-wide
  telemetry registry (serving + any co-resident training series).
* ``GET /metrics.json`` — the JSON metrics snapshot
  (:class:`~veles_tpu.serving.metrics.ServingMetrics`), with
  per-tenant admission stats and per-model blocks.
* ``GET /profile.json`` — the performance-attribution report
  (:func:`veles_tpu.telemetry.profiler.profile_report`): per-bucket
  forward cost/roofline rows, memory sample, startup phases.
* ``GET /history.json?series=&since=`` — retained metric history from
  the bounded :class:`~veles_tpu.telemetry.timeseries.SeriesStore`
  (the canary-comparison substrate).
* ``GET /healthz`` — liveness + current model name/version (every
  hosted model listed under ``"models"``).

Per-tenant QoS: the ``X-Tenant`` header (or the body's ``"tenant"``)
names the client's admission bucket; ``X-QoS`` (or ``"qos"``) declares
``interactive``/``batch``/``best_effort``. Overload answers **HTTP 503
with Retry-After computed from that tenant's own drain rate** — a
greedy tenant sheds onto itself, not onto everyone
(``serving/admission.py``).

A client-supplied ``X-Request-Id`` header (or the body's ``"id"``)
becomes the trace id of the request's span, so a single request can be
found in a ``--trace-out`` dump by the id the client already logs.

Run standalone: ``python -m veles_tpu serve --model <snapshot|package>``
(``--model`` repeats, ``name=path`` names a route; see :func:`main`
for the autoscale/cache/tenant flags, ``docs/SERVING.md`` for the
operations guide). With ``--web-status host:port`` the frontend pushes
its metrics block to the dashboard, rendered in ``/status.html``.
"""

import argparse
import concurrent.futures
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy

from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.restful_api import (_NumpyJSONEncoder, parse_payload,
                                   respond_json)
from veles_tpu.serving.admission import (QOS_MULTIPLIER,
                                         AdmissionController)
from veles_tpu.serving.autoscale import Autoscaler
from veles_tpu.serving.cache import ResultCache
from veles_tpu.serving.engine import (DeadlineExceeded, DynamicBatcher,
                                      EngineOverloaded)
from veles_tpu.serving.metrics import ServingMetrics
from veles_tpu.serving.model_store import ModelStore
from veles_tpu.serving.replica import ReplicaPool
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.registry import get_registry


class _FrontendHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        self.server.frontend.debug("http: " + fmt, *args)

    def do_POST(self):
        self.server.frontend.handle_post(self)

    def do_GET(self):
        self.server.frontend.handle_get(self)


class _FrontendServer(ThreadingHTTPServer):
    daemon_threads = True
    # socketserver's default accept backlog is 5: a burst of >5
    # concurrent connects overflows the SYN queue and the spilled
    # clients stall in kernel retransmit (~1s each) — for a server
    # whose whole point is absorbing concurrent bursts, the backlog
    # must exceed the expected client count
    request_queue_size = 128


class _ModelEntry(object):
    """Everything one hosted model owns: pool, batcher, cache,
    admission buckets, metrics, optional autoscaler."""

    def __init__(self, name, model, replicas, max_batch_size,
                 batch_timeout_ms, max_queue, warm, cache_mb,
                 cache_ttl_s, tenants, min_replicas, max_replicas,
                 autoscale_interval_s):
        self.name = name
        self.metrics = ServingMetrics(model_label=name)
        self.metrics.set_model(model.name, model.version)
        self.pool = ReplicaPool(model, n_replicas=replicas,
                                max_batch_size=max_batch_size,
                                warm=warm)
        self.cache = ResultCache(max_bytes=int(cache_mb * (1 << 20)),
                                 ttl_s=cache_ttl_s,
                                 model=name) if cache_mb else None
        self.admission = AdmissionController(capacity=max_queue,
                                             tenants=tenants,
                                             model=name)
        self.engine = DynamicBatcher(
            self.pool, max_batch_size=max_batch_size,
            batch_timeout_ms=batch_timeout_ms, max_queue=max_queue,
            metrics=self.metrics, cache=self.cache,
            admission=self.admission)
        self.autoscaler = None
        if max_replicas is not None and max_replicas > 0:
            self.autoscaler = Autoscaler(
                self.pool, self.engine,
                min_replicas=min_replicas or replicas,
                max_replicas=max_replicas,
                interval_s=autoscale_interval_s, model=name)

    @property
    def model(self):
        return self.pool.model

    def snapshot(self):
        snap = self.metrics.snapshot()
        snap["tenants"] = self.admission.stats()["tenants"]
        if self.autoscaler is not None:
            snap["autoscale"] = {
                "replicas": self.pool.size(),
                "min": self.autoscaler.min_replicas,
                "max": self.autoscaler.max_replicas,
            }
        return snap

    def stop(self):
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.engine.stop()
        self.pool.stop()


class ServingFrontend(Logger):
    """The serving process: model store + N model entries + HTTP.

    ``model`` may be one :class:`ServeableModel` or path/URI (the
    single-model shape every PR 3 client uses), or a list/dict of
    them — a dict's keys name the routes, otherwise each model's own
    name does. ``swap_model(source, name=...)`` hot-swaps one entry's
    live traffic onto a new version (drain each replica in turn,
    promote, re-warm, atomically invalidate that model's result
    cache).
    """

    def __init__(self, model, host="", port=8180, path="/api",
                 replicas=1, max_batch_size=64, batch_timeout_ms=5.0,
                 max_queue=256, response_timeout=30.0, warm=True,
                 cache_mb=64, cache_ttl_s=300.0, tenants=None,
                 tenant_header="X-Tenant", qos_header="X-QoS",
                 deadline_header="X-Deadline-Ms",
                 min_replicas=None, max_replicas=None,
                 autoscale_interval_s=0.5, store=None,
                 keep_last=None):
        super(ServingFrontend, self).__init__()
        self.store = store or ModelStore(keep_last=keep_last)
        self.path = path
        self.response_timeout = float(response_timeout)
        self.tenant_header = tenant_header
        self.qos_header = qos_header
        self.deadline_header = deadline_header
        self.entries = {}
        if isinstance(model, dict):
            specs = list(model.items())
        elif isinstance(model, (list, tuple)):
            specs = [(None, m) for m in model]
        else:
            specs = [(None, model)]
        try:
            for name, source in specs:
                if isinstance(source, str):
                    served = self.store.load(source, name=name)
                else:
                    # keyed by the ROUTE: two routes serving variants
                    # that share a model name must not overwrite each
                    # other's store entries
                    served = self.store.add(source,
                                            version=source.version,
                                            name=name)
                route = name or served.name
                if route == "batch" or "/" in route:
                    raise ValueError(
                        "model route %r collides with the request "
                        "paths (rename it)" % route)
                if route in self.entries:
                    raise ValueError("duplicate model route %r"
                                     % route)
                self.entries[route] = _ModelEntry(
                    route, served, replicas, max_batch_size,
                    batch_timeout_ms, max_queue, warm, cache_mb,
                    cache_ttl_s, tenants, min_replicas, max_replicas,
                    autoscale_interval_s)
            self.default_route = next(iter(self.entries))
            self._server = _FrontendServer((host, port),
                                           _FrontendHandler)
        except Exception:
            # a later entry (or the HTTP bind) failing must not leak
            # the earlier entries' replica pools and batcher threads —
            # they are already running and warmed, with no handle left
            # for the caller to stop them
            for entry in self.entries.values():
                try:
                    entry.stop()
                except Exception:
                    self.exception("entry %r cleanup failed",
                                   entry.name)
            raise
        self._server.frontend = self
        self.address = self._server.server_address
        self._thread = None
        self._reporter = None
        # continuous SLO evaluation (p95 / queue-depth / shed-burn /
        # cache-collapse / autoscale-flap rules — telemetry/alerts.py)
        from veles_tpu.telemetry import alerts
        alerts.get_engine().start()

    @property
    def port(self):
        return self.address[1]

    # single-model accessors every PR 3 caller/test uses: the default
    # entry IS the frontend when only one model is hosted

    @property
    def default_entry(self):
        return self.entries[self.default_route]

    @property
    def model(self):
        return self.default_entry.model

    @property
    def metrics(self):
        return self.default_entry.metrics

    @property
    def pool(self):
        return self.default_entry.pool

    @property
    def engine(self):
        return self.default_entry.engine

    @property
    def cache(self):
        return self.default_entry.cache

    @property
    def autoscaler(self):
        return self.default_entry.autoscaler

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        for entry in self.entries.values():
            if entry.autoscaler is not None:
                entry.autoscaler.start()
        # retained metric history behind GET /history.json (QPS /
        # latency series for canary comparison and sparklines)
        from veles_tpu.telemetry.timeseries import get_history
        get_history().start()
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serving-http")
        self._thread.start()
        for entry in self.entries.values():
            self.info(
                "serving %s v%d on %s:%d%s (%s replica(s), max batch "
                "%d%s%s)", entry.model.name, entry.model.version,
                self.address[0] or "0.0.0.0", self.port,
                self._route_path(entry.name), entry.pool.size(),
                entry.pool.max_batch_size,
                ", cache %dMB" % (entry.cache.max_bytes >> 20)
                if entry.cache else "",
                ", autoscale [%d,%d]" % (
                    entry.autoscaler.min_replicas,
                    entry.autoscaler.max_replicas)
                if entry.autoscaler else "")
        return self

    def _route_path(self, route):
        return self.path if route == self.default_route \
            else "%s/%s" % (self.path, route)

    def stop(self):
        if self._reporter is not None:
            self._reporter.stop()
            self._reporter = None
        if self._thread is not None:
            # shutdown() blocks on serve_forever's exit handshake —
            # calling it on a built-but-never-started frontend would
            # hang forever
            self._server.shutdown()
        self._server.server_close()
        for entry in self.entries.values():
            entry.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def swap_model(self, source, name=None, version=None):
        """Load + register a new model version and promote its entry
        to it (drain-old / promote-new, one replica at a time), then
        atomically invalidate that entry's result cache — no request
        is ever answered with a stale cached result after this
        returns."""
        entry = self._entry_for(name)
        if isinstance(source, str):
            model = self.store.load(source, name=entry.name,
                                    version=version)
        else:
            model = self.store.add(source, version=version,
                                   name=entry.name)
        if tuple(model.sample_shape) != tuple(entry.model.sample_shape):
            raise ValueError(
                "refusing hot-swap: new sample shape %s != serving %s"
                % (model.sample_shape, entry.model.sample_shape))
        entry.pool.swap(model)
        if entry.cache is not None:
            # AFTER the promotion: entries keyed by the old version
            # can no longer be looked up (the version is in the key),
            # and the epoch bump fences any in-flight insert computed
            # against the drained model
            entry.cache.invalidate()
        entry.metrics.set_model(model.name, model.version)
        return model

    def _entry_for(self, name):
        if name is None:
            return self.default_entry
        entry = self.entries.get(name)
        if entry is None:
            raise KeyError("no model route %r (have %s)"
                           % (name, sorted(self.entries)))
        return entry

    def report_to(self, web_status_address, interval=2.0, name=None):
        """Push the metrics block to a web_status dashboard."""
        self._reporter = _StatusReporter(
            self, web_status_address, interval=interval,
            name=name or self.model.name)
        self._reporter.start()
        return self._reporter

    # -- HTTP plumbing -----------------------------------------------------

    @staticmethod
    def _respond(handler, code, payload, headers=None):
        respond_json(handler, code, payload, headers=headers)

    def _fail(self, handler, endpoint, message, code=400, rid=None,
              headers=None, t0=None, entry=None):
        if code == 503:
            # expected shedding under overload — hundreds per second;
            # the rejected_total metric is the operator's signal
            self.debug(message)
        else:
            self.warning(message)
        payload = {"error": message}
        if rid is not None:
            payload["id"] = rid
        self._respond(handler, code, payload, headers=headers)
        (entry or self.default_entry).metrics.record_request(
            endpoint, code,
            (time.time() - t0) * 1000.0 if t0 else None)

    def handle_get(self, handler):
        if handler.path.startswith("/profile.json"):
            from veles_tpu.telemetry import profiler
            self._respond(handler, 200, profiler.profile_report())
        elif handler.path.startswith("/alerts.json"):
            from veles_tpu.telemetry import alerts
            self._respond(handler, 200, alerts.get_engine().report())
        elif handler.path.startswith("/history.json"):
            from urllib.parse import parse_qs, urlsplit
            from veles_tpu.telemetry.timeseries import get_history
            query = parse_qs(urlsplit(handler.path).query)
            try:
                self._respond(handler, 200, get_history().query(
                    series=(query.get("series") or [None])[0],
                    since=(query.get("since") or [None])[0]))
            except (TypeError, ValueError):
                self._respond(handler, 400,
                              {"error": "bad since cursor"})
        elif handler.path.startswith("/metrics.json"):
            out = self.default_entry.snapshot()
            if len(self.entries) > 1:
                out["models"] = {name: entry.snapshot()
                                 for name, entry in self.entries.items()}
            self._respond(handler, 200, out)
        elif handler.path.startswith("/metrics"):
            body = get_registry().render_prometheus().encode("utf-8")
            handler.send_response(200)
            handler.send_header("Content-Type",
                                "text/plain; version=0.0.4")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        elif handler.path.startswith("/healthz"):
            self._respond(handler, 200, {
                "status": "ok", "model": self.model.name,
                "version": self.model.version,
                "sample_shape": list(self.model.sample_shape),
                "models": {
                    name: {"name": entry.model.name,
                           "version": entry.model.version,
                           "replicas": entry.pool.size(),
                           "path": self._route_path(name)}
                    for name, entry in self.entries.items()}})
        else:
            self._respond(handler, 404, {"error": "not found"})

    def _route(self, path):
        """``(entry, endpoint, batched)`` for a POST path, or None."""
        if not path.startswith(self.path):
            return None
        rest = path[len(self.path):]
        if rest in ("", "/"):
            return self.default_entry, self.path, False
        if rest == "/batch":
            return self.default_entry, self.path + "/batch", True
        if not rest.startswith("/"):
            return None         # /apialpha must not route to "alpha"
        parts = rest.lstrip("/").split("/")
        entry = self.entries.get(parts[0])
        if entry is None:
            return None
        if len(parts) == 1:
            return entry, self._route_path(parts[0]), False
        if len(parts) == 2 and parts[1] == "batch":
            return entry, self._route_path(parts[0]) + "/batch", True
        return None

    def handle_post(self, handler):
        t0 = time.time()
        # same body-drain discipline as restful_api: unread bytes on a
        # keep-alive connection corrupt the next request
        if handler.headers.get("Transfer-Encoding"):
            handler.close_connection = True
            self._fail(handler, handler.path, "Content-Length required "
                       "(Transfer-Encoding is not supported)", code=411,
                       t0=t0)
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            raw = handler.rfile.read(length)
        except (TypeError, ValueError):
            handler.close_connection = True
            self._fail(handler, handler.path, "Invalid Content-Length",
                       t0=t0)
            return
        routed = self._route(handler.path)
        if routed is None:
            self._fail(handler, handler.path,
                       "API path %s is not supported" % handler.path,
                       code=404, t0=t0)
            return
        entry, endpoint, batched = routed
        ctype = (handler.headers.get("Content-Type") or "").split(";")[0]
        if ctype.strip() != "application/json":
            self._fail(handler, endpoint, "Unsupported Content-Type "
                       "(must be \"application/json\")", t0=t0,
                       entry=entry)
            return
        try:
            request = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self._fail(handler, endpoint, "Failed to parse JSON", t0=t0,
                       entry=entry)
            return
        rid = request.get("id") if isinstance(request, dict) else None
        tenant = handler.headers.get(self.tenant_header) or \
            (request.get("tenant") if isinstance(request, dict)
             else None)
        qos = handler.headers.get(self.qos_header) or \
            (request.get("qos") if isinstance(request, dict) else None)
        if qos is not None and qos not in QOS_MULTIPLIER:
            self._fail(handler, endpoint,
                       "Unknown QoS class %r (one of %s)"
                       % (qos, sorted(QOS_MULTIPLIER)), rid=rid, t0=t0,
                       entry=entry)
            return
        deadline_ms = handler.headers.get(self.deadline_header) or \
            (request.get("deadline_ms") if isinstance(request, dict)
             else None)
        deadline = None
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
                if deadline_ms <= 0:
                    raise ValueError(deadline_ms)
            except (TypeError, ValueError):
                self._fail(handler, endpoint,
                           "Invalid %s value %r (positive "
                           "milliseconds)" % (self.deadline_header,
                                              deadline_ms),
                           rid=rid, t0=t0, entry=entry)
                return
            # relative budget -> absolute wall deadline at ARRIVAL:
            # queue time spends the same budget compute would
            deadline = t0 + deadline_ms / 1000.0
        # request-id → trace-id bridge: the span for this request (and
        # everything under it) carries the client's X-Request-Id / "id"
        trace_id = tracing.trace_id_from_request(handler.headers, rid)
        try:
            with tracing.request_span("http:%s" % endpoint,
                                      trace_id=trace_id):
                if batched:
                    self._serve_batch(handler, entry, endpoint, request,
                                      rid, t0, tenant, qos, deadline)
                else:
                    self._serve_one(handler, entry, endpoint, request,
                                    rid, t0, tenant, qos, deadline)
        except EngineOverloaded as e:
            self._fail(handler, endpoint, str(e), code=503, rid=rid,
                       headers={"Retry-After": str(e.retry_after)},
                       t0=t0, entry=entry)

    def _serve_one(self, handler, entry, endpoint, request, rid, t0,
                   tenant, qos, deadline=None):
        data, error = parse_payload(request)
        if error is not None:
            self._fail(handler, endpoint, error, rid=rid, t0=t0,
                       entry=entry)
            return
        try:
            future = entry.engine.submit(data, tenant=tenant, qos=qos,
                                         deadline=deadline)
        except ValueError as e:
            self._fail(handler, endpoint, "Invalid input value: %s" % e,
                       rid=rid, t0=t0, entry=entry)
            return
        self._await_and_reply(handler, entry, endpoint, [future], rid,
                              t0, single=True)

    def _serve_batch(self, handler, entry, endpoint, request, rid, t0,
                     tenant, qos, deadline=None):
        if not isinstance(request, dict) or "codec" not in request or \
                ("inputs" not in request and "input" not in request):
            self._fail(handler, endpoint, "Invalid input format: there "
                       "must be \"inputs\" and \"codec\" attributes",
                       rid=rid, t0=t0, entry=entry)
            return
        if "inputs" in request:
            rows_spec = request["inputs"]
            if not isinstance(rows_spec, list) or not rows_spec:
                self._fail(handler, endpoint,
                           "\"inputs\" must be a non-empty array",
                           rid=rid, t0=t0, entry=entry)
                return
            if request["codec"] == "list":
                try:
                    rows = [numpy.array(r, numpy.float32)
                            for r in rows_spec]
                except (TypeError, ValueError):
                    self._fail(handler, endpoint,
                               "Invalid input array format", rid=rid,
                               t0=t0, entry=entry)
                    return
            else:
                rows = []
                for r in rows_spec:
                    data, error = parse_payload(
                        dict(request, input=r, inputs=None))
                    if error is not None:
                        self._fail(handler, endpoint, error, rid=rid,
                                   t0=t0, entry=entry)
                        return
                    rows.append(data)
        else:
            # base64 with a leading batch dim in "shape"
            data, error = parse_payload(request)
            if error is not None:
                self._fail(handler, endpoint, error, rid=rid, t0=t0,
                           entry=entry)
                return
            rows = list(data)
        futures = []
        try:
            for row in rows:
                futures.append(entry.engine.submit(
                    row, tenant=tenant, qos=qos, deadline=deadline))
        except ValueError as e:
            # rows already admitted still complete; their results are
            # simply dropped with the failed request
            self._fail(handler, endpoint, "Invalid input value: %s" % e,
                       rid=rid, t0=t0, entry=entry)
            return
        self._await_and_reply(handler, entry, endpoint, futures, rid,
                              t0, single=False)

    def _await_and_reply(self, handler, entry, endpoint, futures, rid,
                         t0, single):
        try:
            deadline = t0 + self.response_timeout
            results = [f.result(timeout=max(deadline - time.time(),
                                            0.001))
                       for f in futures]
        except concurrent.futures.TimeoutError:
            self._fail(handler, endpoint,
                       "The model did not respond in time", code=500,
                       rid=rid, t0=t0, entry=entry)
            return
        except DeadlineExceeded as e:
            self._fail(handler, endpoint, str(e), code=504, rid=rid,
                       t0=t0, entry=entry)
            return
        except EngineOverloaded:
            raise
        except Exception as e:
            self._fail(handler, endpoint, "inference failed: %s"
                       % (str(e) or type(e).__name__), code=500,
                       rid=rid, t0=t0, entry=entry)
            return
        if single:
            payload = {"result": results[0]}
        else:
            payload = {"results": results}
        if rid is not None:
            payload["id"] = rid
        self._respond(handler, 200, payload)
        entry.metrics.record_request(endpoint, 200,
                                     (time.time() - t0) * 1000.0)


class _StatusReporter(Logger):
    """POSTs the serving block to web_status ``/update`` periodically
    (the serving analog of the Launcher's status notifier)."""

    def __init__(self, frontend, address, interval=2.0, name="serving"):
        super(_StatusReporter, self).__init__()
        if isinstance(address, str):
            host, _, port = address.partition(":")
            address = (host or "127.0.0.1", int(port or 8090))
        self.url = "http://%s:%d/update" % tuple(address)
        self.frontend = frontend
        self.interval = interval
        self.name = name
        self.id = str(uuid.uuid4())
        self._started = time.time()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-status")
        self._thread.start()
        return self

    def _payload(self):
        return {
            "id": self.id,
            "name": self.name,
            "mode": "serve",
            "master": self.frontend.address[0] or "localhost",
            "time": time.time() - self._started,
            "units": sum(e.pool.size()
                         for e in self.frontend.entries.values()),
            "stopped": False,
            "serving": self.frontend.metrics.dashboard_block(),
        }

    def _post_once(self):
        import urllib.request
        try:
            req = urllib.request.Request(
                self.url,
                data=json.dumps(self._payload(),
                                cls=_NumpyJSONEncoder).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=2.0)
        except Exception as e:
            self.debug("web_status push failed: %s", e)

    def _loop(self):
        while not self._stop.wait(self.interval):
            self._post_once()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def _parse_tenants(specs):
    """``name:weight[:qos]`` flags -> the AdmissionController map."""
    tenants = {}
    for spec in specs or ():
        parts = spec.split(":")
        if not parts[0]:
            raise ValueError("tenant spec %r needs a name" % spec)
        entry = {"weight": float(parts[1]) if len(parts) > 1 else 1.0}
        if len(parts) > 2:
            if parts[2] not in QOS_MULTIPLIER:
                raise ValueError(
                    "tenant spec %r: unknown QoS %r (one of %s)"
                    % (spec, parts[2], sorted(QOS_MULTIPLIER)))
            entry["qos"] = parts[2]
        tenants[parts[0]] = entry
    return tenants or None


def _parse_models(specs):
    """``[name=]path`` flags -> the ServingFrontend model dict."""
    models = {}
    for spec in specs:
        name, sep, path = spec.partition("=")
        if not sep:
            name, path = None, spec
        if name in models:
            # dict insertion would silently drop one artifact —
            # either an unnamed repeat or a name= typo
            if name is None:
                raise ValueError("with multiple --model flags, every "
                                 "one needs a name= prefix")
            raise ValueError("duplicate model route %r (--model %s)"
                             % (name, spec))
        models[name] = path
    if len(models) == 1:
        name, path = next(iter(models.items()))
        return path if name is None else {name: path}
    if None in models:
        raise ValueError("with multiple --model flags, every one "
                         "needs a name= prefix")
    return models


def main(argv=None):
    """``python -m veles_tpu serve ...`` / ``veles-tpu-serve``."""
    parser = argparse.ArgumentParser(
        prog="veles_tpu serve",
        description="dynamic-batching inference server")
    parser.add_argument("--model", required=True, action="append",
                        help="snapshot file/dir/URI or export package; "
                             "repeat with name=path to serve several "
                             "models from one process")
    parser.add_argument("--name", default=None,
                        help="model name in the store (default: from "
                             "the artifact; single --model only)")
    parser.add_argument("--host", default="")
    parser.add_argument("--port", type=int, default=8180)
    parser.add_argument("--path", default=root.common.api.path)
    parser.add_argument("--replicas", type=int, default=1,
                        help="initial replica-pool size per model")
    parser.add_argument("--min-replicas", type=int, default=None,
                        help="autoscaler floor (default: --replicas)")
    parser.add_argument("--max-replicas", type=int, default=None,
                        help="autoscaler ceiling; setting it ENABLES "
                             "telemetry-driven autoscaling")
    parser.add_argument("--max-batch-size", type=int, default=64)
    parser.add_argument("--batch-timeout-ms", type=float, default=5.0)
    parser.add_argument("--max-queue", type=int, default=256,
                        help="admission bound; beyond it requests get "
                             "503 + Retry-After")
    parser.add_argument("--cache-mb", type=float, default=64.0,
                        help="result-cache byte budget per model "
                             "(0 disables the cache)")
    parser.add_argument("--cache-ttl-s", type=float, default=300.0)
    parser.add_argument("--tenant", action="append", metavar="SPEC",
                        help="name:weight[:qos] — pre-register a "
                             "tenant admission bucket (qos one of "
                             "interactive/batch/best_effort); repeat "
                             "per tenant")
    parser.add_argument("--keep-last", type=int, default=None,
                        help="retain at most K versions per model in "
                             "the store (pinned exempt)")
    parser.add_argument("--response-timeout", type=float, default=30.0)
    parser.add_argument("--web-status", default=None, metavar="HOST:PORT",
                        help="push serving metrics to this dashboard")
    parser.add_argument("--trace-out", default=None, metavar="FILE",
                        help="enable span tracing and dump the trace "
                             "buffer (Chrome trace-event JSON, open in "
                             "Perfetto) to FILE at exit")
    parser.add_argument("-v", "--verbosity", default="info",
                        choices=["debug", "info", "warning", "error"])
    args = parser.parse_args(argv)
    import logging

    from veles_tpu.logger import setup_logging
    setup_logging(getattr(logging, args.verbosity.upper()))
    if args.trace_out:
        tracing.enable()
        import os
        try:  # don't merge into a stale file from a previous run
            os.remove(args.trace_out)
        except OSError:
            pass
    from veles_tpu.telemetry import profiler
    profiler.start_memory_sampler()
    models = _parse_models(args.model)
    if args.name and isinstance(models, str):
        models = {args.name: models}
    frontend = ServingFrontend(
        models, host=args.host, port=args.port, path=args.path,
        replicas=args.replicas, max_batch_size=args.max_batch_size,
        batch_timeout_ms=args.batch_timeout_ms, max_queue=args.max_queue,
        response_timeout=args.response_timeout,
        cache_mb=args.cache_mb, cache_ttl_s=args.cache_ttl_s,
        tenants=_parse_tenants(args.tenant),
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        keep_last=args.keep_last)
    if args.web_status:
        frontend.report_to(args.web_status)
    frontend.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        frontend.stop()
        if args.trace_out:
            n = tracing.get_buffer().dump(args.trace_out,
                                          process_name="serve")
            frontend.info("wrote %d trace events to %s", n,
                          args.trace_out)
            if profiler.dump_memory_profile(args.trace_out + ".memprof"):
                frontend.info("wrote device memory profile to %s.memprof",
                              args.trace_out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
