"""Telemetry-driven elastic scaling of the serving replica pool.

ROADMAP item 3's last open mechanism: PR 3 batched onto a *fixed*
warm pool, so a diurnal 10x burst either over-provisioned the quiet
hours or shed the peak. The :class:`Autoscaler` closes the loop using
the signals PR 4/9 already publish — admission-queue depth, replica
occupancy, shed counters — and grows/shrinks the pool inside
``[min_replicas, max_replicas]``:

* **scale-up is fast**: one sustained breach window (``up_for_s``,
  default 1 s) of queue depth per replica above ``up_queue_per_
  replica`` — or ANY shedding — adds a replica. The new replica warms
  every bucket through the staging-ring H2D path *before* joining
  dispatch (``veles_phase_ms{phase="replica_warmup"}``), so burst
  traffic never lands on a cold JIT cache.
* **scale-down is slow**: the pool must be idle (empty queue, no
  replica load, no recent shed) for ``down_idle_for_s`` (default
  30 s) before one replica is drained — and the drain removes it from
  dispatch first, then waits for everything it accepted, so **zero
  in-flight requests die** (``ReplicaPool.remove_replica``).
* **flap never happens**: separate up/down thresholds (hysteresis),
  per-direction cooldowns, and any scale action resets the opposite
  direction's evidence window. The ``autoscale_flap`` alert rule
  (``telemetry/alerts.py``) fires if transitions still churn.

Reaction time — first breach tick to the new replica serving — lands
in the ``veles_autoscale_reaction_s`` histogram; ``bench_serving.py
--scenario burst`` reports it and ``perf_gate.py`` tracks it
report-only.

Drive it with :meth:`start` (a daemon tick thread) or call
:meth:`tick` yourself with an explicit ``now`` for deterministic
tests.
"""

import threading
import time

from veles_tpu.logger import Logger
from veles_tpu.telemetry.registry import get_registry


class Autoscaler(Logger):
    """Grow/shrink one :class:`ReplicaPool` from live engine signals."""

    def __init__(self, pool, batcher, min_replicas=1, max_replicas=4,
                 up_queue_per_replica=8.0, up_for_s=1.0,
                 up_cooldown_s=3.0, down_idle_for_s=30.0,
                 down_cooldown_s=30.0, interval_s=0.5,
                 registry=None, model="default"):
        super(Autoscaler, self).__init__()
        if max_replicas < min_replicas:
            raise ValueError("max_replicas %d < min_replicas %d"
                             % (max_replicas, min_replicas))
        self.pool = pool
        self.batcher = batcher
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = int(max_replicas)
        self.up_queue_per_replica = float(up_queue_per_replica)
        self.up_for_s = float(up_for_s)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_idle_for_s = float(down_idle_for_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.interval_s = float(interval_s)
        self.model = str(model)
        self._breach_since = None
        self._idle_since = None
        self._last_up = None
        self._last_down = None
        self._last_shed_total = None
        self._shed_seen_at = None
        self._stop = threading.Event()
        self._thread = None
        self.transitions = []           # (t, direction, size) history
        registry = registry or get_registry()
        label = {"model": self.model}
        self._g_replicas = registry.gauge(
            "veles_autoscale_replicas", "Current replica-pool size",
            labels=("model",)).labels(**label)
        self._g_target = registry.gauge(
            "veles_autoscale_bounds",
            "Configured pool bounds", labels=("model", "bound"))
        self._g_target.labels(model=self.model,
                              bound="min").set(self.min_replicas)
        self._g_target.labels(model=self.model,
                              bound="max").set(self.max_replicas)
        self._m_transitions = registry.counter(
            "veles_autoscale_transitions_total",
            "Scale actions taken", labels=("model", "direction"))
        self._h_reaction = registry.histogram(
            "veles_autoscale_reaction_s",
            "Breach start -> new replica serving",
            labels=("model",))
        self._g_replicas.set(self.pool.size())

    # -- signal sampling ---------------------------------------------------

    def _shed_delta(self):
        """Samples shed since the last tick (engine admission)."""
        stats = self.batcher.admission.stats()
        total = sum(t["shed"] for t in stats["tenants"].values())
        delta = 0 if self._last_shed_total is None else \
            max(0, total - self._last_shed_total)
        self._last_shed_total = total
        return delta

    def signals(self):
        """One consistent sample of the scaling inputs."""
        depth = self.batcher.queue_depth()
        stats = self.pool.stats()
        return {
            "replicas": len(stats),
            "queue_depth": depth,
            "busy_replicas": sum(1 for s in stats if s["load"] > 0),
            "shed_delta": self._shed_delta(),
        }

    # -- the control decision ----------------------------------------------

    def tick(self, now=None):
        """Evaluate once; perform at most one scale action. Returns
        ``+1``/``-1``/``0`` for up/down/hold."""
        now = time.monotonic() if now is None else now
        sig = self.signals()
        n = sig["replicas"]
        self._g_replicas.set(n)
        if sig["shed_delta"] > 0:
            self._shed_seen_at = now
        if n < self.min_replicas:
            return self._scale_up(now, "below min_replicas")

        # -- up evidence: deep queue per replica, or active shedding
        pressured = (sig["queue_depth"] >
                     self.up_queue_per_replica * n) or \
            sig["shed_delta"] > 0
        if pressured:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
            held = now - self._breach_since >= self.up_for_s
            cooled = self._last_up is None or \
                now - self._last_up >= self.up_cooldown_s
            if held and cooled and n < self.max_replicas:
                return self._scale_up(
                    now, "depth %d over %d replicas, shed +%d"
                    % (sig["queue_depth"], n, sig["shed_delta"]))
            return 0
        self._breach_since = None

        # -- down evidence: truly idle, long enough, nothing shed
        # recently (a shedding service is NOT idle no matter the queue)
        idle = (sig["queue_depth"] == 0 and
                sig["busy_replicas"] == 0 and
                (self._shed_seen_at is None or
                 now - self._shed_seen_at >= self.down_idle_for_s))
        if idle and n > self.min_replicas:
            if self._idle_since is None:
                self._idle_since = now
            held = now - self._idle_since >= self.down_idle_for_s
            cooled = ((self._last_down is None or
                       now - self._last_down >= self.down_cooldown_s)
                      and (self._last_up is None or
                           now - self._last_up >= self.down_cooldown_s))
            if held and cooled:
                return self._scale_down(now)
        else:
            self._idle_since = None
        return 0

    def _scale_up(self, now, why):
        breach = self._breach_since
        t0 = time.monotonic()
        self.pool.add_replica()         # warms before joining dispatch
        warm_s = time.monotonic() - t0
        # reaction = evidence window (in the tick clock, injectable by
        # tests) + the real warm-up the new replica just paid
        done = now + warm_s
        self._last_up = done
        self._breach_since = None
        self._idle_since = None
        size = self.pool.size()
        self._g_replicas.set(size)
        self._m_transitions.labels(model=self.model,
                                   direction="up").inc()
        if breach is not None:
            self._h_reaction.labels(model=self.model).observe(
                max(0.0, done - breach))
        self.transitions.append((done, "up", size))
        self.info("scale up -> %d replica(s): %s", size, why)
        return 1

    def _scale_down(self, now):
        victim = self.pool.remove_replica()
        if victim is None:
            return 0                    # drain stalled; retry later
        self._last_down = now
        self._idle_since = None
        size = self.pool.size()
        self._g_replicas.set(size)
        self._m_transitions.labels(model=self.model,
                                   direction="down").inc()
        self.transitions.append((now, "down", size))
        self.info("scale down -> %d replica(s): idle %.0fs", size,
                  self.down_idle_for_s)
        return -1

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="autoscaler-%s" % self.model)
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                self.exception("autoscaler tick failed")

    def stop(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)
