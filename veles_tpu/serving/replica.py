"""Model replicas: warm JIT caches, least-loaded dispatch, hot-swap.

A :class:`Replica` owns one jitted forward of the current model plus a
worker thread draining its private work queue — the thread-backed
analog of a per-chip serving process (process isolation is a deployment
choice layered on top; inside one host, threads share the XLA compile
cache and the weights' device buffers, which is exactly what we want
for N replicas of the same model on one chip).

Batch shapes are bucketed to powers of two up to ``max_batch_size``
(``bucket_for``): the padded batch always hits a warm compilation, so
tail latency never pays a compile. ``warm()`` pre-compiles every bucket
at startup and after every swap — a swapped-in model serves its first
request from a warm cache.

:class:`ReplicaPool` fans work out across replicas by least queued
work, and :meth:`ReplicaPool.swap` hot-swaps the model: the swap rides
the same work queue as inference, so each replica drains everything
already accepted, swaps, re-warms, and only then takes new work — no
request ever observes a half-swapped replica.
"""

import queue
import threading

import numpy

from veles_tpu.logger import Logger


def bucket_for(n, max_batch_size):
    """Smallest power-of-two >= n, clamped to max_batch_size."""
    if n >= max_batch_size:
        return max_batch_size
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch_size)


def buckets_upto(max_batch_size):
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b <<= 1
    out.append(max_batch_size)
    return out


class _Swap(object):
    """Queue sentinel: drain, then swap to ``model``."""

    def __init__(self, model):
        self.model = model
        self.done = threading.Event()


class Replica(Logger):
    """One warm copy of the model with a private dispatch queue."""

    #: load charged while a swap is queued/running: a swapping replica
    #: must look maximally busy to pick()/any_idle(), or new batches
    #: would be routed behind its drain + full re-warm while the other
    #: replicas sit idle
    SWAP_LOAD = 1 << 20

    def __init__(self, model, index=0, max_batch_size=64, warm=True):
        super(Replica, self).__init__()
        self.index = index
        self.max_batch_size = int(max_batch_size)
        self._queue = queue.Queue()
        self._pending = 0           # queued + running rows, approx load
        self._pending_lock = threading.Lock()
        self.batches_done = 0
        self.rows_done = 0
        self._stop = threading.Event()
        self._bind(model, warm=warm)
        self._thread = threading.Thread(
            target=self._work_loop, daemon=True,
            name="replica-%d" % index)
        self._thread.start()

    # -- model binding -----------------------------------------------------

    def _bind(self, model, warm=True):
        import jax
        self.model = model
        self._forward = jax.jit(model.forward_fn())
        self.warmed_buckets = []
        if warm:
            self.warm()

    def warm(self):
        """Compile every batch bucket ahead of traffic."""
        from veles_tpu.telemetry import profiler
        book = profiler.get_cost_book()
        with profiler.phase("warmup"):
            for bucket in buckets_upto(self.max_batch_size):
                x = numpy.zeros((bucket,) + self.model.sample_shape,
                                numpy.float32)
                numpy.asarray(self._forward(x))  # force compile + execute
                # cost harvest AFTER the warming call: its compile
                # populated the persistent XLA cache, so the harvest's
                # lower().compile() deserializes instead of paying a
                # second full compile — and the roofline table then
                # covers every serving bucket alongside the train
                # segments
                book.harvest("serve_forward:b%d" % bucket,
                             self._forward, (x,))
                self.warmed_buckets.append(bucket)
        self.debug("replica %d warm: %s v%d, buckets %s", self.index,
                   self.model.name, self.model.version,
                   self.warmed_buckets)

    # -- inference ---------------------------------------------------------

    def infer(self, batch):
        """Synchronous padded forward (runs on the worker thread)."""
        from veles_tpu.telemetry import profiler
        rows = batch.shape[0]
        bucket = bucket_for(rows, self.max_batch_size)
        if rows < bucket:
            pad = numpy.zeros((bucket - rows,) + batch.shape[1:],
                              batch.dtype)
            batch = numpy.concatenate([batch, pad], axis=0)
        with profiler.timed_op("serve_forward:b%d" % bucket):
            out = numpy.asarray(self._forward(batch))
        return out[:rows], bucket

    @property
    def load(self):
        with self._pending_lock:
            return self._pending

    def submit(self, batch, on_done):
        """Queue a batch; ``on_done(result_rows, bucket, error)`` fires
        on the worker thread."""
        with self._pending_lock:
            self._pending += int(batch.shape[0])
        self._queue.put((batch, on_done))

    def swap(self, model):
        """Queue a drain-then-swap; returns an event set when done."""
        op = _Swap(model)
        with self._pending_lock:
            self._pending += self.SWAP_LOAD
        self._queue.put(op)
        return op.done

    def _work_loop(self):
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                break
            if isinstance(item, _Swap):
                try:
                    self._bind(item.model)
                    self.info("replica %d promoted to %s v%d",
                              self.index, item.model.name,
                              item.model.version)
                finally:
                    with self._pending_lock:
                        self._pending -= self.SWAP_LOAD
                    item.done.set()
                continue
            batch, on_done = item
            try:
                result, bucket = self.infer(batch)
                error = None
            except Exception as e:  # scatter the failure, don't die
                result, bucket = None, 0
                error = e
                self.exception("replica %d batch failed", self.index)
            finally:
                with self._pending_lock:
                    self._pending -= int(batch.shape[0])
            self.batches_done += 1
            self.rows_done += int(batch.shape[0])
            on_done(result, bucket, error)

    def stop(self):
        self._stop.set()
        self._queue.put(None)
        self._thread.join(timeout=10)
        # fail whatever was still queued: a stranded batch would leave
        # its clients blocked until their response timeout
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, _Swap):
                with self._pending_lock:
                    self._pending -= self.SWAP_LOAD
                item.done.set()
            elif item is not None:
                batch, on_done = item
                on_done(None, 0, RuntimeError("replica stopped"))

    def stats(self):
        return {"index": self.index, "load": self.load,
                "batches": self.batches_done, "rows": self.rows_done,
                "model": self.model.name, "version": self.model.version}


class ReplicaPool(Logger):
    """N replicas of one model; least-loaded dispatch; atomic swap."""

    def __init__(self, model, n_replicas=1, max_batch_size=64,
                 warm=True):
        super(ReplicaPool, self).__init__()
        self.max_batch_size = int(max_batch_size)
        self._dispatch_lock = threading.Lock()
        self._rr = 0
        self.replicas = [
            Replica(model, index=i, max_batch_size=max_batch_size,
                    warm=warm)
            for i in range(max(1, int(n_replicas)))]

    @property
    def model(self):
        return self.replicas[0].model

    def pick(self):
        """Least-loaded replica; round-robin breaks ties so idle
        replicas alternate instead of replica 0 taking everything."""
        with self._dispatch_lock:
            self._rr += 1
            order = self.replicas[self._rr % len(self.replicas):] + \
                self.replicas[:self._rr % len(self.replicas)]
            return min(order, key=lambda r: r.load)

    def any_idle(self):
        """True when some replica has no queued/running work — the
        batcher's dispatch gate: while every replica is busy, a forming
        batch keeps growing instead of queueing up small fragments."""
        return any(r.load == 0 for r in self.replicas)

    def submit(self, batch, on_done):
        self.pick().submit(batch, on_done)

    def swap(self, model, timeout=120.0):
        """Hot-swap every replica, one at a time: each drains its
        accepted work, promotes, re-warms, and rejoins dispatch while
        the others keep serving — capacity dips by 1/N, never to 0."""
        for replica in self.replicas:
            done = replica.swap(model)
            if not done.wait(timeout):
                raise TimeoutError(
                    "replica %d did not finish the swap in %.0fs" %
                    (replica.index, timeout))
        self.info("pool promoted to %s v%d", model.name, model.version)

    def stats(self):
        return [r.stats() for r in self.replicas]

    def stop(self):
        for replica in self.replicas:
            replica.stop()
